"""DataLoader (reference `fluid/reader.py:149` +
`fluid/dataloader/dataloader_iter.py:265/469`).

Threaded prefetch pipeline: `num_workers` threads pull index batches from
the sampler, fetch+collate to numpy (GIL released in numpy), and push to a
bounded queue; a process pool handles decode-heavy datasets when
`use_process_workers=True`. Batches are handed out as framework Tensors
(host-resident; H2D overlaps with compute under jit).
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Callable, Optional

import numpy as np

from ..framework.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info"]

_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch):
    """Stack a list of samples (reference
    `fluid/dataloader/collate.py:default_collate_fn`)."""
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch])
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items))
                     for items in zip(*batch))
    return batch


def _to_tensors(collated):
    if isinstance(collated, np.ndarray):
        if collated.dtype == np.float64:
            collated = collated.astype(np.float32)
        return Tensor(collated)
    if isinstance(collated, dict):
        return {k: _to_tensors(v) for k, v in collated.items()}
    if isinstance(collated, (list, tuple)):
        return type(collated)(_to_tensors(v) for v in collated)
    return collated


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._iterable_mode:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
            self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_threaded()

    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return _to_tensors(self.collate_fn(samples))

    def _iter_single(self):
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield _to_tensors(self.collate_fn(batch))
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield _to_tensors(self.collate_fn(batch))

    def _iter_threaded(self):
        """Ordered multi-thread prefetch (reference multiprocess iter
        `dataloader_iter.py:469`, re-designed without shared-mem plumbing)."""
        nw = self.num_workers
        depth = nw * self.prefetch_factor
        task_q: "queue.Queue" = queue.Queue(depth)
        done = object()
        results = {}
        results_lock = threading.Condition()
        stop = threading.Event()

        def worker(wid):
            _worker_info.info = WorkerInfo(wid, nw, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(wid)
            while not stop.is_set():
                item = task_q.get()
                if item is done:
                    task_q.put(done)
                    return
                seq, indices = item
                try:
                    out = self._fetch(indices)
                except Exception as e:  # propagate to consumer
                    out = e
                with results_lock:
                    results[seq] = out
                    results_lock.notify_all()

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(nw)]
        for t in threads:
            t.start()

        def feeder():
            for seq, indices in enumerate(self.batch_sampler):
                if stop.is_set():
                    return
                task_q.put((seq, indices))
            task_q.put(done)

        feed_thread = threading.Thread(target=feeder, daemon=True)
        feed_thread.start()

        total = len(self.batch_sampler)
        try:
            for seq in range(total):
                with results_lock:
                    while seq not in results:
                        results_lock.wait(timeout=self.timeout or None)
                    out = results.pop(seq)
                if isinstance(out, Exception):
                    raise out
                yield out
        finally:
            stop.set()
            try:
                task_q.put_nowait(done)
            except queue.Full:
                pass
