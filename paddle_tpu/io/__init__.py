"""paddle.io: Dataset / DataLoader / samplers (reference
`python/paddle/io/`, `fluid/reader.py:149`, `fluid/dataloader/`).

TPU-native DataLoader: worker threads + a bounded prefetch queue feeding
host numpy batches (device transfer happens at first op / jit boundary —
XLA pipelines H2D asynchronously). The reference's multiprocess+shared-mem
design exists to dodge the GIL for Python-heavy decode; batch collation
here is numpy-bound (releases the GIL), so threads deliver the same overlap
without the mmap allocator machinery (#9 mmap_allocator in SURVEY §2).
A `num_workers>0` process pool is kept for decode-heavy datasets.
"""
from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,
                      IterableDataset, Subset, TensorDataset, random_split)
from .sampler import (BatchSampler, DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, WeightedRandomSampler)
from .dataloader import DataLoader, default_collate_fn, get_worker_info
from .device_loader import DeviceFeeder
from .packing import PackingCollator, suggest_rows

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split", "Sampler",
    "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "BatchSampler", "DistributedBatchSampler", "DataLoader", "DeviceFeeder",
    "PackingCollator", "suggest_rows", "default_collate_fn",
    "get_worker_info",
]
