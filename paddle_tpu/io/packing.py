"""Sequence packing collator: stop paying for padding FLOPs.

Variable-length training pads every sequence to the batch max, so on
real-corpus length distributions most attention/MLP FLOPs are spent on
pad tokens. This collator instead packs several sequences into one fixed
`(rows, max_tokens)` pack (greedy first-fit, Krell et al. "Efficient
Sequence Packing") and emits the tensors the segment-aware attention
path (ops/splash_ops.py via `F.scaled_dot_product_attention(
segment_ids=...)`) and the token-masked loss (hapi/model.py) need:

  pack layout:  (field_0, segment_ids, position_ids, *fields_1.., mask)
    field_i      [rows, max_tokens]  each per-token field of the sample,
                                     in sample order (field_0 = model
                                     input tokens, the rest = labels)
    segment_ids  [rows, max_tokens]  int32, 0,1,2,... per row in packing
                                     order; the padded tail of a row gets
                                     ONE trailing pad segment id (one past
                                     the last real segment), so ids stay
                                     non-decreasing — the splash kernel's
                                     block-skip contract — and pad tokens
                                     only ever attend to each other
    position_ids [rows, max_tokens]  int32, restart at 0 per segment
                                     (packed rows must NOT share absolute
                                     positions across segments)
    mask         [rows, max_tokens]  float32 token validity; Model.fit
                                     pops it as the token-level loss mask

Because every pack — including a partial final one — has the same fixed
shape, a packed epoch costs exactly ONE train-step compile and composes
with PR 4's tail machinery by simply not needing it (a short tail is just
a pack with more masked tokens).

Used as a DataLoader `collate_fn`, so packs ride the shm ring, the
sharding-aware DeviceFeeder prefetch and fit's async hot loop unchanged.
Samples are a single 1-D per-token array or a tuple/list of equal-length
1-D arrays. Sequences longer than `max_tokens` are truncated (counted);
a sequence no row can host is DROPPED (counted, warned once) — size
`rows` for your length distribution (`suggest_rows`) so drops stay rare.

`policy="pad"` is the one-sequence-per-row baseline (classic pad-to-max
with the same tensor layout) — the control arm of `bench.py --mode
packing` and of parity tests.

Counters (framework/monitor.py): STAT_packing_packs,
STAT_packing_sequences, STAT_packing_tokens (real), STAT_packing_slots
(rows*max_tokens), STAT_packing_fill_ratio_pct (cumulative per-pack
percentage — divide by STAT_packing_packs for the mean fill),
STAT_packing_dropped_seqs, STAT_packing_truncated_seqs. The collate runs
under a `packing::collate[n=...]` trace scope (PR 5 tracer).
"""
from __future__ import annotations

import warnings

import numpy as np

from ..framework.monitor import STAT_ADD
from ..profiler import RecordEvent

__all__ = ["PackingCollator", "suggest_rows"]


def _note_pack(tokens, slots):
    """Pack-level counter emission. With num_workers > 0 these land in
    the WORKER's registry copy and reach the trainer through the
    DataLoader's generic cross-process stat relay
    (`monitor.drain_deltas()` shipped with every batch) — including the
    per-sequence drop/truncation counters the old mask-leaf
    re-derivation could not reconstruct."""
    STAT_ADD("STAT_packing_packs")
    STAT_ADD("STAT_packing_tokens", tokens)
    STAT_ADD("STAT_packing_slots", slots)
    STAT_ADD("STAT_packing_fill_ratio_pct",
             int(round(100.0 * tokens / max(slots, 1))))


def suggest_rows(lengths, batch_size, max_tokens, headroom=1.1):
    """Row count for a `(rows, max_tokens)` pack that fits `batch_size`
    sequences of the given observed/expected lengths with `headroom`
    slack over the perfect-fill row count."""
    mean_len = float(np.mean(np.minimum(np.asarray(lengths), max_tokens)))
    return max(1, int(np.ceil(batch_size * mean_len * headroom
                              / max_tokens)))


def _fields_of(sample):
    if isinstance(sample, (tuple, list)):
        fields = [np.asarray(f) for f in sample]
    else:
        fields = [np.asarray(sample)]
    L = fields[0].shape[0]
    for f in fields:
        if f.ndim != 1 or f.shape[0] != L:
            raise ValueError(
                "PackingCollator samples must be 1-D per-token arrays of "
                f"equal length; got shapes "
                f"{[tuple(f.shape) for f in fields]}")
    return fields, L


class PackingCollator:
    """DataLoader collate_fn packing variable-length samples into fixed
    `(rows, max_tokens)` packs with segment ids / position ids / token
    mask. See module docstring for the batch layout and contract."""

    # Model.fit/evaluate key off this: the last batch leaf is a
    # token-level loss mask, replacing the row-mask tail machinery
    emits_token_mask = True

    def __init__(self, max_tokens, rows, pad_value=0, policy="first_fit"):
        if policy not in ("first_fit", "pad"):
            raise ValueError(f"unknown packing policy {policy!r}")
        if max_tokens <= 0 or rows <= 0:
            raise ValueError("max_tokens and rows must be positive")
        self.max_tokens = int(max_tokens)
        self.rows = int(rows)
        self.pad_value = pad_value
        self.policy = policy
        self.last_fill_ratio = 0.0
        self._warned_drop = False

    def __call__(self, batch):
        with RecordEvent(f"packing::collate[n={len(batch)}]"):
            return self._pack(batch)

    def _place(self, used, L, i):
        if self.policy == "pad":
            if i >= self.rows:
                return None  # more sequences than rows: overflow
            return i if used[i] == 0 and L <= self.max_tokens else None
        for r in range(self.rows):           # greedy first-fit
            if used[r] + L <= self.max_tokens:
                return r
        return None

    def _pack(self, batch):
        rows, T = self.rows, self.max_tokens
        samples = [_fields_of(s) for s in batch]
        if not samples:
            raise ValueError("PackingCollator: empty batch")
        nfields = len(samples[0][0])
        out = None
        seg = np.zeros((rows, T), np.int32)
        pos = np.zeros((rows, T), np.int32)
        mask = np.zeros((rows, T), np.float32)
        used = [0] * rows
        nseg = [0] * rows
        placed = dropped = truncated = tokens = 0
        for i, (fields, L) in enumerate(samples):
            if len(fields) != nfields:
                raise ValueError("inconsistent sample arity in batch")
            if L > T:
                fields = [f[:T] for f in fields]
                L = T
                truncated += 1
                STAT_ADD("STAT_packing_truncated_seqs")
            r = self._place(used, L, i)
            if r is None:
                dropped += 1
                STAT_ADD("STAT_packing_dropped_seqs")
                if not self._warned_drop:
                    self._warned_drop = True
                    warnings.warn(
                        f"PackingCollator: a {L}-token sequence fit no "
                        f"row of the ({rows}, {T}) pack and was dropped "
                        "— raise `rows` (io.packing.suggest_rows) or "
                        "max_tokens if drops matter", stacklevel=2)
                continue
            if out is None:
                out = [np.full((rows, T), self.pad_value, dtype=f.dtype)
                       for f in fields]
            o = used[r]
            for dst, f in zip(out, fields):
                dst[r, o:o + L] = f
            seg[r, o:o + L] = nseg[r]
            pos[r, o:o + L] = np.arange(L, dtype=np.int32)
            mask[r, o:o + L] = 1.0
            used[r] = o + L
            nseg[r] += 1
            placed += 1
            tokens += L
        if out is None:
            raise ValueError("PackingCollator: empty batch (or every "
                             "sequence overflowed the pack)")
        for r in range(rows):
            # ONE trailing pad segment per row keeps ids non-decreasing
            # (splash block-skip contract); pad tokens attend only to
            # each other and the mask zero-weights them in the loss
            seg[r, used[r]:] = nseg[r]
        self.last_fill_ratio = tokens / float(rows * T)
        _note_pack(tokens, rows * T)
        STAT_ADD("STAT_packing_sequences", placed)
        return tuple([out[0], seg, pos] + out[1:] + [mask])
