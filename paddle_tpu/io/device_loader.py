"""DeviceFeeder: host->device transfer overlapped with compute.

Reference `fluid/reader.py` use_buffer_reader / the GPU
`buffered_reader.py` double buffer: while the accelerator chews on batch
N, a background thread already runs `jax.device_put` on batch N+1, so the
train step never waits on PCIe/ICI for input data (tf.data-style prefetch,
Murray et al. 2021). Depth 2 is the classic double buffer — one batch in
flight on device, one being staged.

The feeder wraps ANY iterator (DataLoader, generator, list of batches) and
preserves batch order and structure; Tensor/ndarray leaves come out as
device-committed Tensors. `Model.fit`/`evaluate` wrap their DataLoader
with this automatically when `use_buffer_reader` is set (the default).

Sharding-aware placement: `device` may be a jax Device, a
`jax.sharding.Sharding`, or a CALLABLE `leaf -> Device/Sharding` (see
`parallel.spmd.batch_placement`). With a placement callable the feeder
thread lays every batch directly into its dp/sp-sharded device layout, so
the sharded train step consumes the arrays as-is instead of re-splitting
them on the synchronous step path.

Counters (framework/monitor.py):
  STAT_device_feeder_batches  — batches handed to the consumer
  STAT_device_feeder_overlap  — hand-outs whose staging was actually
                                hidden behind the consumer's compute: the
                                consumer blocked for < 25% of the wall
                                time since the previous hand-out (an
                                instantaneous queue probe instead would
                                read false whenever the producer's
                                device_put lands just-in-time — e.g. a
                                CPU mesh whose copies contend with the
                                step for the same cores — even though the
                                fetch latency WAS hidden). Only real
                                batches count; the end-of-stream sentinel
                                or a forwarded exception never does.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..framework.monitor import STAT_ADD
from ..framework.tensor import Tensor
from ..profiler import RecordEvent

__all__ = ["DeviceFeeder"]

_DONE = object()


def _device_put_tree(obj, device=None):
    """jax.device_put every array leaf, preserving the batch structure.

    `device` may be None, a Device, a Sharding, or a callable resolving a
    per-leaf placement (a leaf's target sharding depends on its rank).
    """
    import jax

    def target(x):
        return device(x) if callable(device) else device

    def put(x):
        if isinstance(x, Tensor):
            return Tensor(jax.device_put(x._value, target(x._value)),
                          stop_gradient=x.stop_gradient)
        if isinstance(x, (np.ndarray, np.generic)):
            arr = np.asarray(x)
            return Tensor(jax.device_put(arr, target(arr)))
        if isinstance(x, jax.Array):
            return jax.device_put(x, target(x))
        if isinstance(x, dict):
            return {k: put(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(put(v) for v in x)
        return x

    return put(obj)


class DeviceFeeder:
    """Double-buffered async device feed over any batch iterator.

    depth=2 keeps at most one staged batch ahead of the consumer (plus the
    one being produced), bounding device memory at ~2 extra batches.
    """

    def __init__(self, loader, depth: int = 2, device=None):
        if depth < 1:
            raise ValueError(f"DeviceFeeder depth must be >= 1, got {depth}")
        self.loader = loader
        self.depth = depth
        self.device = device

    def __len__(self):
        # delegate without assuming the source sized itself: generators
        # have no __len__, and a DataLoader over an IterableDataset raises
        # TypeError from its own — both must surface as TypeError so
        # callers probing with try/except fall back to countless mode
        n = getattr(self.loader, "__len__", None)
        if n is None:
            raise TypeError(
                f"{type(self.loader).__name__} loader has no __len__; "
                "iterate the feeder instead of sizing it")
        return n()

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        it = iter(self.loader)

        def produce():
            try:
                while not stop.is_set():
                    try:
                        with RecordEvent("feeder::fetch"):
                            batch = next(it)
                    except StopIteration:
                        break
                    with RecordEvent("feeder::stage"):
                        item = _device_put_tree(batch, self.device)
                    # bounded put that stays responsive to consumer exit
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except BaseException as e:  # noqa: BLE001 — forward to consumer
                while not stop.is_set():
                    try:
                        q.put(e, timeout=0.1)
                        return
                    except queue.Full:
                        continue
            finally:
                # close the source iterator from its owning thread (the mp
                # DataLoader's shutdown must not run in a GC finalizer)
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
            while not stop.is_set():
                try:
                    q.put(_DONE, timeout=0.1)
                    return
                except queue.Full:
                    continue

        t = threading.Thread(target=produce, daemon=True,
                             name="paddle_tpu-device-feeder")
        t.start()
        try:
            last = time.perf_counter()
            while True:
                t0 = time.perf_counter()
                item = q.get()
                now = time.perf_counter()
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                # overlap = the producer hid this batch's staging behind
                # the consumer's compute: the consumer's blocking wait is
                # a small fraction of the inter-hand-out wall time. (The
                # first hand-out has nothing to hide behind: wait ==
                # elapsed, so it never counts.)
                wait, elapsed = now - t0, now - last
                if wait < 0.25 * elapsed:
                    STAT_ADD("STAT_device_feeder_overlap")
                STAT_ADD("STAT_device_feeder_batches")
                last = now
                yield item
        finally:
            stop.set()
            # unblock a producer parked on a full queue
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            # short join: a producer blocked inside next(it) on a slow
            # batch can't observe `stop` until that batch lands — don't
            # stall the caller's exit path for it. The daemon thread
            # still runs its finally (source close) once next() returns.
            t.join(timeout=1)
