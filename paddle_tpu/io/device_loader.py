"""DeviceFeeder: host->device transfer overlapped with compute.

Reference `fluid/reader.py` use_buffer_reader / the GPU
`buffered_reader.py` double buffer: while the accelerator chews on batch
N, a background thread already runs `jax.device_put` on batch N+1, so the
train step never waits on PCIe/ICI for input data (tf.data-style prefetch,
Murray et al. 2021). Depth 2 is the classic double buffer — one batch in
flight on device, one being staged.

The feeder wraps ANY iterator (DataLoader, generator, list of batches) and
preserves batch order and structure; Tensor/ndarray leaves come out as
device-committed Tensors. `Model.fit`/`evaluate` wrap their DataLoader
with this automatically when `use_buffer_reader` is set (the default).

Counters (framework/monitor.py):
  STAT_device_feeder_batches  — batches handed to the consumer
  STAT_device_feeder_overlap  — hand-outs where the next batch was already
                                staged (proof the overlap actually engaged)
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ..framework.monitor import STAT_ADD
from ..framework.tensor import Tensor

__all__ = ["DeviceFeeder"]

_DONE = object()


def _device_put_tree(obj, device=None):
    """jax.device_put every array leaf, preserving the batch structure."""
    import jax

    def put(x):
        if isinstance(x, Tensor):
            return Tensor(jax.device_put(x._value, device),
                          stop_gradient=x.stop_gradient)
        if isinstance(x, (np.ndarray, np.generic)):
            return Tensor(jax.device_put(np.asarray(x), device))
        if isinstance(x, jax.Array):
            return jax.device_put(x, device)
        if isinstance(x, dict):
            return {k: put(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return type(x)(put(v) for v in x)
        return x

    return put(obj)


class DeviceFeeder:
    """Double-buffered async device feed over any batch iterator.

    depth=2 keeps at most one staged batch ahead of the consumer (plus the
    one being produced), bounding device memory at ~2 extra batches.
    """

    def __init__(self, loader, depth: int = 2, device=None):
        if depth < 1:
            raise ValueError(f"DeviceFeeder depth must be >= 1, got {depth}")
        self.loader = loader
        self.depth = depth
        self.device = device

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        it = iter(self.loader)

        def produce():
            try:
                while not stop.is_set():
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    staged = _device_put_tree(batch, self.device)
                    # bounded put that stays responsive to consumer exit
                    while not stop.is_set():
                        try:
                            q.put(staged, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except BaseException as e:  # noqa: BLE001 — forward to consumer
                while not stop.is_set():
                    try:
                        q.put(e, timeout=0.1)
                        return
                    except queue.Full:
                        continue
            finally:
                # close the source iterator from its owning thread (the mp
                # DataLoader's shutdown must not run in a GC finalizer)
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        pass
            while not stop.is_set():
                try:
                    q.put(_DONE, timeout=0.1)
                    return
                except queue.Full:
                    continue

        t = threading.Thread(target=produce, daemon=True,
                             name="paddle_tpu-device-feeder")
        t.start()
        try:
            while True:
                staged_ahead = not q.empty()
                item = q.get()
                if item is _DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                if staged_ahead:
                    # this batch was staged while the last one computed —
                    # only real batches count, not the sentinel/exceptions
                    STAT_ADD("STAT_device_feeder_overlap")
                STAT_ADD("STAT_device_feeder_batches")
                yield item
        finally:
            stop.set()
            # unblock a producer parked on a full queue
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            # short join: a producer blocked inside next(it) on a slow
            # batch can't observe `stop` until that batch lands — don't
            # stall the caller's exit path for it. The daemon thread
            # still runs its finally (source close) once next() returns.
            t.join(timeout=1)
