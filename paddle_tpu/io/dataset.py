"""Datasets (reference `python/paddle/io/__init__.py` /
`fluid/dataloader/dataset.py`)."""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        from ..framework.tensor import Tensor
        arrays = [t.numpy() if isinstance(t, Tensor) else np.asarray(t)
                  for t in tensors]
        assert all(a.shape[0] == arrays[0].shape[0] for a in arrays)
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: List[Dataset]):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, tuple):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets: List[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: Iterable[Dataset]):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum(
            [len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out
