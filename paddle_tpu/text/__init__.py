"""paddle.text datasets (reference `python/paddle/text/datasets/`: Imdb,
Imikolov, Conll05st, Movielens, UCIHousing, WMT14/16). Offline env:
datasets read local files in the reference formats when present, else
deterministic synthetic corpora keeping the shape/dtype contracts."""
from __future__ import annotations

import os
import warnings

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "WMT14", "WMT16", "Conll05st",
           "Movielens", "ViterbiDecoder", "viterbi_decode"]


def _synth_text(n, vocab, seq_len, seed, with_label=True, n_classes=2):
    rng = np.random.RandomState(seed)
    docs = [rng.randint(1, vocab, size=rng.randint(5, seq_len)).astype(
        "int64") for _ in range(n)]
    labels = rng.randint(0, n_classes, n).astype("int64")
    return docs, labels


class Imdb(Dataset):
    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        warnings.warn("Imdb: synthetic fallback (offline env)") \
            if not (data_file and os.path.exists(data_file)) else None
        self.docs, self.labels = _synth_text(
            512 if mode == "train" else 128, 5000, 100,
            seed=50 if mode == "train" else 51)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        self.window = window_size
        rng = np.random.RandomState(60)
        n = 1024 if mode == "train" else 256
        self.data = rng.randint(0, 2000, size=(n, window_size)).astype(
            "int64")

    def __getitem__(self, idx):
        row = self.data[idx]
        return tuple(row[:-1]) + (row[-1:],)

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        rng = np.random.RandomState(70)
        n = 404 if mode == "train" else 102
        self.x = rng.randn(n, 13).astype("float32")
        w = rng.randn(13).astype("float32")
        self.y = (self.x @ w + 0.1 * rng.randn(n)).astype(
            "float32").reshape(-1, 1)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class _MTBase(Dataset):
    def __init__(self, mode="train", src_vocab=1000, tgt_vocab=1000,
                 seed=80):
        rng = np.random.RandomState(seed)
        n = 512 if mode == "train" else 64
        self.src = [rng.randint(2, src_vocab, size=rng.randint(4, 20))
                    .astype("int64") for _ in range(n)]
        self.tgt = [rng.randint(2, tgt_vocab, size=rng.randint(4, 20))
                    .astype("int64") for _ in range(n)]

    def __getitem__(self, idx):
        t = self.tgt[idx]
        return self.src[idx], t[:-1], t[1:]

    def __len__(self):
        return len(self.src)


class WMT14(_MTBase):
    def __init__(self, data_file=None, mode="train", dict_size=1000,
                 download=True):
        super().__init__(mode, dict_size, dict_size, 81)


class WMT16(_MTBase):
    def __init__(self, data_file=None, mode="train", src_dict_size=1000,
                 trg_dict_size=1000, lang="en", download=True):
        super().__init__(mode, src_dict_size, trg_dict_size, 82)


class Conll05st(Dataset):
    def __init__(self, data_file=None, mode="train", download=True, **kw):
        rng = np.random.RandomState(90)
        n = 256
        self.sents = [rng.randint(0, 500, size=rng.randint(5, 30)).astype(
            "int64") for _ in range(n)]
        self.labels = [rng.randint(0, 20, size=len(s)).astype("int64")
                       for s in self.sents]

    def __getitem__(self, idx):
        return self.sents[idx], self.labels[idx]

    def __len__(self):
        return len(self.sents)


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        rng = np.random.RandomState(95)
        n = 1024 if mode == "train" else 128
        self.users = rng.randint(0, 500, n).astype("int64")
        self.items = rng.randint(0, 1000, n).astype("int64")
        self.ratings = rng.randint(1, 6, n).astype("float32")

    def __getitem__(self, idx):
        return self.users[idx], self.items[idx], self.ratings[idx]

    def __len__(self):
        return len(self.users)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """Viterbi decoding (reference `text/viterbi_decode.py` /
    `operators/viterbi_decode_op`) — lax.scan based."""
    import jax
    import jax.numpy as jnp
    from ..framework.tensor import Tensor, apply_op

    def impl(pot, trans):
        # pot: [B, T, N], trans: [N, N]
        def step(carry, emit):
            score = carry  # [B, N]
            cand = score[:, :, None] + trans[None] + emit[:, None, :]
            best = jnp.max(cand, axis=1)
            idx = jnp.argmax(cand, axis=1)
            return best, idx
        score0 = pot[:, 0]
        scores, backptrs = jax.lax.scan(
            step, score0, jnp.moveaxis(pot[:, 1:], 1, 0))
        last = jnp.argmax(scores, axis=-1)

        def backtrack(carry, bp):
            cur = carry
            prev = jnp.take_along_axis(bp, cur[:, None], axis=1)[:, 0]
            return prev, cur
        _, path = jax.lax.scan(backtrack, last, backptrs, reverse=True)
        path = jnp.concatenate([jnp.moveaxis(path, 0, 1),
                                last[:, None]], axis=1)
        best_score = jnp.max(scores, axis=-1)
        return best_score, path.astype("int64")
    return apply_op("viterbi_decode", impl,
                    (potentials, transition_params), {})


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths)
