"""Optimizer update rules (reference `paddle/fluid/operators/optimizers/*`:
sgd_op, momentum_op, adam_op, adamw, lamb_op, lars_momentum_op, rmsprop_op,
adagrad_op, adadelta_op, adamax_op). Each is a pure pytree rule; see
Optimizer for the execution model."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "LarsMomentum"]


class SGD(Optimizer):
    def _update(self, g, p, state, lr, step):
        g = self._apply_weight_decay(g, p)
        return p - lr.astype(p.dtype) * g.astype(p.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, v):
        return {"velocity": jnp.zeros_like(v)}

    def _update(self, g, p, state, lr, step):
        g = self._apply_weight_decay(g.astype(p.dtype), p)
        vel = self._momentum * state["velocity"] + g
        if self._nesterov:
            new_p = p - lr.astype(p.dtype) * (g + self._momentum * vel)
        else:
            new_p = p - lr.astype(p.dtype) * vel
        return new_p, {"velocity": vel}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon

    def _init_state(self, v):
        return {"moment1": jnp.zeros_like(v, "float32"),
                "moment2": jnp.zeros_like(v, "float32")}

    def _adam_core(self, g, p, state, lr, step):
        g32 = g.astype("float32")
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g32 * g32
        t = step.astype("float32")
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return upd, {"moment1": m, "moment2": v}

    def _update(self, g, p, state, lr, step):
        g = self._apply_weight_decay(g.astype(p.dtype), p)
        upd, new_state = self._adam_core(g, p, state, lr, step)
        return (p.astype("float32") - upd).astype(p.dtype), new_state


class AdamW(Adam):
    """Decoupled weight decay (reference `paddle/optimizer/adamw.py`)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, name=name)
        self._coeff = float(weight_decay) if not hasattr(
            weight_decay, "_coeff") else weight_decay._coeff
        self._apply_decay_fn = apply_decay_param_fun

    def _update(self, g, p, state, lr, step):
        upd, new_state = self._adam_core(g, p, state, lr, step)
        p32 = p.astype("float32")
        p32 = p32 - lr * self._coeff * p32 - upd
        return p32.astype(p.dtype), new_state


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, v):
        return {"moment": jnp.zeros_like(v, "float32"),
                "inf_norm": jnp.zeros_like(v, "float32")}

    def _update(self, g, p, state, lr, step):
        g = self._apply_weight_decay(g.astype("float32"), p.astype("float32"))
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        t = step.astype("float32")
        upd = lr / (1 - self._beta1 ** t) * m / (u + self._eps)
        return ((p.astype("float32") - upd).astype(p.dtype),
                {"moment": m, "inf_norm": u})


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, v):
        return {"moment": jnp.full_like(v, self._init_acc, "float32")}

    def _update(self, g, p, state, lr, step):
        g = self._apply_weight_decay(g.astype("float32"), p.astype("float32"))
        acc = state["moment"] + g * g
        new_p = p.astype("float32") - lr * g / (jnp.sqrt(acc) + self._eps)
        return new_p.astype(p.dtype), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps, self._rho = epsilon, rho

    def _init_state(self, v):
        return {"avg_squared_grad": jnp.zeros_like(v, "float32"),
                "avg_squared_update": jnp.zeros_like(v, "float32")}

    def _update(self, g, p, state, lr, step):
        g = self._apply_weight_decay(g.astype("float32"), p.astype("float32"))
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = (jnp.sqrt(state["avg_squared_update"] + self._eps)
               / jnp.sqrt(asg + self._eps)) * g
        asu = (self._rho * state["avg_squared_update"]
               + (1 - self._rho) * upd * upd)
        return ((p.astype("float32") - lr * upd).astype(p.dtype),
                {"avg_squared_grad": asg, "avg_squared_update": asu})


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, v):
        st = {"mean_square": jnp.zeros_like(v, "float32"),
              "momentum_acc": jnp.zeros_like(v, "float32")}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(v, "float32")
        return st

    def _update(self, g, p, state, lr, step):
        g = self._apply_weight_decay(g.astype("float32"), p.astype("float32"))
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum_acc"] + lr * g / denom
        new_p = (p.astype("float32") - mom).astype(p.dtype)
        st = {"mean_square": ms, "momentum_acc": mom}
        if mg is not None:
            st["mean_grad"] = mg
        return new_p, st


class Lamb(Optimizer):
    _rowwise_safe = False  # trust ratio needs whole-tensor norms
    """reference `operators/optimizers/lamb_op.h`."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, v):
        return {"moment1": jnp.zeros_like(v, "float32"),
                "moment2": jnp.zeros_like(v, "float32")}

    def _update(self, g, p, state, lr, step):
        g32 = g.astype("float32")
        p32 = p.astype("float32")
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * g32 * g32
        t = step.astype("float32")
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + self._lamb_wd * p32
        w_norm = jnp.sqrt(jnp.sum(p32 * p32))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = (p32 - lr * trust * r).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}


class LarsMomentum(Optimizer):
    _rowwise_safe = False  # local-lr needs whole-tensor norms
    """reference `operators/optimizers/lars_momentum_op.*`."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005, parameters=None,
                 grad_clip=None, epsilon=1e-9, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon

    def _init_state(self, v):
        return {"velocity": jnp.zeros_like(v, "float32")}

    def _update(self, g, p, state, lr, step):
        g32 = g.astype("float32")
        p32 = p.astype("float32")
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(g32 * g32))
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm /
            (g_norm + self._lars_wd * p_norm + self._eps), 1.0)
        vel = (self._momentum * state["velocity"]
               + lr * local_lr * (g32 + self._lars_wd * p32))
        return (p32 - vel).astype(p.dtype), {"velocity": vel}
