"""Optimizer base (reference `python/paddle/optimizer/optimizer.py`; in the
reference each update rule is a CUDA op, e.g. `operators/optimizers/adam_op`).

TPU-native design: every optimizer is a *pure pytree update rule*
`_update(grads, params, state, lr) -> (new_params, new_state)`. Eager
`step()` runs it through a cached jit over the whole parameter set (one
fused XLA program — the analogue of the reference's fused_adam); the
functional train paths (Model.fit / fleet / to_static) call the same rule
inside their compiled step, and ZeRO shards `state` over the dp axis.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Parameter, Tensor
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            self._weight_decay = float(weight_decay)
        elif weight_decay is None:
            self._weight_decay = 0.0
        else:  # L2Decay-like object
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay,
                                                       "coeff", 0.0)))
        self._accumulators: Dict[int, dict] = {}
        self._global_step = 0
        self._jit_cache = {}

    # -- lr -----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the lr is an LRScheduler; call "
                "scheduler.step() instead")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # -- state --------------------------------------------------------------
    def _init_state(self, param_value) -> dict:
        """Per-parameter accumulator init. Override."""
        return {}

    def _update(self, g, p, state: dict, lr, step) -> tuple:
        """Pure per-parameter update: returns (new_p, new_state)."""
        raise NotImplementedError

    def _state_for(self, p: Parameter) -> dict:
        st = self._accumulators.get(id(p))
        if st is None:
            st = self._init_state(p._value)
            self._accumulators[id(p)] = st
        return st

    # -- eager step ---------------------------------------------------------
    def step(self):
        params = [p for p in (self._parameter_list or [])
                  if not p.stop_gradient and p._grad is not None]
        if not params:
            return
        grads = [p._grad for p in params]
        if self._grad_clip is not None:
            clipped = self._grad_clip._tree_clip(grads)
            grads = clipped
        states = [self._state_for(p) for p in params]
        lr = self.get_lr()
        step_no = self._global_step
        key = (len(params), tuple(p._value.shape for p in params),
               tuple(str(p._value.dtype) for p in params))
        fn = self._jit_cache.get(key)
        if fn is None:
            def batch_update(gs, ps, sts, lr_, st_no):
                new_ps, new_sts = [], []
                for g, p, s in zip(gs, ps, sts):
                    np_, ns_ = self._update(g, p, s, lr_, st_no)
                    new_ps.append(np_)
                    new_sts.append(ns_)
                return new_ps, new_sts
            fn = jax.jit(batch_update)
            self._jit_cache[key] = fn
        new_vals, new_states = fn(grads, [p._value for p in params], states,
                                  jnp.asarray(lr, "float32"),
                                  jnp.asarray(step_no + 1, "int32"))
        for p, v, s in zip(params, new_vals, new_states):
            p._value = v
            self._accumulators[id(p)] = s
        self._global_step += 1

    # optimizers whose _update takes whole-tensor norms (trust ratios)
    # cannot be applied row-wise; they override this to False
    _rowwise_safe = True

    def apply_selected_rows(self, param, srows, advance_step=True):
        """Sparse-row update over a SelectedRows gradient (reference
        sparse kernels in `operators/optimizers/*_op.cc` consuming
        `framework/selected_rows.h` grads): only the touched rows of the
        parameter and of its accumulators are read or written — no
        vocab-sized dense gradient is ever materialized.

        When updating several sparse tables in one optimization step,
        pass advance_step=False for all but the last call so Adam-family
        bias correction sees one step per iteration, like step()."""
        if not self._rowwise_safe:
            raise NotImplementedError(
                f"{type(self).__name__} computes whole-tensor trust-ratio "
                f"norms; a row-subset update would change its scale — use "
                f"a dense gradient")
        m = srows.merge()
        if m.height != param._value.shape[0]:
            raise ValueError(
                f"SelectedRows height {m.height} != param rows "
                f"{param._value.shape[0]}")
        rows = jnp.asarray(m.rows)
        st = self._state_for(param)
        prow = jnp.take(param._value, rows, axis=0)
        sliced, passthrough = {}, {}
        for k, v in st.items():
            va = jnp.asarray(v)
            if va.ndim >= 1 and va.shape[0] == param._value.shape[0]:
                sliced[k] = jnp.take(va, rows, axis=0)
            else:
                passthrough[k] = va
        g = jnp.asarray(m.value).reshape(prow.shape)
        if self._grad_clip is not None:
            g = self._grad_clip._tree_clip([g])[0]
        new_prow, new_state = self._update(
            g, prow, {**sliced, **passthrough},
            jnp.asarray(self.get_lr(), "float32"),
            jnp.asarray(self._global_step + 1, "int32"))
        param._value = param._value.at[rows].set(
            new_prow.astype(param._value.dtype))
        for k in st:
            if k in sliced:
                st[k] = jnp.asarray(st[k]).at[rows].set(new_state[k])
            else:
                st[k] = new_state[k]
        self._accumulators[id(param)] = st
        if advance_step:
            self._global_step += 1

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import program as _static
        if _static.in_static_mode():
            prog = _static.default_main_program()
            pg = _static.append_backward(loss, parameters)
            prog._opt_hooks.append(self)
            return [], pg
        loss.backward()
        self.step()
        return [], []

    def clear_grad(self):
        for p in (self._parameter_list or []):
            p.clear_grad()

    clear_gradients = clear_grad

    # -- functional API (used by jitted train steps / fleet / ZeRO) ---------
    def init_state_pytree(self, params_pytree):
        return jax.tree_util.tree_map(
            lambda v: self._init_state(v), params_pytree,
            is_leaf=lambda x: isinstance(x, jnp.ndarray) or hasattr(x, "shape"))

    def apply_gradients_pytree(self, grads, params, opt_state, lr=None,
                               step=0):
        """Pure: same rule as step(), over arbitrary pytrees (jit/pjit-safe)."""
        if self._grad_clip is not None:
            grads = self._grad_clip._tree_clip(grads)
        lr = self.get_lr() if lr is None else lr
        leaves_g, treedef = jax.tree_util.tree_flatten(grads)
        leaves_p = treedef.flatten_up_to(params)
        leaves_s = treedef.flatten_up_to(opt_state)
        new_p, new_s = [], []
        for g, p, s in zip(leaves_g, leaves_p, leaves_s):
            np_, ns_ = self._update(g, p, s, lr, step)
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s))

    # -- checkpoint ---------------------------------------------------------
    def state_dict(self):
        out = {"global_step": self._global_step}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        for i, p in enumerate(self._parameter_list or []):
            st = self._accumulators.get(id(p))
            if st:
                for k, v in st.items():
                    out[f"{p.name}_{k}"] = Tensor(v)
        return out

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("global_step", 0))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state_dict:
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
        for p in (self._parameter_list or []):
            st = self._init_state(p._value)
            found = False
            for k in st:
                key = f"{p.name}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    st[k] = jnp.asarray(
                        v.numpy() if isinstance(v, Tensor) else v)
                    found = True
            if found:
                self._accumulators[id(p)] = st

    def _apply_weight_decay(self, g, p):
        if self._weight_decay:
            return g + self._weight_decay * p
        return g
