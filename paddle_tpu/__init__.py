"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle ~v2.0 (reference: /root/reference), rebuilt on
JAX/XLA/Pallas: ops lower to HLO, parallelism is GSPMD/shard_map over
device meshes, autograd is jax.vjp (eager tape) / jax.grad (compiled).
"""
from __future__ import annotations

from .framework import (
    CPUPlace, CUDAPlace, DType, Parameter, Place, TPUPlace, Tensor,
    bfloat16, bool_, complex128, complex64, enable_grad, float16, float32,
    float64, get_device, get_flags, grad, int16, int32, int64, int8,
    is_grad_enabled, no_grad, seed, set_device, set_flags, to_tensor, uint8,
)
from .framework.place import (device_count, is_compiled_with_cuda,
                              is_compiled_with_tpu)

from .ops import *  # noqa: F401,F403  (tensor/math/… API at top level)
from .ops import creation, linalg, logic, manipulation, math, reduction, search
from .ops import random_ops as random  # paddle.rand etc already exported

from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import amp  # noqa: E402
from . import jit  # noqa: E402
from . import static  # noqa: E402
from . import io  # noqa: E402
from . import metric  # noqa: E402
from . import hapi  # noqa: E402
from . import vision  # noqa: E402
from . import distributed  # noqa: E402
from . import parallel  # noqa: E402
from . import models  # noqa: E402
from . import autograd  # noqa: E402
from . import device  # noqa: E402
from . import incubate  # noqa: E402
from . import inference  # noqa: E402
from . import onnx  # noqa: E402
from . import profiler  # noqa: E402
from . import quantization  # noqa: E402
from . import text  # noqa: E402
from . import utils  # noqa: E402
from .distributed.parallel import DataParallel  # noqa: E402
from .hapi.model import Model  # noqa: E402
from .hapi.model_summary import summary  # noqa: E402
from .framework.io_state import load, save  # noqa: E402
from .framework.param_attr import ParamAttr  # noqa: E402
from .static.program import disable_static, enable_static  # noqa: E402
from .static.program import in_static_mode as _in_static  # noqa: E402


def in_dynamic_mode():
    return not _in_static()


__version__ = "0.1.0"
