"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle ~v2.0 (reference: /root/reference), rebuilt on
JAX/XLA/Pallas: ops lower to HLO, parallelism is GSPMD/shard_map over
device meshes, autograd is jax.vjp (eager tape) / jax.grad (compiled).
"""
from __future__ import annotations

from .framework import (
    CPUPlace, CUDAPlace, DType, Parameter, Place, TPUPlace, Tensor,
    bfloat16, bool_, complex128, complex64, enable_grad, float16, float32,
    float64, get_device, get_flags, grad, int16, int32, int64, int8,
    is_grad_enabled, no_grad, seed, set_device, set_flags, to_tensor, uint8,
)
from .framework.place import (device_count, is_compiled_with_cuda,
                              is_compiled_with_tpu)

from .ops import *  # noqa: F401,F403  (tensor/math/… API at top level)
from .ops import creation, linalg, logic, manipulation, math, reduction, search
from .ops import random_ops as random  # paddle.rand etc already exported

from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import amp  # noqa: E402
from . import jit  # noqa: E402
from . import static  # noqa: E402
from . import io  # noqa: E402
from . import metric  # noqa: E402
from . import hapi  # noqa: E402
from . import vision  # noqa: E402
from . import distributed  # noqa: E402
from . import parallel  # noqa: E402
from . import models  # noqa: E402
from . import autograd  # noqa: E402
from . import device  # noqa: E402
from . import incubate  # noqa: E402
from . import inference  # noqa: E402
from . import onnx  # noqa: E402
from . import profiler  # noqa: E402
from . import quantization  # noqa: E402
from . import serving  # noqa: E402
from . import text  # noqa: E402
from . import utils  # noqa: E402
from .distributed.parallel import DataParallel  # noqa: E402
from .hapi.model import Model  # noqa: E402
from .hapi.model_summary import summary  # noqa: E402
from .framework.io_state import load, save  # noqa: E402
from .framework.param_attr import ParamAttr  # noqa: E402
from .static.program import disable_static, enable_static  # noqa: E402
from .static.program import in_static_mode as _in_static  # noqa: E402


def in_dynamic_mode():
    return not _in_static()


def in_dygraph_mode():
    return not _in_static()


def enable_dygraph(place=None):
    disable_static(place)


def disable_dygraph():
    enable_static()


# ---- legacy / compat surface -------------------------------------------
from .framework.place import (  # noqa: E402
    CUDAPinnedPlace, NPUPlace, XPUPlace, get_cudnn_version,
    is_compiled_with_npu, is_compiled_with_xpu,
)
from .framework.random import (  # noqa: E402
    get_cuda_rng_state, get_rng_state, set_cuda_rng_state, set_rng_state,
)
from .hapi import callbacks  # noqa: E402
from .hapi.model_summary import flops  # noqa: E402
from .ops.legacy import (  # noqa: E402
    LoDTensor, LoDTensorArray, get_default_dtype, set_default_dtype,
    set_printoptions,
)
from .static.program import data  # noqa: E402

VarBase = Tensor  # reference 2.0: paddle.Tensor is the pybind VarBase


def monkey_patch_math_varbase():
    """No-op: operator overloads are bound at import (ops.tensor_methods);
    the reference needed an explicit patch pass over pybind VarBase."""


def monkey_patch_variable():
    """No-op: static Variables share the Tensor method surface here."""


def _inplace_fn(name):
    def fn(x, *args, **kwargs):
        return getattr(x, name)(*args, **kwargs)
    fn.__name__ = name
    return fn


reshape_ = _inplace_fn("reshape_")
scatter_ = _inplace_fn("scatter_")
squeeze_ = _inplace_fn("squeeze_")
unsqueeze_ = _inplace_fn("unsqueeze_")
tanh_ = _inplace_fn("tanh_")
clip_ = _inplace_fn("clip_")
scale_ = _inplace_fn("scale_")
flatten_ = _inplace_fn("flatten_")
exp_ = _inplace_fn("exp_")
sqrt_ = _inplace_fn("sqrt_")


__version__ = "0.1.0"
