"""Custom C++ op extension (reference `paddle/fluid/extension/` +
`framework/custom_operator.cc` PD_BUILD_OP dlopen loading).

TPU-native: a custom op is a C function with a flat numpy ABI
  void op(const float** inputs, const long** shapes, const int* ndims,
          int n_inputs, float* output, const long* out_shape, int out_ndim)
compiled with g++ and bound via ctypes. It enters the framework as a
host-callback op (jax.pure_callback): jittable, with the computation
running host-side — the honest TPU analogue of a CPU custom kernel. An
optional `grad_source` provides the custom VJP the same way.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = ["load", "CppExtension", "CustomOp", "load_op_from_callable"]

_TEMPLATE_HELP = """
expected exported symbol signature (extern "C"):
  void {name}(const float** ins, const long long** shapes,
              const int* ndims, int n_in,
              float* out, const long long* out_shape, int out_ndim);
"""


class CustomOp:
    def __init__(self, name: str, fwd: Callable, out_shape_fn: Callable,
                 bwd: Optional[Callable] = None):
        self.name = name
        self._fwd = fwd
        self._out_shape_fn = out_shape_fn
        self._bwd = bwd

    def __call__(self, *tensors):
        import jax
        import jax.numpy as jnp
        from ..framework.tensor import Tensor, apply_op

        out_shape = self._out_shape_fn(
            *[tuple(t.shape) for t in tensors])
        sds = jax.ShapeDtypeStruct(tuple(out_shape), jnp.float32)
        fwd = self._fwd
        bwd = self._bwd

        def host_fwd(*arrays):
            return fwd(*[np.asarray(a, np.float32) for a in arrays])

        if bwd is None:
            def impl(*vals):
                return jax.pure_callback(host_fwd, sds, *vals)
            return apply_op(self.name, impl, tensors, {})

        @jax.custom_vjp
        def op(*vals):
            return jax.pure_callback(host_fwd, sds, *vals)

        def op_fwd(*vals):
            return op(*vals), vals

        def op_bwd(res, g):
            shapes = [jax.ShapeDtypeStruct(v.shape, jnp.float32)
                      for v in res]

            def host_bwd(g_, *vals):
                outs = bwd(np.asarray(g_, np.float32),
                           *[np.asarray(v, np.float32) for v in vals])
                return tuple(np.asarray(o, np.float32) for o in outs)
            return jax.pure_callback(host_bwd, tuple(shapes), g, *res)

        op.defvjp(op_fwd, op_bwd)

        def impl(*vals):
            return op(*vals)
        return apply_op(self.name, impl, tensors, {})


def load_op_from_callable(name, fwd, out_shape_fn, bwd=None):
    """Register a python/numpy callable as a framework op (host callback)."""
    return CustomOp(name, fwd, out_shape_fn, bwd)


def _compile(sources: Sequence[str], extra_cxx_flags=()) -> str:
    key = hashlib.sha1()
    srcs = []
    for s in sources:
        with open(s, "rb") as f:
            data = f.read()
        key.update(data)
        srcs.append(s)
    build_dir = os.path.join(tempfile.gettempdir(), "paddle_tpu_ext")
    os.makedirs(build_dir, exist_ok=True)
    so = os.path.join(build_dir, f"ext_{key.hexdigest()[:16]}.so")
    if not os.path.exists(so):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", so,
               *srcs, *extra_cxx_flags]
        r = subprocess.run(cmd, capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(f"custom op build failed:\n{r.stderr}\n"
                               f"{_TEMPLATE_HELP}")
    return so


def load(name: str, sources: Sequence[str], out_shape_fn: Callable = None,
         grad_symbol: Optional[str] = None, extra_cxx_flags=(),
         verbose=False) -> CustomOp:
    """Compile + load a custom C++ op (reference
    `utils/cpp_extension.load`). `name` is the exported symbol."""
    so = _compile(sources, extra_cxx_flags)
    lib = ctypes.CDLL(so)
    sym = getattr(lib, name)
    sym.argtypes = [
        ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_longlong)),
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
    ]
    out_shape_fn = out_shape_fn or (lambda *shapes: shapes[0])

    def fwd(*arrays):
        arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
        n = len(arrays)
        ins = (ctypes.POINTER(ctypes.c_float) * n)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        shape_arrs = [np.asarray(a.shape, np.longlong) for a in arrays]
        shapes = (ctypes.POINTER(ctypes.c_longlong) * n)(
            *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
              for s in shape_arrs])
        ndims = (ctypes.c_int * n)(*[a.ndim for a in arrays])
        oshape = tuple(out_shape_fn(*[tuple(a.shape) for a in arrays]))
        out = np.empty(oshape, np.float32)
        oshape_arr = np.asarray(oshape, np.longlong)
        sym(ins, shapes, ndims, n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            oshape_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            out.ndim)
        return out

    bwd = None
    if grad_symbol:
        gsym = getattr(lib, grad_symbol)
        gsym.argtypes = sym.argtypes

        def bwd(g, *arrays):  # noqa: F811
            # grad symbol computes d/d(input0) only in this simple ABI;
            # it receives [g, *forward_inputs]
            full = [g] + list(arrays)
            arrays2 = [np.ascontiguousarray(a, np.float32) for a in full]
            n = len(arrays2)
            ins = (ctypes.POINTER(ctypes.c_float) * n)(
                *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                  for a in arrays2])
            shape_arrs = [np.asarray(a.shape, np.longlong)
                          for a in arrays2]
            shapes = (ctypes.POINTER(ctypes.c_longlong) * n)(
                *[s.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
                  for s in shape_arrs])
            ndims = (ctypes.c_int * n)(*[a.ndim for a in arrays2])
            out = np.empty(arrays[0].shape, np.float32)
            oshape_arr = np.asarray(arrays[0].shape, np.longlong)
            gsym(ins, shapes, ndims, n,
                 out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                 oshape_arr.ctypes.data_as(
                     ctypes.POINTER(ctypes.c_longlong)),
                 out.ndim)
            return (out,) + tuple(np.zeros_like(a) for a in arrays[1:])
    return CustomOp(name, fwd, out_shape_fn, bwd)


class CppExtension:
    """setuptools-style descriptor (API parity)."""

    def __init__(self, sources, **kwargs):
        self.sources = sources
        self.kwargs = kwargs
