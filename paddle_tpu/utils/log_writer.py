"""Scalar/metric logging (reference ecosystem: VisualDL `LogWriter`, the
`paddle.callbacks.VisualDL` hapi callback, and the STAT counters of
`platform/monitor.h`).

TPU-native stance: no daemon, no protobuf — one append-only JSONL file
per run ({"tag", "step", "value", "wall_time"} records) that any plotting
stack ingests, plus a `dump_stats()` bridge that snapshots the framework
STAT counters into the same stream."""
from __future__ import annotations

import json
import os
import time
from typing import Optional

__all__ = ["LogWriter"]


class LogWriter:
    def __init__(self, logdir: str, file_name: str = "scalars.jsonl",
                 display_name: str = ""):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(logdir, file_name)
        self._f = open(self._path, "a", buffering=1)
        self.display_name = display_name

    def add_scalar(self, tag: str, value, step: int) -> None:
        self._f.write(json.dumps({
            "tag": tag, "step": int(step), "value": float(value),
            "wall_time": time.time()}) + "\n")

    def add_text(self, tag: str, text: str, step: int = 0) -> None:
        self._f.write(json.dumps({
            "tag": tag, "step": int(step), "text": str(text),
            "wall_time": time.time()}) + "\n")

    def dump_stats(self, step: int = 0, prefix: str = "stat/") -> None:
        """Snapshot every framework STAT counter
        (framework/monitor.py) into the scalar stream."""
        from ..framework.monitor import all_stats
        for name, v in all_stats().items():
            self.add_scalar(prefix + name, v, step)

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
