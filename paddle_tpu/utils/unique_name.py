"""Unique name generator (reference `fluid/unique_name.py`)."""
from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "guard", "switch"]

_counters = defaultdict(int)


def generate(key: str) -> str:
    _counters[key] += 1
    return f"{key}_{_counters[key] - 1}"


def switch(new_generator=None):
    global _counters
    old = _counters
    _counters = defaultdict(int)
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch()
    try:
        yield
    finally:
        global _counters
        _counters = old
