"""paddle.utils (reference `python/paddle/utils/`)."""
from . import download, unique_name
from .download import get_weights_path_from_url
from .lazy_import import try_import
from .log_writer import LogWriter

__all__ = ["download", "get_weights_path_from_url", "try_import",
           "unique_name", "deprecated", "run_check", "LogWriter"]


def deprecated(update_to="", since="", reason=""):
    def deco(fn):
        import functools
        import warnings

        @functools.wraps(fn)
        def wrapper(*a, **k):
            warnings.warn(f"{fn.__name__} is deprecated since {since}: "
                          f"{reason}; use {update_to}", DeprecationWarning)
            return fn(*a, **k)
        return wrapper
    return deco


def run_check():
    import jax
    import paddle_tpu as paddle
    x = paddle.ones([2, 2])
    y = (x @ x).sum()
    assert float(y) == 8.0
    print(f"paddle_tpu is installed successfully! devices: {jax.devices()}")
