"""try_import (reference `python/paddle/utils/lazy_import.py`)."""
from __future__ import annotations

import importlib

__all__ = ["try_import"]


def try_import(module_name: str, err_msg: str = None):
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed "
            "(this environment is offline; vendored deps only)") from e
