"""Pretrained weight fetch (reference `python/paddle/utils/download.py`).
This image has zero egress: resolves from a local cache dir
(~/.cache/paddle_tpu or $PADDLE_TPU_WEIGHTS_DIR) and raises with guidance
when the file is absent instead of downloading."""
from __future__ import annotations

import os
import os.path as osp

__all__ = ["get_weights_path_from_url", "get_path_from_url", "WEIGHTS_HOME"]

WEIGHTS_HOME = os.environ.get(
    "PADDLE_TPU_WEIGHTS_DIR",
    osp.expanduser("~/.cache/paddle_tpu/weights"))


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    fname = osp.basename(url.split("?")[0])
    local = osp.join(WEIGHTS_HOME, fname)
    if osp.exists(local):
        return local
    raise FileNotFoundError(
        f"pretrained weights {fname} not found in {WEIGHTS_HOME} and this "
        f"environment has no network egress. Place the file there manually "
        f"(source url: {url}).")


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True,
                      decompress=True):
    root_dir = root_dir or WEIGHTS_HOME
    local = osp.join(root_dir, osp.basename(url.split("?")[0]))
    if osp.exists(local):
        return local
    raise FileNotFoundError(f"{local} missing; no network egress "
                            f"(source url: {url})")
