"""Metrics (reference `python/paddle/metric/metrics.py`)."""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        correct = (idx == label_np[..., None])
        return Tensor(correct.astype("float32"))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim else 1
        accs = []
        for k in self.topk:
            num_corr = c[..., :k].sum()
            self.total[self.topk.index(k)] += num_corr
            self.count[self.topk.index(k)] += num
            accs.append(float(num_corr) / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(int).reshape(-1)
        l = _np(labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(int).reshape(-1)
        l = _np(labels).astype(int).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = _np(labels).astype(int).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(int), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds high→low
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """functional accuracy (reference `fluid/layers/metric_op.py`)."""
    pred = _np(input)
    l = _np(label).reshape(-1)
    idx = np.argsort(-pred, axis=-1)[:, :k]
    corr = (idx == l[:, None]).any(axis=1).mean()
    return Tensor(np.asarray(corr, dtype="float32"))
