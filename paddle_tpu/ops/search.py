"""Search/sort ops (reference `python/paddle/tensor/search.py`,
`operators/arg_max_op`, `top_k_v2_op`, `argsort_op`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from ..framework.tensor import Tensor, apply_op

__all__ = ["argmax", "argmin", "argsort", "sort", "topk", "searchsorted",
           "kthvalue", "mode", "index_sample"]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = to_jax_dtype(dtype)
    return apply_op("argmax",
                    lambda v: jnp.argmax(v, axis=axis,
                                         keepdims=keepdim).astype(dt), (x,), {})


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = to_jax_dtype(dtype)
    return apply_op("argmin",
                    lambda v: jnp.argmin(v, axis=axis,
                                         keepdims=keepdim).astype(dt), (x,), {})


def argsort(x, axis=-1, descending=False, name=None):
    def impl(v):
        idx = jnp.argsort(v, axis=axis, descending=descending)
        return idx.astype("int64")
    return apply_op("argsort", impl, (x,), {})


def sort(x, axis=-1, descending=False, name=None):
    return apply_op("sort",
                    lambda v: jnp.sort(v, axis=axis, descending=descending),
                    (x,), {})


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())

    def impl(v):
        ax = axis if axis >= 0 else v.ndim + axis
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, k)
        else:
            vals, idx = jax.lax.top_k(-vm, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx, -1, ax).astype("int64"))
    return apply_op("top_k_v2", impl, (x,), {})


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    dt = "int32" if out_int32 else "int64"
    return apply_op("searchsorted",
                    lambda s, v: jnp.searchsorted(s, v, side=side).astype(dt),
                    (sorted_sequence, values), {})


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def impl(v):
        ax = axis if axis >= 0 else v.ndim + axis
        srt = jnp.sort(v, axis=ax)
        idx = jnp.argsort(v, axis=ax)
        vals = jnp.take(srt, k - 1, axis=ax)
        inds = jnp.take(idx, k - 1, axis=ax).astype("int64")
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            inds = jnp.expand_dims(inds, ax)
        return vals, inds
    return apply_op("kthvalue", impl, (x,), {})


def mode(x, axis=-1, keepdim=False, name=None):
    import numpy as np
    v = np.asarray(x._value)
    vm = np.moveaxis(v, axis, -1)
    srt = np.sort(vm, axis=-1)
    # mode = most frequent value per row (ties → smallest, paddle keeps last)
    def row_mode(r):
        vals, counts = np.unique(r, return_counts=True)
        m = vals[np.argmax(counts)]
        idx = np.where(r == m)[0][-1]
        return m, idx
    flat = srt.reshape(-1, srt.shape[-1])
    vflat = vm.reshape(-1, vm.shape[-1])
    ms, idxs = [], []
    for orig in vflat:
        m, _ = row_mode(orig)
        ms.append(m)
        idxs.append(np.where(orig == m)[0][-1])
    out_shape = vm.shape[:-1]
    mvals = np.array(ms).reshape(out_shape)
    minds = np.array(idxs).reshape(out_shape).astype(np.int64)
    if keepdim:
        mvals = np.expand_dims(mvals, axis)
        minds = np.expand_dims(minds, axis)
    return Tensor(jnp.asarray(mvals)), Tensor(jnp.asarray(minds))


def index_sample(x, index):
    """reference `operators/index_sample_op`: per-row gather."""
    return apply_op("index_sample",
                    lambda v, i: jnp.take_along_axis(v, i, axis=1),
                    (x, index), {})


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """Bucket indices of x in a 1-D sorted sequence (reference
    `paddle.bucketize` over searchsorted)."""
    return searchsorted(sorted_sequence, x, out_int32=out_int32,
                        right=right, name=name)


__all__.append("bucketize")
