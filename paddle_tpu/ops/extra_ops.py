"""Op-library gap closers, batch 2 (round 5).

Each op cites its reference implementation under
`/root/reference/paddle/fluid/operators/`. All are jittable static-shape
jnp/lax compositions recorded through apply_op, so autograd, AMP and
static-program recording work uniformly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op

__all__ = [
    "pixel_unshuffle", "channel_shuffle", "max_unpool2d", "temporal_shift",
    "affine_grid", "segment_sum", "segment_mean", "segment_max",
    "segment_min", "gather_tree", "affine_channel", "row_conv",
    "conv_shift", "cvm", "data_norm", "space_to_depth",
    "pad_constant_like", "partial_concat", "partial_sum", "l1_norm",
    "squared_l2_norm", "rank_loss", "bpr_loss", "center_loss",
    "hinge_loss", "im2sequence", "linear_chain_crf", "roi_pool",
    "shuffle_batch",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# vision / layout
# ---------------------------------------------------------------------------

def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    """Inverse of pixel_shuffle (reference `pixel_shuffle_op.cc` inverse
    path; space-to-depth layout)."""
    r = int(downscale_factor)

    def impl(v):
        if data_format == "NHWC":
            v = v.transpose(0, 3, 1, 2)
        B, C, H, W = v.shape
        v = v.reshape(B, C, H // r, r, W // r, r)
        v = v.transpose(0, 1, 3, 5, 2, 4).reshape(B, C * r * r, H // r,
                                                  W // r)
        if data_format == "NHWC":
            v = v.transpose(0, 2, 3, 1)
        return v
    return apply_op("pixel_unshuffle", impl, (x,), {})


def space_to_depth(x, blocksize, name=None):
    """reference `space_to_depth_op.cc` — same layout transform as
    pixel_unshuffle."""
    return pixel_unshuffle(x, blocksize)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """reference `shuffle_channel_op.cc` (ShuffleNet)."""
    g = int(groups)

    def impl(v):
        if data_format == "NHWC":
            v = v.transpose(0, 3, 1, 2)
        B, C, H, W = v.shape
        v = v.reshape(B, g, C // g, H, W).transpose(0, 2, 1, 3, 4)
        v = v.reshape(B, C, H, W)
        if data_format == "NHWC":
            v = v.transpose(0, 2, 3, 1)
        return v
    return apply_op("channel_shuffle", impl, (x,), {})


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """reference `unpool_op.cc`: scatter pooled values back to the
    positions recorded by max_pool2d(return_mask=True)."""
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    if stride is None:
        stride = kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)

    def impl(v, idx):
        B, C, Hp, Wp = v.shape
        if output_size is not None:
            Ho, Wo = output_size[-2:]
        else:
            Ho = (Hp - 1) * stride[0] + kernel_size[0] - 2 * padding[0]
            Wo = (Wp - 1) * stride[1] + kernel_size[1] - 2 * padding[1]
        flat = jnp.zeros((B, C, Ho * Wo), v.dtype)
        vi = v.reshape(B, C, -1)
        ii = idx.reshape(B, C, -1).astype(jnp.int32)
        flat = jax.vmap(jax.vmap(
            lambda f, i, s: f.at[i].add(s)))(flat, ii, vi)
        return flat.reshape(B, C, Ho, Wo)
    return apply_op("unpool", impl, (x, indices), {})


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """reference `temporal_shift_op.cc` (TSM): shift a channel slice one
    step along the segment (time) axis in each direction."""
    T = int(seg_num)

    def impl(v):
        if data_format == "NHWC":
            v = v.transpose(0, 3, 1, 2)
        NT, C, H, W = v.shape
        N = NT // T
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        v5 = v.reshape(N, T, C, H, W)
        # reference temporal_shift_op.h: channels [0, c1) read frame t-1
        # (shift forward in time), channels [c1, c2) read frame t+1
        prev = jnp.concatenate([jnp.zeros_like(v5[:, :1, :c1]),
                                v5[:, :-1, :c1]], axis=1)
        nxt = jnp.concatenate([v5[:, 1:, c1:c2], jnp.zeros_like(
            v5[:, :1, c1:c2])], axis=1)
        out = jnp.concatenate([prev, nxt, v5[:, :, c2:]], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = out.transpose(0, 2, 3, 1)
        return out
    return apply_op("temporal_shift", impl, (x,), {})


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """reference `affine_grid_op.cc`: 2D sampling grid [N,H,W,2] from
    batched affine matrices [N,2,3] (pairs with F.grid_sample)."""
    if isinstance(out_shape, Tensor):
        from ..static.program import Variable
        if isinstance(out_shape, Variable):
            raise ValueError(
                "affine_grid: pass out_shape as a Python list in static "
                "mode — a placeholder Variable has no concrete value at "
                "graph-build time")
        out_shape = [int(s) for s in np.asarray(out_shape.numpy())]
    N, C, H, W = [int(s) for s in out_shape]

    def impl(th):
        def axis(n):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, n)
            step = 2.0 / n
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)
        ys = axis(H)
        xs = axis(W)
        gx, gy = jnp.meshgrid(xs, ys)            # [H, W]
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
        return jnp.einsum("hwk,nak->nhwa", base,
                          th.astype(jnp.float32)).astype(th.dtype)
    return apply_op("affine_grid", impl, (theta,), {})


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """reference `roi_pool_op.cc`: max-pool each RoI into a fixed grid
    (quantized bins, unlike roi_align's bilinear sampling)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def impl(feat, rois, rois_num):
        N, C, H, W = feat.shape
        R = rois.shape[0]
        batch_idx = jnp.repeat(jnp.arange(N), rois_num, axis=0,
                               total_repeat_length=R)
        r = jnp.round(rois * spatial_scale).astype(jnp.int32)
        x1, y1, x2, y2 = r[:, 0], r[:, 1], r[:, 2], r[:, 3]
        bh = jnp.maximum(y2 - y1 + 1, 1)
        bw = jnp.maximum(x2 - x1 + 1, 1)

        iy = jnp.arange(H)
        ix = jnp.arange(W)
        bins_h = jnp.arange(oh)
        bins_w = jnp.arange(ow)

        def one(b, xx1, yy1, hh, ww):
            fmap = feat[b].astype(jnp.float32)    # [C, H, W]
            # reference bins overlap: bin i covers
            # [floor(i*h/oh), ceil((i+1)*h/oh)) relative to y1
            y_lo = yy1 + jnp.floor(bins_h * hh / oh).astype(jnp.int32)
            y_hi = yy1 + jnp.ceil((bins_h + 1) * hh / oh).astype(jnp.int32)
            x_lo = xx1 + jnp.floor(bins_w * ww / ow).astype(jnp.int32)
            x_hi = xx1 + jnp.ceil((bins_w + 1) * ww / ow).astype(jnp.int32)
            in_y = ((iy[None, :] >= jnp.maximum(y_lo, 0)[:, None])
                    & (iy[None, :] < jnp.minimum(y_hi, H)[:, None]))
            in_x = ((ix[None, :] >= jnp.maximum(x_lo, 0)[:, None])
                    & (ix[None, :] < jnp.minimum(x_hi, W)[:, None]))
            neg = jnp.finfo(jnp.float32).min
            # two cheap masked reductions instead of one [oh,ow,C,H,W]
            # broadcast: rows first -> [oh, C, W], then cols -> [oh,ow,C]
            rowmax = jnp.where(in_y[:, None, :, None], fmap[None], neg
                               ).max(2)                     # [oh, C, W]
            sel = jnp.where(in_x[None, :, None, :],
                            rowmax[:, None], neg).max(3)    # [oh, ow, C]
            # empty bins output 0 (reference roi_pool_op.cc `is_empty`)
            empty = ~(in_y.any(1)[:, None] & in_x.any(1)[None, :])
            sel = jnp.where(empty[:, :, None], 0.0, sel)
            return sel.transpose(2, 0, 1)                   # [C,oh,ow]
        out = jax.vmap(one)(batch_idx, x1, y1, bh, bw)
        return out.astype(feat.dtype)
    return apply_op("roi_pool", impl, (x, boxes, boxes_num), {})


# ---------------------------------------------------------------------------
# segment / tree ops
# ---------------------------------------------------------------------------

def _segment(name, reducer):
    def op(data, segment_ids, num_segments=None, name=None):
        if num_segments is None:
            # XLA needs a static segment count; derive it only from a
            # concrete eager ids array — placeholders/tracers would bake
            # a wrong count silently
            from ..static.program import Variable
            if isinstance(segment_ids, Variable):
                raise ValueError(
                    f"segment_{name}: pass num_segments explicitly in "
                    "static mode (the count cannot be derived from a "
                    "placeholder)")
            try:
                ids_np = np.asarray(_val(segment_ids))
            except Exception as e:
                raise ValueError(
                    f"segment_{name}: pass num_segments explicitly "
                    "under tracing") from e
            num_segments = int(ids_np.max()) + 1 if ids_np.size else 0
        num = int(num_segments)

        def impl(d, ids):
            return reducer(d, ids.astype(jnp.int32), num)
        return apply_op(f"segment_{name}", impl, (data, segment_ids), {})
    op.__name__ = f"segment_{name}"
    return op


def _seg_mean(d, ids, num):
    s = jax.ops.segment_sum(d, ids, num)
    cnt = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), ids, num)
    return s / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (d.ndim - 1))


segment_sum = _segment("sum", lambda d, i, n: jax.ops.segment_sum(d, i, n))
segment_mean = _segment("mean", _seg_mean)
segment_max = _segment("max", lambda d, i, n: jax.ops.segment_max(d, i, n))
segment_min = _segment("min", lambda d, i, n: jax.ops.segment_min(d, i, n))


def gather_tree(ids, parents, name=None):
    """reference `gather_tree_op.cc`: walk beam-search parent pointers
    backwards to assemble full sequences. ids/parents: [T, B, beam]."""
    def impl(idv, parv):
        T, B, W = idv.shape
        beam = jnp.broadcast_to(jnp.arange(W), (B, W))

        def step(path, t):
            out = jnp.take_along_axis(idv[t], path, axis=1)
            nxt = jnp.take_along_axis(parv[t].astype(jnp.int32), path,
                                      axis=1)
            return nxt, out
        _, outs = jax.lax.scan(step, beam, jnp.arange(T - 1, -1, -1))
        return outs[::-1]
    return apply_op("gather_tree", impl, (ids, parents), {})


# ---------------------------------------------------------------------------
# fluid-era CTR / sequence ops
# ---------------------------------------------------------------------------

def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    """reference `affine_channel_op.cc`: per-channel x*scale+bias."""
    def impl(v, s, b):
        shape = ((1, -1, 1, 1) if data_layout == "NCHW" and v.ndim == 4
                 else (1,) * (v.ndim - 1) + (-1,))
        return v * s.reshape(shape) + b.reshape(shape)
    return apply_op("affine_channel", impl, (x, scale, bias), {})


def row_conv(x, weight, name=None):
    """reference `row_conv_op.cc` (lookahead conv for streaming ASR):
    out[t] = sum_i x[t+i] @diag w[i], x [B,T,D], weight [ctx+1, D]."""
    def impl(v, w):
        ctx = w.shape[0]
        B, T, D = v.shape
        pad = jnp.concatenate([v, jnp.zeros((B, ctx - 1, D), v.dtype)], 1)
        out = jnp.zeros_like(v)
        for i in range(ctx):
            out = out + pad[:, i:i + T, :] * w[i][None, None, :]
        return out
    return apply_op("row_conv", impl, (x, weight), {})


def conv_shift(x, y, name=None):
    """reference `conv_shift_op.cc`: per-row circular convolution,
    x [B, M], y [B, N] (N odd, N <= M)."""
    def impl(xv, yv):
        B, M = xv.shape
        N = yv.shape[1]
        half = N // 2
        out = jnp.zeros_like(xv)
        for j in range(N):
            out = out + jnp.roll(xv, half - j, axis=1) * yv[:, j:j + 1]
        return out
    return apply_op("conv_shift", impl, (x, y), {})


def cvm(x, cvm_input, use_cvm=True, name=None):
    """reference `cvm_op.h` (CTR show/click feature): with use_cvm the
    first two slots of X itself become log(show+1) and
    log(click+1)-log(show+1); without, they are stripped. `cvm_input`
    only matters for the reference's gradient path (the backward writes
    the CVM values into dX's leading columns) — here autodiff mirrors the
    forward, and cvm_input is kept in the signature for parity."""
    def impl(v, c):
        if use_cvm:
            col0 = jnp.log(v[:, :1] + 1.0)
            col1 = jnp.log(v[:, 1:2] + 1.0) - col0
            return jnp.concatenate([col0, col1, v[:, 2:]], axis=1)
        return v[:, 2:]
    return apply_op("cvm", impl, (x, cvm_input), {})


def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4,
              name=None):
    """reference `data_norm_op.cc`: normalize by accumulated batch
    statistics (large-scale CTR models)."""
    def impl(v, n, s, sq):
        mean = s / n
        scale = jnp.sqrt(n / jnp.maximum(sq - n * mean * mean, epsilon))
        return (v - mean) * scale
    return apply_op("data_norm", impl,
                    (x, batch_size, batch_sum, batch_square_sum), {})


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """reference `pad_constant_like_op.cc`: pad y up to x's shape."""
    def impl(xv, yv):
        pads = [(0, xv.shape[i] - yv.shape[i]) for i in range(yv.ndim)]
        return jnp.pad(yv, pads, constant_values=pad_value)
    return apply_op("pad_constant_like", impl, (x, y), {})


def partial_concat(xs, start_index=0, length=-1, name=None):
    """reference `partial_concat_op.cc`: concat a column slice of each
    input."""
    def impl(*vals):
        stop = None if length < 0 else start_index + length
        return jnp.concatenate([v[:, start_index:stop] for v in vals], 1)
    return apply_op("partial_concat", impl, tuple(xs), {})


def partial_sum(xs, start_index=0, length=-1, name=None):
    """reference `partial_sum_op.cc`."""
    def impl(*vals):
        stop = None if length < 0 else start_index + length
        out = vals[0][:, start_index:stop]
        for v in vals[1:]:
            out = out + v[:, start_index:stop]
        return out
    return apply_op("partial_sum", impl, tuple(xs), {})


def l1_norm(x, name=None):
    """reference `l1_norm_op.cc`."""
    return apply_op("l1_norm", lambda v: jnp.abs(v).sum(), (x,), {})


def squared_l2_norm(x, name=None):
    """reference `squared_l2_norm_op.cc`."""
    return apply_op("squared_l2_norm", lambda v: (v * v).sum(), (x,), {})


def shuffle_batch(x, seed=None, name=None):
    """reference `shuffle_batch_op.cc`: random permutation of rows.

    Like F.dropout, the random key is drawn at op-build time — a static
    Program replays the recorded permutation (the framework's random ops
    share this build-time-key convention)."""
    from ..framework import random as frandom
    key = frandom.get_rng_key() if seed is None \
        else jax.random.PRNGKey(int(seed))
    perm = jax.random.permutation(key, int(x.shape[0]))

    def impl(v):
        return jnp.take(v, perm, axis=0)
    return apply_op("shuffle_batch", impl, (x,), {})


# ---------------------------------------------------------------------------
# ranking / metric-learning losses
# ---------------------------------------------------------------------------

def rank_loss(label, left, right, name=None):
    """reference `rank_loss_op.cc` (RankNet): C = log(1+e^o) - t*o."""
    def impl(t, l, r):
        o = l - r
        return jnp.logaddexp(0.0, o) - t * o
    return apply_op("rank_loss", impl, (label, left, right), {})


def bpr_loss(logit, label, name=None):
    """reference `bpr_loss_op.cc` (Bayesian Personalized Ranking):
    -mean_j log(sigmoid(logit_pos - logit_j)), j != pos."""
    def impl(lv, yv):
        B, C = lv.shape
        pos = jnp.take_along_axis(lv, yv.reshape(B, 1).astype(jnp.int32),
                                  axis=1)
        diff = pos - lv                      # [B, C]
        lsm = jax.nn.log_sigmoid(diff)
        mask = jnp.arange(C)[None, :] != yv.reshape(B, 1)
        return -(lsm * mask).sum(1, keepdims=True) / jnp.maximum(C - 1, 1)
    return apply_op("bpr_loss", impl, (logit, label), {})


def center_loss(x, label, centers, alpha=0.1, update_center=True,
                name=None):
    """reference `center_loss_op.cc`: 0.5*||x - c_y||^2; returns
    (loss [B,1], updated centers)."""
    def impl(xv, yv, cv):
        y = yv.astype(jnp.int32).reshape(-1)
        cy = jnp.take(cv, y, axis=0)
        diff = xv - cy
        loss = 0.5 * (diff * diff).sum(1, keepdims=True)
        if update_center:
            num = jax.ops.segment_sum(jnp.ones_like(y, cv.dtype), y,
                                      cv.shape[0])
            upd = jax.ops.segment_sum(diff, y, cv.shape[0])
            new_c = cv + alpha * upd / (1.0 + num)[:, None]
        else:
            new_c = cv
        return loss, new_c
    return apply_op("center_loss", impl, (x, label, centers), {})


def hinge_loss(logits, labels, name=None):
    """reference `hinge_loss_op.cc`: max(0, 1 - (2y-1)*logit)."""
    def impl(lv, yv):
        return jnp.maximum(0.0, 1.0 - (2.0 * yv - 1.0) * lv)
    return apply_op("hinge_loss", impl, (logits, labels), {})


# ---------------------------------------------------------------------------
# sequence ops
# ---------------------------------------------------------------------------

def im2sequence(x, filter_size=1, stride=1, padding=0, name=None):
    """reference `im2sequence_op.cc`: sliding windows to sequence rows
    [B*oh*ow, C*kh*kw]."""
    ks = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def impl(v):
        B, C, H, W = v.shape
        patches = jax.lax.conv_general_dilated_patches(
            v, ks, st, [(pd[0], pd[0]), (pd[1], pd[1])],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [B, C*kh*kw, oh, ow]
        Bp, CK, oh, ow = patches.shape
        return patches.transpose(0, 2, 3, 1).reshape(B * oh * ow, CK)
    return apply_op("im2sequence", impl, (x,), {})


def linear_chain_crf(emission, transition, label, length, name=None):
    """reference `linear_chain_crf_op.cc`: per-sequence negative
    log-likelihood of a linear-chain CRF (training-time counterpart of
    paddle.text.viterbi_decode).

    emission [B,T,C]; transition [C+2,C] (row0=start, row1=stop, rows
    2..=pairwise); label [B,T] int; length [B] int. Returns nll [B,1].
    """
    def impl(em, tr, yv, ln):
        em = em.astype(jnp.float32)
        tr = tr.astype(jnp.float32)
        B, T, C = em.shape
        start, stop, trans = tr[0], tr[1], tr[2:]
        y = yv.astype(jnp.int32)
        ln = ln.astype(jnp.int32).reshape(-1)
        t_idx = jnp.arange(T)
        valid = t_idx[None, :] < ln[:, None]               # [B,T]

        # gold score
        em_y = jnp.take_along_axis(em, y[:, :, None], axis=2)[..., 0]
        score = (em_y * valid).sum(1) + jnp.take(start, y[:, 0])
        pair = trans[y[:, :-1], y[:, 1:]]                  # [B,T-1]
        score = score + (pair * valid[:, 1:]).sum(1)
        last = jnp.take_along_axis(y, (ln - 1)[:, None], axis=1)[:, 0]
        score = score + jnp.take(stop, last)

        # partition function
        def step(alpha, t):
            nxt = jax.scipy.special.logsumexp(
                alpha[:, :, None] + trans[None], axis=1) + em[:, t]
            keep = valid[:, t][:, None]
            return jnp.where(keep, nxt, alpha), None
        alpha0 = start[None] + em[:, 0]
        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        logz = jax.scipy.special.logsumexp(alpha + stop[None], axis=1)
        return (logz - score)[:, None]
    return apply_op("linear_chain_crf", impl,
                    (emission, transition, label, length), {})


# ---------------------------------------------------------------------------
# distillation / detection / flow ops (round-5 batch 3)
# ---------------------------------------------------------------------------

def fsp(x, y, name=None):
    """reference `fsp_op.cc` (flow-of-solution-procedure matrix for
    distillation): [B,C1,H,W] x [B,C2,H,W] -> [B,C1,C2] / (H*W)."""
    def impl(a, b):
        H, W = a.shape[2], a.shape[3]
        return jnp.einsum("bchw,bdhw->bcd", a, b) / (H * W)
    return apply_op("fsp", impl, (x, y), {})


def cross_entropy2(input, label, ignore_index=-100, name=None):
    """reference `cross_entropy_op.cc` (cross_entropy2): -log(prob[label])
    over POST-softmax probabilities, with ignore_index rows zeroed."""
    def impl(p, y):
        yi = y.astype(jnp.int32).reshape(p.shape[0], 1)
        picked = jnp.take_along_axis(p, jnp.maximum(yi, 0), axis=-1)
        loss = -jnp.log(jnp.maximum(picked, 1e-12))
        return jnp.where(yi == ignore_index, 0.0, loss)
    return apply_op("cross_entropy2", impl, (input, label), {})


def psroi_pool(x, boxes, boxes_num, output_channels, spatial_scale,
               pooled_height, pooled_width, name=None):
    """reference `psroi_pool_op.cc` (R-FCN position-sensitive RoI
    pooling): input C = output_channels*oh*ow; bin (i,j) AVERAGES its own
    channel group."""
    oh, ow = int(pooled_height), int(pooled_width)
    oc = int(output_channels)

    def impl(feat, rois, rois_num):
        N, C, H, W = feat.shape
        R = rois.shape[0]
        batch_idx = jnp.repeat(jnp.arange(N), rois_num, axis=0,
                               total_repeat_length=R)
        r = rois * spatial_scale
        x1, y1, x2, y2 = r[:, 0], r[:, 1], r[:, 2], r[:, 3]
        bh = jnp.maximum(y2 - y1, 0.1)
        bw = jnp.maximum(x2 - x1, 0.1)
        iy = jnp.arange(H).astype(jnp.float32)
        ix = jnp.arange(W).astype(jnp.float32)

        def one(b, xx1, yy1, hh, ww):
            fmap = feat[b].astype(jnp.float32)   # [C,H,W]
            grp = fmap.reshape(oc, oh, ow, H, W)
            outs = []
            for i in range(oh):
                row = []
                for j in range(ow):
                    ylo = yy1 + i * hh / oh
                    yhi = yy1 + (i + 1) * hh / oh
                    xlo = xx1 + j * ww / ow
                    xhi = xx1 + (j + 1) * ww / ow
                    my = (iy >= jnp.floor(ylo)) & (iy < jnp.ceil(yhi))
                    mx = (ix >= jnp.floor(xlo)) & (ix < jnp.ceil(xhi))
                    m = my[:, None] & mx[None, :]
                    cnt = jnp.maximum(m.sum(), 1)
                    row.append((grp[:, i, j] * m[None]).sum((1, 2)) / cnt)
                outs.append(jnp.stack(row, -1))   # [oc, ow]
            return jnp.stack(outs, -2)            # [oc, oh, ow]
        out = jax.vmap(one)(batch_idx, x1, y1, bh, bw)
        return out.astype(feat.dtype)
    return apply_op("psroi_pool", impl, (x, boxes, boxes_num), {})


def prroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """reference `prroi_pool_op.cc` (Precise RoI Pooling: exact integral
    of the bilinearly-interpolated feature). TPU stand-in: dense bilinear
    average via roi_align with a fine sampling grid — converges to the
    same integral as the sampling density grows."""
    from ..vision.ops import roi_align
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=4, aligned=False)


def correlation(x1, x2, pad_size, kernel_size, max_displacement,
                stride1=1, stride2=1, corr_type_multiply=1, name=None):
    """reference `correlation_op.cc` (FlowNet cost volume): per-pixel dot
    products between x1 and x2 shifted over a (2d+1)^2 displacement grid
    (kernel_size=1, stride 1 fast path — the FlowNet-C configuration)."""
    d = int(max_displacement)

    def impl(a, b):
        B, C, H, W = a.shape
        maps = []
        for dy in range(-d, d + 1):
            for dx in range(-d, d + 1):
                shifted = jnp.roll(b, (dy, dx), axis=(2, 3))
                # zero out the wrapped border
                ygood = jnp.zeros((H,), bool).at[
                    max(0, dy):H + min(0, dy)].set(True)
                xgood = jnp.zeros((W,), bool).at[
                    max(0, dx):W + min(0, dx)].set(True)
                valid = ygood[:, None] & xgood[None, :]
                corr = (a * shifted).mean(1)
                maps.append(jnp.where(valid[None], corr, 0.0))
        return jnp.stack(maps, 1)     # [B, (2d+1)^2, H, W]
    return apply_op("correlation", impl, (x1, x2), {})


def nce(input, label, num_total_classes, nid_weight=None, bias=None,
        num_neg_samples=10, sampler="uniform", seed=None, name=None,
        param_attr=None, bias_attr=None):
    """reference `nce_op.cc` (noise-contrastive estimation): positive
    class + sampled negatives through a logistic loss. Weights/bias are
    created lazily if not given (param_attr/bias_attr names share them
    across calls, fluid LayerHelper-style); negatives use the framework
    PRNG (same build-time-key convention as F.dropout)."""
    from ..framework import random as frandom
    from ..static.nn import shared_parameter

    D = input.shape[-1]
    C = int(num_total_classes)
    w = nid_weight if nid_weight is not None else \
        shared_parameter([C, D], "float32", attr=param_attr)
    b = bias if bias is not None else \
        shared_parameter([C], "float32", attr=bias_attr, is_bias=True)
    key = frandom.get_rng_key() if seed is None \
        else jax.random.PRNGKey(int(seed))
    B = input.shape[0]
    neg = jax.random.randint(key, (B, int(num_neg_samples)), 0, C)

    def impl(xv, yv, wv, bv):
        y = yv.astype(jnp.int32).reshape(-1)
        pos_w = jnp.take(wv, y, axis=0)                  # [B, D]
        pos_s = (xv * pos_w).sum(-1) + jnp.take(bv, y)
        neg_w = jnp.take(wv, neg, axis=0)                # [B, S, D]
        neg_s = jnp.einsum("bd,bsd->bs", xv, neg_w) + jnp.take(bv, neg)
        loss = -jax.nn.log_sigmoid(pos_s) \
            - jax.nn.log_sigmoid(-neg_s).sum(-1)
        return loss[:, None]
    return apply_op("nce", impl, (input, label, w, b), {})


def deformable_conv(x, offset, weight, mask=None, stride=1, padding=0,
                    dilation=1, deformable_groups=1, groups=1,
                    im2col_step=1, name=None):
    """reference `deformable_conv_op.cc` (v2; v1 = mask None): sample the
    input at offset-perturbed kernel positions via bilinear interpolation,
    then contract with the kernel — built on the same bilinear gather as
    F.grid_sample."""
    if deformable_groups != 1 or groups != 1:
        raise NotImplementedError("deformable_conv: deformable_groups/"
                                  "groups > 1")
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def _bilinear(img, yy, xx):
        """img [C,H,W]; yy/xx [Ho,Wo] float -> [C,Ho,Wo] (zeros OOB)."""
        C, H, W = img.shape
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy = yy - y0
        wx = xx - x0
        out = 0.0
        for oy, wy_ in ((0, 1 - wy), (1, wy)):
            for ox, wx_ in ((0, 1 - wx), (1, wx)):
                yi = (y0 + oy).astype(jnp.int32)
                xi = (x0 + ox).astype(jnp.int32)
                ok = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
                v = img[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
                out = out + jnp.where(ok[None], v, 0.0) * (wy_ * wx_)[None]
        return out

    def impl(xv, ov, wv, *mv):
        B, C, H, W = xv.shape
        O, _, kh, kw = wv.shape
        Ho = (H + 2 * p[0] - dl[0] * (kh - 1) - 1) // s[0] + 1
        Wo = (W + 2 * p[1] - dl[1] * (kw - 1) - 1) // s[1] + 1
        base_y = jnp.arange(Ho) * s[0] - p[0]
        base_x = jnp.arange(Wo) * s[1] - p[1]

        def one(img, off, *m):
            cols = []
            for ki in range(kh):
                for kj in range(kw):
                    k = ki * kw + kj
                    dy = off[2 * k]
                    dx = off[2 * k + 1]
                    yy = base_y[:, None] + ki * dl[0] + dy[:Ho, :Wo]
                    xx = base_x[None, :] + kj * dl[1] + dx[:Ho, :Wo]
                    samp = _bilinear(img, yy, xx)        # [C,Ho,Wo]
                    if m:
                        samp = samp * m[0][k][None, :Ho, :Wo]
                    cols.append(samp)
            col = jnp.stack(cols, 1)                     # [C,kh*kw,Ho,Wo]
            return jnp.einsum("ckhw,ock->ohw",
                              col, wv.reshape(O, C, kh * kw))
        if mv:
            return jax.vmap(one)(xv, ov, mv[0])
        return jax.vmap(one)(xv, ov)

    args = (x, offset, weight) + ((mask,) if mask is not None else ())
    return apply_op("deformable_conv", impl, args, {})


__all__ += ["fsp", "cross_entropy2", "psroi_pool", "prroi_pool",
            "correlation", "nce", "deformable_conv"]


def batch_fc(input, w, bias=None, name=None):
    """reference `batch_fc_op.cc` (CTR per-slot FC): input
    [slot_num, B, in_dim] x w [slot_num, in_dim, out_dim] (+ bias
    [slot_num, out_dim]) -> [slot_num, B, out_dim]."""
    def impl(x, wv, *bv):
        out = jnp.einsum("sbi,sio->sbo", x, wv)
        if bv:
            out = out + bv[0][:, None, :]
        return out
    args = (input, w) + ((bias,) if bias is not None else ())
    return apply_op("batch_fc", impl, args, {})


def sample_logits(logits, label, num_samples, seed=None, name=None):
    """reference `sample_logits_op.cc` (sampled-softmax prep): keep the
    true-label logit and `num_samples` uniformly sampled negatives.
    Returns (sampled_logits [B, 1+S], sampled_ids [B, 1+S]) — column 0
    is the positive. Sampling uses the framework PRNG (build-time-key
    convention, like F.dropout)."""
    from ..framework import random as frandom
    C = int(logits.shape[-1])
    B = int(logits.shape[0])
    key = frandom.get_rng_key() if seed is None \
        else jax.random.PRNGKey(int(seed))
    neg = jax.random.randint(key, (B, int(num_samples)), 0, C)

    def impl(lg, yv):
        y = yv.astype(jnp.int32).reshape(B, 1)
        ids = jnp.concatenate([y, neg], axis=1)
        samp = jnp.take_along_axis(lg, ids, axis=1)
        return samp, ids
    return apply_op("sample_logits", impl, (logits, label), {})


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True, name=None):
    """reference `filter_by_instag_op.cc` (CTR): keep the rows whose tag
    set intersects `filter_tag`. ins: dense [N, D] (row i = instance i);
    ins_tag: LoDTensor of per-instance tag lists; filter_tag: 1-D ints.
    Returns (filtered rows, kept row indices, loss_weight)."""
    from .legacy import LoDTensor, _seq_offsets

    tags = np.asarray(ins_tag._value).reshape(-1).astype(int)
    offs = _seq_offsets(ins_tag) if isinstance(ins_tag, LoDTensor) \
        else list(range(len(tags) + 1))
    want = set(np.asarray(
        filter_tag._value if isinstance(filter_tag, Tensor)
        else filter_tag).reshape(-1).astype(int).tolist())
    keep = [i for i, (a, b) in enumerate(zip(offs[:-1], offs[1:]))
            if want & set(tags[a:b].tolist())]
    keep_idx = np.asarray(keep, np.int64)
    rows = np.asarray(ins._value)[keep_idx] if len(keep) else \
        np.zeros((1,) + np.asarray(ins._value).shape[1:],
                 np.asarray(ins._value).dtype)
    lw = np.ones((max(len(keep), 1), 1), np.float32) if len(keep) else \
        np.zeros((1, 1), np.float32)
    return (Tensor(jnp.asarray(rows)), Tensor(jnp.asarray(keep_idx)),
            Tensor(jnp.asarray(lw)))


__all__ += ["batch_fc", "sample_logits", "filter_by_instag"]


def var_conv_2d(input, row, col, input_channel, output_channel,
                filter_size, stride=1, w=None, name=None):
    """reference `operators/var_conv_2d_op.cc` (variable-size image conv
    over a LoD batch): each sequence i is an image flattened to
    [C_in * row_i * col_i] rows; conv2d applies per image. Host-side
    loop like the other LoD ops (XLA needs static shapes per call, and
    each image gets its own shape).

    input: LoDTensor whose level-0 offsets delimit images; row/col:
    per-image heights/widths; w: [C_out, C_in, k, k] filter (created if
    None). Returns a LoDTensor of flattened conv outputs."""
    from ..nn import functional as F
    from .legacy import LoDTensor, _seq_offsets, create_parameter

    k = filter_size if isinstance(filter_size, int) else filter_size[0]
    s = stride if isinstance(stride, int) else stride[0]
    if w is None:
        w = create_parameter([output_channel, input_channel, k, k],
                             "float32")
    offs = _seq_offsets(input)
    v = np.asarray(input._value).reshape(-1)
    rows = np.asarray(row.numpy() if isinstance(row, Tensor)
                      else row).reshape(-1).astype(int)
    cols = np.asarray(col.numpy() if isinstance(col, Tensor)
                      else col).reshape(-1).astype(int)
    outs, new_offs = [], [0]
    for i, (a, b) in enumerate(zip(offs[:-1], offs[1:])):
        img = v[a:b].reshape(1, input_channel, rows[i], cols[i])
        o = F.conv2d(Tensor(jnp.asarray(img)), w, stride=s,
                     padding=k // 2)
        flat = np.asarray(o.numpy()).reshape(-1)
        outs.append(flat)
        new_offs.append(new_offs[-1] + flat.size)
    return LoDTensor(jnp.asarray(np.concatenate(outs)), [new_offs])


def tree_conv(nodes_vector, edge_set, filter, max_depth=1, name=None):
    """reference `operators/tree_conv_op.cc` (TBCNN continuous binary
    tree convolution): for each node, aggregate its (<= max_depth)-hop
    subtree with position-interpolated filters W_t (top), W_l, W_r.

    nodes_vector [B, N, D]; edge_set [B, E, 2] (parent, child) int pairs
    (0-padded); filter [D, H, 3] holding (W_t, W_l, W_r). Returns
    [B, N, H]. The per-node receptive field is its direct children (the
    depth-1 TBCNN window, the common configuration)."""
    def impl(x, edges, f):
        B, N, D = x.shape
        wt, wl, wr = f[..., 0], f[..., 1], f[..., 2]   # [D, H]
        par = edges[..., 0].astype(jnp.int32)          # [B, E]
        chi = edges[..., 1].astype(jnp.int32)
        valid = (par != chi)                           # padding: (0,0)

        # children per parent: counts + left-to-right position
        onehot = (jnp.arange(N)[None, :, None] == par[:, None, :]) \
            & valid[:, None, :]                        # [B, N, E]
        n_child = onehot.sum(-1)                       # [B, N]
        order = jnp.cumsum(onehot, axis=-1) * onehot   # 1-based position
        # eta_l/eta_r per TBCNN: position interpolation in [0, 1]
        denom = jnp.maximum(n_child[:, :, None] - 1, 1)
        eta_r = (order - 1) / denom * onehot
        eta_l = (1 - (order - 1) / denom) * onehot

        child_vec = jnp.take_along_axis(
            x, chi[:, :, None].repeat(D, -1), axis=1)  # [B, E, D]
        top = jnp.einsum("bnd,dh->bnh", x, wt)
        left = jnp.einsum("bne,bed,dh->bnh", eta_l, child_vec, wl)
        right = jnp.einsum("bne,bed,dh->bnh", eta_r, child_vec, wr)
        return jnp.tanh(top + left + right)
    return apply_op("tree_conv", impl,
                    (nodes_vector, edge_set, filter), {})


__all__ += ["var_conv_2d", "tree_conv"]


def bilateral_slice(x, guide, grid, has_offset=True, name=None):
    """reference `operators/bilateral_slice_op.cc` (HDRNet): slice a
    bilateral grid of affine coefficients at (x, y, guide) with
    trilinear interpolation and apply the per-pixel affine transform.

    x [N, Ci, H, W]; guide [N, H, W] in [0,1]; grid
    [N, Co*(Ci+1), Gd, Gh, Gw] when has_offset (affine + bias), else
    [N, Co*Ci, ...]. Returns [N, Co, H, W]."""
    def impl(xv, gv, grid_v):
        N, Ci, H, W = xv.shape
        _, CC, Gd, Gh, Gw = grid_v.shape
        cols = Ci + 1 if has_offset else Ci
        if CC % cols != 0:
            raise ValueError(
                f"bilateral_slice: grid channels {CC} not divisible by "
                f"{cols} (= input channels{' + offset' if has_offset else ''})"
                " — check has_offset / grid layout")
        Co = CC // cols

        gx = (jnp.arange(W, dtype=jnp.float32) + 0.5) * Gw / W - 0.5
        gy = (jnp.arange(H, dtype=jnp.float32) + 0.5) * Gh / H - 0.5
        gxb = jnp.broadcast_to(gx[None, :], (H, W))
        gyb = jnp.broadcast_to(gy[:, None], (H, W))

        def one(img, guide1, g1):
            gz = jnp.clip(guide1, 0.0, 1.0) * Gd - 0.5       # [H,W]
            x0 = jnp.floor(gxb)
            y0 = jnp.floor(gyb)
            z0 = jnp.floor(gz)
            wx = gxb - x0
            wy = gyb - y0
            wz = gz - z0
            coef = jnp.zeros((CC, H, W), jnp.float32)
            for dz, wz_ in ((0, 1 - wz), (1, wz)):
                for dy, wy_ in ((0, 1 - wy), (1, wy)):
                    for dx, wx_ in ((0, 1 - wx), (1, wx)):
                        zi = jnp.clip(z0 + dz, 0, Gd - 1).astype(jnp.int32)
                        yi = jnp.clip(y0 + dy, 0, Gh - 1).astype(jnp.int32)
                        xi = jnp.clip(x0 + dx, 0, Gw - 1).astype(jnp.int32)
                        corner = g1[:, zi, yi, xi]           # [CC,H,W]
                        coef = coef + corner * (wz_ * wy_ * wx_)[None]
            coef = coef.reshape(Co, cols, H, W)
            out = jnp.einsum("ochw,chw->ohw", coef[:, :Ci],
                             img.astype(jnp.float32))
            if has_offset:
                out = out + coef[:, Ci]
            return out
        return jax.vmap(one)(xv, gv,
                             grid_v.astype(jnp.float32)).astype(xv.dtype)
    return apply_op("bilateral_slice", impl, (x, guide, grid), {})


__all__ += ["bilateral_slice"]


def rank_attention(input, rank_offset, rank_param, max_rank=3,
                   param_attr=None, name=None):
    """reference `operators/rank_attention_op.cc` +
    `rank_attention.cu.h` (CTR rank-feature attention):

    X [ins, D]; RankOffset [ins, 2*max_rank+1] ints — col 0 is this
    instance's 1-based rank (0 = absent), then (rank_k, row_index_k)
    pairs for up to max_rank related instances. RankParam holds a
    [D, para_col] block per (my_rank, other_rank) combination, laid out
    as [max_rank^2 * D, para_col]. Out[i] = concat_k X[index_k] @
    block[(my_rank-1)*max_rank + (rank_k-1)], with absent entries
    contributing zero — exactly the expand_input/expand_param kernels'
    gather semantics. rank_param may be a Tensor or created lazily
    ([max_rank^2*D, para_col] via param_attr when given a shape tuple).
    """
    if isinstance(rank_param, (tuple, list)):
        from ..static.nn import shared_parameter
        rank_param = shared_parameter(list(rank_param), "float32",
                                      attr=param_attr)
    # reference InferShape PADDLE_ENFORCEs these; the clip below would
    # otherwise silently read the wrong parameter block
    off_cols = int(rank_offset.shape[1])
    if off_cols != 2 * max_rank + 1:
        raise ValueError(
            f"rank_attention: RankOffset has {off_cols} columns, "
            f"expected 2*max_rank+1 = {2 * max_rank + 1}")
    D_in = int(input.shape[1])
    p_rows = int(rank_param.shape[0])
    if p_rows != max_rank * max_rank * D_in:
        raise ValueError(
            f"rank_attention: RankParam has {p_rows} rows, expected "
            f"max_rank^2 * input_dim = {max_rank * max_rank * D_in}")

    def impl(x, off, p):
        ins, D = x.shape
        para_col = p.shape[1]
        off = off.astype(jnp.int32)
        my = off[:, 0] - 1                       # [ins]
        ranks = off[:, 1::2] - 1                 # [ins, K]
        idxs = off[:, 2::2]                      # [ins, K]
        valid = (my[:, None] >= 0) & (ranks >= 0)
        gathered = jnp.take(x, jnp.clip(idxs, 0, ins - 1), axis=0)
        input_help = jnp.where(valid[..., None], gathered, 0.0)
        start = jnp.clip(my[:, None] * max_rank + ranks, 0,
                         max_rank * max_rank - 1)
        pb = p.reshape(max_rank * max_rank, D, para_col)
        param_help = jnp.where(valid[..., None, None],
                               jnp.take(pb, start, axis=0), 0.0)
        return jnp.einsum("ikd,ikdp->ip", input_help, param_help)
    return apply_op("rank_attention", impl,
                    (input, rank_offset, rank_param), {})


__all__ += ["rank_attention"]
