"""Math ops (reference `python/paddle/tensor/math.py`; kernels in
`paddle/fluid/operators/elementwise/`, `activation_op.*`). All lower to XLA
elementwise HLO — fusion is the compiler's job (no hand-written CUDA)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from ..framework.tensor import Tensor, apply_op

__all__ = []


def _raw(x):
    return x._value if isinstance(x, Tensor) else x


def _unary(name, fn):
    def op(x, name=None):
        return apply_op(name, fn, (x,), {})
    op.__name__ = name
    globals()[name] = op
    __all__.append(name)
    return op


def _binary(name, fn):
    def op(x, y, name=None):
        return apply_op(name, fn, (x, y), {})
    op.__name__ = name
    globals()[name] = op
    __all__.append(name)
    return op


_unary("exp", jnp.exp)
_unary("expm1", jnp.expm1)
_unary("log", jnp.log)
_unary("log2", jnp.log2)
_unary("log10", jnp.log10)
_unary("log1p", jnp.log1p)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", jax.lax.rsqrt)
_unary("square", jnp.square)
_unary("abs", jnp.abs)
_unary("neg", jnp.negative)
_unary("sign", jnp.sign)
_unary("floor", jnp.floor)
_unary("ceil", jnp.ceil)
_unary("round", jnp.round)
_unary("trunc", jnp.trunc)
_unary("frac", lambda v: v - jnp.trunc(v))
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("asin", jnp.arcsin)
_unary("acos", jnp.arccos)
_unary("atan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("asinh", jnp.arcsinh)
_unary("acosh", jnp.arccosh)
_unary("atanh", jnp.arctanh)
_unary("reciprocal", jnp.reciprocal)
_unary("erf", jax.lax.erf)
_unary("erfinv", jax.lax.erf_inv)
_unary("sigmoid", jax.nn.sigmoid)
_unary("digamma", jax.scipy.special.digamma)
_unary("lgamma", jax.scipy.special.gammaln)
_unary("angle", jnp.angle)
_unary("conj", jnp.conj)
_unary("real", jnp.real)
_unary("imag", jnp.imag)

_binary("add", jnp.add)
_binary("subtract", jnp.subtract)
_binary("multiply", jnp.multiply)
_binary("divide", jnp.divide)
_binary("floor_divide", jnp.floor_divide)
_binary("mod", jnp.mod)
_binary("remainder", jnp.mod)
_binary("floor_mod", jnp.mod)
_binary("pow_", jnp.power)
_binary("maximum", jnp.maximum)
_binary("minimum", jnp.minimum)
_binary("fmax", jnp.fmax)
_binary("fmin", jnp.fmin)
_binary("atan2", jnp.arctan2)
_binary("logaddexp", jnp.logaddexp)
_binary("heaviside", jnp.heaviside)
_binary("kron", jnp.kron)
_binary("outer", jnp.outer)
_binary("inner", jnp.inner)
_binary("gcd", lambda a, b: jnp.gcd(a, b))
_binary("lcm", lambda a, b: jnp.lcm(a, b))


def pow(x, y, name=None):
    return apply_op("pow", jnp.power, (x, y), {})


__all__.append("pow")


def elementwise_add(x, y, axis=-1, name=None):
    return add(x, y)


def elementwise_mul(x, y, axis=-1, name=None):
    return multiply(x, y)


def elementwise_sub(x, y, axis=-1, name=None):
    return subtract(x, y)


def elementwise_div(x, y, axis=-1, name=None):
    return divide(x, y)


__all__ += ["elementwise_add", "elementwise_mul", "elementwise_sub",
            "elementwise_div"]


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """reference `operators/scale_op.cc`."""
    def impl(v, s, b):
        out = v * s + b if bias_after_scale else (v + b) * s
        return out
    s = _raw(scale) if isinstance(scale, Tensor) else scale
    return apply_op("scale", lambda v: impl(v, s, bias), (x,), {})


def clip(x, min=None, max=None, name=None):
    mn = _raw(min) if isinstance(min, Tensor) else min
    mx = _raw(max) if isinstance(max, Tensor) else max
    return apply_op("clip", lambda v: jnp.clip(v, mn, mx), (x,), {})


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply_op("lerp", lambda a, b, w: a + w * (b - a),
                        (x, y, weight), {})
    return apply_op("lerp", lambda a, b: a + weight * (b - a), (x, y), {})


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), (x,), {})


def logit(x, eps=None, name=None):
    def impl(v):
        u = v if eps is None else jnp.clip(v, eps, 1 - eps)
        return jnp.log(u / (1 - u))
    return apply_op("logit", impl, (x,), {})


def multiplex(inputs, index, name=None):
    def impl(idx, *xs):
        stacked = jnp.stack(xs, 0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0)[0]
    return apply_op("multiplex", lambda *xs: impl(xs[-1], *xs[:-1]),
                    (*inputs, index), {})


def cumsum(x, axis=None, dtype=None, name=None):
    dt = None if dtype is None else to_jax_dtype(dtype)
    return apply_op("cumsum", lambda v: jnp.cumsum(v, axis=axis, dtype=dt),
                    (x,), {})


def cumprod(x, dim=None, dtype=None, name=None):
    dt = None if dtype is None else to_jax_dtype(dtype)
    return apply_op("cumprod", lambda v: jnp.cumprod(v, axis=dim, dtype=dt),
                    (x,), {})


def cummax(x, axis=None, dtype="int64", name=None):
    def impl(v):
        a = 0 if axis is None else axis
        vv = v.reshape(-1) if axis is None else v
        vals = jax.lax.associative_scan(jnp.maximum, vv, axis=a)
        return vals
    return apply_op("cummax", impl, (x,), {})


def isnan(x, name=None):
    return apply_op("isnan", jnp.isnan, (x,), {})


def isinf(x, name=None):
    return apply_op("isinf", jnp.isinf, (x,), {})


def isfinite(x, name=None):
    return apply_op("isfinite", jnp.isfinite, (x,), {})


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op("nan_to_num",
                    lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                             neginf=neginf), (x,), {})


def increment(x, value=1.0, name=None):
    out = apply_op("increment", lambda v: v + value, (x,), {})
    x.set_value(out._value)
    return x


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op("addmm",
                    lambda i, a, b: beta * i + alpha * (a @ b),
                    (input, x, y), {})


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace",
                    lambda v: jnp.trace(v, offset=offset, axis1=axis1,
                                        axis2=axis2), (x,), {})


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return apply_op("diff", lambda v: jnp.diff(v, n=n, axis=axis), (x,), {})


__all__ += ["scale", "clip", "lerp", "stanh", "logit", "multiplex", "cumsum",
            "cumprod", "cummax", "isnan", "isinf", "isfinite", "nan_to_num",
            "increment", "addmm", "trace", "diff"]


def renorm(x, p, axis, max_norm, name=None):
    """Per-slice p-norm clamp along `axis` (reference
    `operators/renorm_op.cc`): slices whose p-norm exceeds max_norm are
    rescaled to exactly max_norm."""
    def impl(v):
        ax = axis if axis >= 0 else v.ndim + axis
        red = tuple(i for i in range(v.ndim) if i != ax)
        norms = jnp.sum(jnp.abs(v) ** p, axis=red, keepdims=True) \
            ** (1.0 / p)
        factor = jnp.where(norms > max_norm,
                           max_norm / (norms + 1e-7), 1.0)
        return v * factor
    return apply_op("renorm", impl, (x,), {})


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Numerically-stable cumulative logsumexp (reference
    `operators/cum_op.h` LogcumsumexpKernel): running max + rescaled
    cumsum through lax.associative_scan (parallel on TPU, not a serial
    loop)."""
    def impl(v):
        if dtype is not None:
            v = v.astype(to_jax_dtype(dtype))
        ax = axis
        if ax is None:
            v = v.reshape(-1)
            ax = 0
        elif ax < 0:
            ax = v.ndim + ax

        def combine(a, b):
            am, al = a
            bm, bl = b
            m = jnp.maximum(am, bm)
            return m, jnp.log(jnp.exp(al + am - m) +
                              jnp.exp(bl + bm - m))
        m, l = jax.lax.associative_scan(
            combine, (v, jnp.zeros_like(v)), axis=ax)
        return m + l
    return apply_op("logcumsumexp", impl, (x,), {})


__all__ += ["renorm", "logcumsumexp"]


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """reference `paddle.trapezoid` (operators/... trapezoidal rule)."""
    if x is not None:
        return apply_op("trapezoid",
                        lambda yv, xv: jnp.trapezoid(yv, xv, axis=axis),
                        (y, x), {})
    dx_ = 1.0 if dx is None else dx
    return apply_op("trapezoid",
                    lambda yv: jnp.trapezoid(yv, dx=dx_, axis=axis),
                    (y,), {})


def hypot(x, y, name=None):
    return apply_op("hypot", jnp.hypot, (x, y), {})


def copysign(x, y, name=None):
    if not hasattr(y, "shape"):
        y = Tensor(jnp.asarray(y, "float32"))
    return apply_op("copysign", jnp.copysign, (x, y), {})


def ldexp(x, y, name=None):
    return apply_op("ldexp",
                    lambda a, b: a * (2.0 ** b.astype(a.dtype)), (x, y), {})


def polar(abs, angle, name=None):
    return apply_op(
        "polar",
        lambda r, t: (r * jnp.cos(t) + 1j * r * jnp.sin(t)).astype(
            "complex64"), (abs, angle), {})


def sgn(x, name=None):
    def impl(v):
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0.0 + 0.0j, v / mag)
        return jnp.sign(v)
    return apply_op("sgn", impl, (x,), {})


def sinc(x, name=None):
    return apply_op("sinc", jnp.sinc, (x,), {})


def i0(x, name=None):
    return apply_op("i0", lambda v: jax.scipy.special.i0(v), (x,), {})


def i0e(x, name=None):
    return apply_op("i0e", lambda v: jax.scipy.special.i0e(v), (x,), {})


def i1(x, name=None):
    return apply_op("i1", lambda v: jax.scipy.special.i1(v), (x,), {})


def i1e(x, name=None):
    return apply_op("i1e", lambda v: jax.scipy.special.i1e(v), (x,), {})


def gammaln(x, name=None):
    return apply_op("gammaln", jax.scipy.special.gammaln, (x,), {})


def gammainc(x, y, name=None):
    return apply_op("gammainc", jax.scipy.special.gammainc, (x, y), {})


def nextafter(x, y, name=None):
    return apply_op("nextafter", jnp.nextafter, (x, y), {})


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op(
        "nanquantile",
        lambda v: jnp.nanquantile(v, q, axis=axis, keepdims=keepdim),
        (x,), {})


def frexp(x, name=None):
    def impl(v):
        m, e = jnp.frexp(v)
        return m, e.astype("int32")
    return apply_op("frexp", impl, (x,), {})


__all__ += ["trapezoid", "hypot", "copysign", "ldexp", "polar", "sgn",
            "sinc", "i0", "i0e", "i1", "i1e", "gammaln", "gammainc",
            "nextafter", "nanquantile", "frexp"]
