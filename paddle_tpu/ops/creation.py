"""Creation ops (reference `python/paddle/tensor/creation.py`)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import to_jax_dtype
from ..framework.tensor import Tensor, to_tensor

__all__ = [
    "to_tensor", "ones", "zeros", "full", "ones_like", "zeros_like",
    "full_like", "arange", "linspace", "logspace", "eye", "empty",
    "empty_like", "meshgrid", "diag", "diagflat", "tril", "triu", "assign",
    "clone", "numel", "tril_indices", "triu_indices",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(x) for x in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(x) for x in shape)


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), to_jax_dtype(dtype)))


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), to_jax_dtype(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        return Tensor(jnp.full(_shape(shape), fill_value))
    return Tensor(jnp.full(_shape(shape), fill_value, to_jax_dtype(dtype)))


def _like(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def ones_like(x, dtype=None, name=None):
    v = jnp.ones_like(_like(x))
    return Tensor(v if dtype is None else v.astype(to_jax_dtype(dtype)))


def zeros_like(x, dtype=None, name=None):
    v = jnp.zeros_like(_like(x))
    return Tensor(v if dtype is None else v.astype(to_jax_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    v = jnp.full_like(_like(x), fill_value)
    return Tensor(v if dtype is None else v.astype(to_jax_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    dt = None if dtype is None else to_jax_dtype(dtype)
    if dt is None and all(isinstance(v, (int, np.integer))
                          for v in (start, end, step)):
        dt = jnp.int64 if False else jnp.dtype("int64")
    return Tensor(jnp.arange(start, end, step, dt))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=to_jax_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=to_jax_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows),
                          None if num_columns is None else int(num_columns),
                          dtype=to_jax_dtype(dtype)))


def meshgrid(*args, **kwargs):
    arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
            for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(v) for v in jnp.meshgrid(*arrs, indexing="ij")]


from ..framework.tensor import apply_op


def diag(x, offset=0, padding_value=0, name=None):
    def impl(v):
        if v.ndim == 1 and padding_value != 0:
            n = v.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, v.dtype)
            return base + jnp.diag(v, k=offset) - jnp.diag(
                jnp.full((v.shape[0],), padding_value, v.dtype), k=offset)
        return jnp.diag(v, k=offset)
    return apply_op("diag", impl, (x,), {})


def diagflat(x, offset=0, name=None):
    return apply_op("diagflat", lambda v: jnp.diagflat(v, k=offset), (x,), {})


def tril(x, diagonal=0, name=None):
    return apply_op("tril", lambda v: jnp.tril(v, k=diagonal), (x,), {})


def triu(x, diagonal=0, name=None):
    return apply_op("triu", lambda v: jnp.triu(v, k=diagonal), (x,), {})


def tril_indices(row, col=None, offset=0, dtype="int64"):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


def assign(x, output=None):
    if output is not None:
        v = x._value if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
        output.set_value(v)
        return output
    if isinstance(x, Tensor):
        # grad op of assign is identity (reference assign_op grad maker)
        from ..framework.tensor import apply_op
        return apply_op("assign", lambda v: v, (x,), {})
    return Tensor(jnp.asarray(np.asarray(x)))


def clone(x, name=None):
    return apply_op("clone", lambda v: v + jnp.zeros_like(v), (x,), {})


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) if x.shape else 1,
                              dtype="int64"))


def vander(x, n=None, increasing=False, name=None):
    """Vandermonde matrix (reference `paddle.vander`)."""
    def impl(v):
        cols = v.shape[0] if n is None else int(n)
        powers = jnp.arange(cols)
        if not increasing:
            powers = powers[::-1]
        return v[:, None] ** powers[None, :].astype(v.dtype)
    return apply_op("vander", impl, (x,), {})


__all__.append("vander")
