"""Random ops (reference `python/paddle/tensor/random.py`,
`operators/gaussian_random_op` etc). Keys come from the PRNG scope stack
(`framework/random.py`): stateful UX eagerly, trace-safe under capture."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from ..framework.random import get_rng_key
from ..framework.tensor import Tensor, apply_op

__all__ = ["rand", "randn", "randint", "randint_like", "uniform", "normal",
           "standard_normal", "bernoulli", "multinomial", "randperm",
           "poisson", "uniform_", "normal_", "shuffle"]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(x) for x in shape.tolist())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s.item() if isinstance(s, Tensor) else s) for s in shape)


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(get_rng_key(), _shape(shape),
                                     to_jax_dtype(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(get_rng_key(), _shape(shape),
                                    to_jax_dtype(dtype)))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(get_rng_key(), _shape(shape), low, high,
                                     to_jax_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = x._value.dtype if dtype is None else to_jax_dtype(dtype)
    return Tensor(jax.random.randint(get_rng_key(), x._value.shape, low, high,
                                     dt))


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else get_rng_key()
    return Tensor(jax.random.uniform(key, _shape(shape), to_jax_dtype(dtype),
                                     minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(m + s * jax.random.normal(get_rng_key(), shp))
    shp = _shape(shape) if shape is not None else ()
    return Tensor(mean + std * jax.random.normal(get_rng_key(), shp))


def bernoulli(x, name=None):
    key = get_rng_key()
    return apply_op("bernoulli",
                    lambda v: jax.random.bernoulli(key, v).astype(v.dtype),
                    (x,), {})


def poisson(x, name=None):
    key = get_rng_key()
    return apply_op("poisson",
                    lambda v: jax.random.poisson(key, v).astype(v.dtype),
                    (x,), {})


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = get_rng_key()

    def impl(v):
        logits = jnp.log(jnp.clip(v, 1e-30, None))
        if replacement:
            return jax.random.categorical(
                key, logits, axis=-1,
                shape=(*v.shape[:-1], num_samples)).astype("int64")
        # without replacement: gumbel top-k trick
        g = jax.random.gumbel(key, v.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype("int64")
    return apply_op("multinomial", impl, (x,), {})


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(get_rng_key(),
                                         n).astype(to_jax_dtype(dtype)))


def shuffle(x, axis=0, name=None):
    key = get_rng_key()
    return apply_op("shuffle",
                    lambda v: jax.random.permutation(key, v, axis=axis,
                                                     independent=False),
                    (x,), {})


# in-place variants (dygraph convenience)
def uniform_(x, min=-1.0, max=1.0, name=None):
    x.set_value(jax.random.uniform(get_rng_key(), x._value.shape,
                                   x._value.dtype, minval=min, maxval=max))
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x.set_value(mean + std * jax.random.normal(get_rng_key(), x._value.shape,
                                               x._value.dtype))
    return x
