"""Pallas TPU splash attention: segment-aware flash attention for packed
sequences.

Sequence packing (io/packing.py) concatenates short sequences into one
fixed-shape row; attention must then be masked PER SEGMENT so packed
neighbours never attend to each other. This module is the kernel layer of
that pipeline — the flash kernels of pallas_ops.py extended with
segment-id-driven masking plus the property that gives splash attention
its name: kv blocks entirely outside a q block's segment span are
SKIPPED, not just masked, so attention FLOPs track real tokens instead of
the padded row shape (in the spirit of
`jax.experimental.pallas.ops.tpu.splash_attention`'s `SegmentIds` —
SNIPPETS.md [1][2] — but sharing pallas_ops' layout, stats and
interpret-mode test story).

Design:
  * masking: attend iff q_seg == kv_seg, AND q_pos >= k_pos when causal
    ("causal within segment" — positions are global row offsets, so the
    plain causal predicate composes with the segment predicate).
  * block skipping: segment ids are CONTRACTUALLY non-decreasing along
    each row (the packing layout). The host wrapper then computes, per
    (batch, q block), the kv-index span [searchsorted(kv_seg, first_q_seg,
    left), searchsorted(kv_seg, last_q_seg, right)) with jnp reductions,
    rounds it to kv blocks, and ships the bounds into SMEM; the kernel's
    fori_loop runs only those blocks (the backward dkv kernel gets the
    transposed bounds over q blocks). Non-monotonic ids would make the
    skip DROP attention silently — the dispatch layer only builds ids via
    the packing collator, and splash_attention validates concrete inputs.
  * degenerate rows: a row whose segment has no visible key anywhere
    (cannot happen in the packing layout — causal keeps the diagonal and
    a token is its own key) outputs ZEROS, and the dense reference below
    mirrors that, unlike a -1e30 softmax which would emit a uniform mix.
  * forward/backward structure, dropout replay, f32 softmax stats, and
    the O(S·D) recompute backward are pallas_ops' — see its docstring.

Tile sizes ride the same FLAGS_flash_block_q / FLAGS_flash_block_kv knobs
as the flash kernel (tools/perf_splash_sweep.py re-runs the sweep for
this path; the prior 512/512 flash result is the default).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .pallas_ops import (_BLOCK_MIN, _NEG_INF, _HAS_PALLAS, _KernelStats,
                         _dropout_bits, _interpret, _pick_blocks,
                         _smem_scalar_spec)

if _HAS_PALLAS:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

__all__ = ["splash_attention", "splash_attention_raw", "splash_supported",
           "sdpa_segment_reference", "STATS"]


class _SplashStats(_KernelStats):
    _keys = {"splash_fwd": "STAT_splash_attention_fwd",
             "splash_bwd": "STAT_splash_attention_bwd"}


STATS = _SplashStats()


def sdpa_segment_reference(q, k, v, q_seg, kv_seg, causal, scale):
    """Dense reference with the kernel's exact segment semantics — the
    _sdpa_reference extension the interpret-mode parity tests check the
    kernels against. q/k/v: [B,H,S,D]; q_seg/kv_seg: [B,S] int.

    KEEP IN SYNC with the production dense fallback
    (nn/functional/attention.py `_sdpa_ref` with `seg=`): same
    segment-equality mask, same causal AND, same zero-output rule for
    fully-masked rows. This f32 copy exists so kernel parity tests
    don't depend on the functional layer's dtype/dropout plumbing."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    allowed = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        allowed = jnp.logical_and(
            allowed, jnp.tril(jnp.ones((Sq, Sk), bool))[None, None])
    s = jnp.where(allowed, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    # fully-masked rows emit zeros (kernel semantics), not a uniform mix
    out = jnp.where(jnp.any(allowed, axis=-1)[..., None], out, 0.0)
    return out.astype(q.dtype)


def _block_bounds(q_seg, kv_seg, block_q, block_k, causal):
    """Per-block loop bounds that realize the splash skip.

    Returns int32 arrays
      kv_lo, kv_hi [B, n_q_blocks] — kv-block range each q block visits
      q_lo,  q_hi  [B, n_kv_blocks] — q-block range each kv block visits
    computed from the non-decreasing segment ids: a q block spanning
    segments [s_first, s_last] can only see kv indices inside
    [first kv of s_first, last kv of s_last] — everything outside is
    masked by construction, so it is never loaded. Causal additionally
    caps at the diagonal exactly like the flash kernels."""
    B, Sq = q_seg.shape
    Sk = kv_seg.shape[1]
    nqb, nkb = Sq // block_q, Sk // block_k
    ss_l = jax.vmap(functools.partial(jnp.searchsorted, side="left"))
    ss_r = jax.vmap(functools.partial(jnp.searchsorted, side="right"))

    kv_lo = ss_l(kv_seg, q_seg[:, ::block_q]) // block_k
    kv_hi = -(-ss_r(kv_seg, q_seg[:, block_q - 1::block_q]) // block_k)
    if causal:
        cap = (jnp.arange(1, nqb + 1) * block_q
               + block_k - 1) // block_k          # flash's causal bound
        kv_hi = jnp.minimum(kv_hi, cap[None, :])
    kv_hi = jnp.maximum(kv_hi, kv_lo)             # empty span, not negative

    q_lo = ss_l(q_seg, kv_seg[:, ::block_k]) // block_q
    if causal:
        floor = (jnp.arange(nkb) * block_k) // block_q
        q_lo = jnp.maximum(q_lo, floor[None, :])
    q_hi = -(-ss_r(q_seg, kv_seg[:, block_k - 1::block_k]) // block_q)
    q_hi = jnp.maximum(q_hi, q_lo)
    return (kv_lo.astype(jnp.int32), kv_hi.astype(jnp.int32),
            q_lo.astype(jnp.int32), q_hi.astype(jnp.int32))


def _seg_mask(qseg, kseg, q_offs, k_offs, causal):
    allowed = qseg == kseg
    if causal:
        allowed = jnp.logical_and(allowed, q_offs >= k_offs)
    return allowed


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, lo_ref, hi_ref, q_ref, k_ref, v_ref, qs_ref,
                ks_ref, o_ref, lse_ref, *, scale, causal, block_k,
                dropout_p):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[:]
    S, D = k_ref.shape
    bq = q_ref.shape[0]
    qseg = qs_ref[:]                      # [bq, 1] int32
    q_offs = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    seed = seed_ref[0, 0]

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        kseg = ks_ref[0, pl.ds(kb * block_k, block_k)][None, :]   # [1, bk]
        k_offs = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        allowed = _seg_mask(qseg, kseg, q_offs, k_offs, causal)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
                                precision=jax.lax.Precision.DEFAULT) * scale
        s = jnp.where(allowed, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        # where, not exp alone: an all-masked row keeps p = 0 (l stays 0
        # -> zero output) instead of exp(-1e30 - -1e30) = 1 garbage
        p = jnp.where(allowed, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        if dropout_p > 0.0:
            keep = _dropout_bits(seed, bh, qi, kb, p.shape, dropout_p)
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        acc_new = alpha * acc + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(lo_ref[0, 0], hi_ref[0, 0], body,
                                  (m0, l0, acc0))
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# backward: dQ over q blocks, dK/dV over kv blocks (probability recompute)
# ---------------------------------------------------------------------------

def _recompute_p(q, k_blk, allowed, lse, scale):
    s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.DEFAULT) * scale
    # masked entries are zeroed OUTSIDE the exp so a degenerate row's
    # lse (= -1e30) cannot resurrect them as exp(0) = 1
    return jnp.where(allowed, jnp.exp(s - lse), 0.0)


def _dq_kernel(seed_ref, lo_ref, hi_ref, q_ref, k_ref, v_ref, qs_ref,
               ks_ref, do_ref, lse_ref, dl_ref, dq_ref, *, scale, causal,
               block_k, dropout_p):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[:]
    delta = dl_ref[:]
    S, D = k_ref.shape
    bq = q_ref.shape[0]
    qseg = qs_ref[:]
    q_offs = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    seed = seed_ref[0, 0]
    inv_keep = 1.0 / (1.0 - dropout_p) if dropout_p > 0.0 else 1.0

    def body(kb, dq):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        kseg = ks_ref[0, pl.ds(kb * block_k, block_k)][None, :]
        k_offs = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        allowed = _seg_mask(qseg, kseg, q_offs, k_offs, causal)
        p = _recompute_p(q, k_blk, allowed, lse, scale)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=jax.lax.Precision.DEFAULT)
        if dropout_p > 0.0:
            keep = _dropout_bits(seed, bh, qi, kb, p.shape, dropout_p)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)

    dq0 = jnp.zeros((bq, D), jnp.float32)
    dq = jax.lax.fori_loop(lo_ref[0, 0], hi_ref[0, 0], body, dq0)
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, lo_ref, hi_ref, q_ref, k_ref, v_ref, qs_ref,
                ks_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref, *, scale,
                causal, block_q, dropout_p):
    bh = pl.program_id(0)
    kb = pl.program_id(1)
    k_blk = k_ref[:]                        # [bk, D]
    v_blk = v_ref[:]
    S, D = q_ref.shape
    bk = k_ref.shape[0]
    kseg = ks_ref[:]                        # [1, bk] (kv-block slice)
    k_offs = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    seed = seed_ref[0, 0]
    inv_keep = 1.0 / (1.0 - dropout_p) if dropout_p > 0.0 else 1.0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qi * block_q, block_q), :]
        do = do_ref[pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[pl.ds(qi * block_q, block_q), :]
        delta = dl_ref[pl.ds(qi * block_q, block_q), :]
        qseg = qs_ref[pl.ds(qi * block_q, block_q), :]
        q_offs = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        allowed = _seg_mask(qseg, kseg, q_offs, k_offs, causal)
        p = _recompute_p(q, k_blk, allowed, lse, scale)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
                                 precision=jax.lax.Precision.DEFAULT)
        if dropout_p > 0.0:
            keep = _dropout_bits(seed, bh, qi, kb, p.shape, dropout_p)
            pd = jnp.where(keep, p * inv_keep, 0.0)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        else:
            pd = p
        ds = p * (dp - delta)
        dv = dv + jax.lax.dot_general(pd.astype(do.dtype), do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=jax.lax.Precision.DEFAULT)
        dk = dk + jax.lax.dot_general(ds.astype(q.dtype), q,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32,
                                      precision=jax.lax.Precision.DEFAULT)
        return dk, dv

    dk0 = jnp.zeros((bk, D), jnp.float32)
    dv0 = jnp.zeros((bk, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo_ref[0, 0], hi_ref[0, 0], body,
                               (dk0, dv0))
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers
# ---------------------------------------------------------------------------

def _smem_block_spec(H):
    """One int32 per (batch, block) grid cell, indexed off the fused
    batch*heads grid axis."""
    return pl.BlockSpec((1, 1), lambda b, i: (b // H, i),
                        memory_space=pltpu.SMEM)


def _prep(q, k, v, q_seg, kv_seg):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)
    qs3 = q_seg.astype(jnp.int32).reshape(B, Sq, 1)   # [bq,1] kernel slices
    ks3 = kv_seg.astype(jnp.int32).reshape(B, 1, Sk)  # [1,bk] kernel slices
    return (B, H, Sq, Sk, D), qr, kr, vr, qs3, ks3


def _splash_call(q, k, v, q_seg, kv_seg, seed, causal, scale, dropout_p,
                 block_q, block_k):
    (B, H, Sq, Sk, D), qr, kr, vr, qs3, ks3 = _prep(q, k, v, q_seg, kv_seg)
    kv_lo, kv_hi, _, _ = _block_bounds(q_seg, kv_seg, block_q, block_k,
                                       causal)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, dropout_p=dropout_p)
    STATS.bump("splash_fwd")
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Sq // block_q),
        in_specs=[
            _smem_scalar_spec(),
            _smem_block_spec(H),
            _smem_block_spec(H),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b // H, i, 0)),
            pl.BlockSpec((None, 1, Sk), lambda b, i: (b // H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(seed_arr, kv_lo, kv_hi, qr, kr, vr, qs3, ks3)
    return out.reshape(B, H, Sq, D), lse


def _splash_bwd_call(q, k, v, q_seg, kv_seg, seed, out, lse, g, causal,
                     scale, dropout_p, block_q, block_k):
    (B, H, Sq, Sk, D), qr, kr, vr, qs3, ks3 = _prep(q, k, v, q_seg, kv_seg)
    kv_lo, kv_hi, q_lo, q_hi = _block_bounds(q_seg, kv_seg, block_q,
                                             block_k, causal)
    gr = g.reshape(B * H, Sq, D)
    delta = jnp.sum(gr.astype(jnp.float32)
                    * out.reshape(B * H, Sq, D).astype(jnp.float32),
                    axis=-1, keepdims=True)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    # q segment ids sliced per q block in dq, but streamed whole-row in
    # dkv — [B, Sq, 1] serves both index maps
    qs_col = qs3
    STATS.bump("splash_bwd")

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, dropout_p=dropout_p),
        grid=(B * H, Sq // block_q),
        in_specs=[
            _smem_scalar_spec(),
            _smem_block_spec(H),
            _smem_block_spec(H),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b // H, i, 0)),
            pl.BlockSpec((None, 1, Sk), lambda b, i: (b // H, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        interpret=_interpret(),
    )(seed_arr, kv_lo, kv_hi, qr, kr, vr, qs_col, ks3, gr, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, dropout_p=dropout_p),
        grid=(B * H, Sk // block_k),
        in_specs=[
            _smem_scalar_spec(),
            _smem_block_spec(H),
            _smem_block_spec(H),
            pl.BlockSpec((None, Sq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Sq, 1), lambda b, i: (b // H, 0, 0)),
            pl.BlockSpec((None, 1, block_k), lambda b, i: (b // H, 0, i)),
            pl.BlockSpec((None, Sq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Sq, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Sq, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sk, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), q.dtype),
        ],
        interpret=_interpret(),
    )(seed_arr, q_lo, q_hi, qr, kr, vr, qs_col, ks3, gr, lse, delta)
    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _splash_raw_blocked(q, k, v, q_seg, kv_seg, seed, causal, scale,
                        dropout_p, block_q, block_k):
    out, _ = _splash_fwd_rule(q, k, v, q_seg, kv_seg, seed, causal, scale,
                              dropout_p, block_q, block_k)
    return out


def _splash_fwd_rule(q, k, v, q_seg, kv_seg, seed, causal, scale,
                     dropout_p, block_q, block_k):
    out, lse = _splash_call(q, k, v, q_seg, kv_seg, seed, causal, scale,
                            dropout_p, block_q, block_k)
    return out, (q, k, v, q_seg, kv_seg, seed, out, lse)


def _splash_bwd_rule(causal, scale, dropout_p, block_q, block_k, res, g):
    q, k, v, q_seg, kv_seg, seed, out, lse = res
    dq, dk, dv = _splash_bwd_call(q, k, v, q_seg, kv_seg, seed, out, lse,
                                  g, causal, scale, dropout_p, block_q,
                                  block_k)

    def zero_seg(s):
        return jnp.zeros_like(s) \
            if jnp.issubdtype(s.dtype, jnp.floating) \
            else jnp.zeros(s.shape, jax.dtypes.float0)
    dseed = np.zeros((), jax.dtypes.float0)
    return dq, dk, dv, zero_seg(q_seg), zero_seg(kv_seg), dseed


_splash_raw_blocked.defvjp(_splash_fwd_rule, _splash_bwd_rule)


def splash_attention_raw(q, k, v, q_seg, kv_seg, seed, causal, scale,
                         dropout_p):
    """Segment-aware flash attention with block skipping.

    q/k/v: [B, H, S, D]; q_seg/kv_seg: [B, S] int segment ids,
    NON-DECREASING along each row (the packing layout — the block-skip
    bounds assume it; see module docstring). seed: int32 scalar for
    in-kernel dropout. causal/scale/dropout_p are static. Segment ids
    and seed are non-differentiable.

    Tile sizes are snapshotted here and threaded through the custom_vjp
    as static args (same reason as flash_attention_raw: the dropout
    replay keys on block indices, so the forward and a later backward
    must never read different FLAGS_flash_block_* values).
    """
    bq, bk = _pick_blocks(q.shape[2], k.shape[2])
    return _splash_raw_blocked(q, k, v, q_seg, kv_seg, seed, causal,
                               scale, dropout_p, bq, bk)


def splash_supported(q_shape, k_shape=None, v_shape=None, is_causal=False,
                     min_seq=None):
    """Static gate: shapes the splash kernels handle AND where they win.

    Packing is self-attention over one fixed row shape, so the gate is
    stricter than flash_supported: S_q == S_kv. Below `min_seq`
    (FLAGS_splash_attention_min_seq) the dense segment-masked fallback
    wins, same crossover story as the flash kernel.
    """
    if not _HAS_PALLAS or len(q_shape) != 4:
        return False
    B, H, Sq, D = q_shape
    k_shape = tuple(k_shape) if k_shape is not None else tuple(q_shape)
    v_shape = tuple(v_shape) if v_shape is not None else k_shape
    if len(k_shape) != 4 or k_shape != v_shape:
        return False
    if k_shape != (B, H, Sq, D):      # packed rows: strict self-attention
        return False
    if Sq % _BLOCK_MIN != 0 or D % 8 != 0 or D > 512:
        return False
    if min_seq is None:
        from ..framework.flags import flag
        min_seq = flag("FLAGS_splash_attention_min_seq")
    return Sq >= min_seq


def _check_monotonic(seg):
    """Host-side validation when the ids are concrete (not traced): the
    block-skip contract. Inside jit the ids are tracers and the packing
    collator is the producer, so this is a best-effort guard."""
    try:
        arr = np.asarray(seg)
    except Exception:
        return  # traced: cannot inspect values
    if arr.ndim == 2 and np.any(np.diff(arr, axis=1) < 0):
        raise ValueError(
            "splash attention requires NON-DECREASING segment ids along "
            "each row (the packing layout); got a row with a decreasing "
            "id — re-pack or route through dense attention")


def splash_attention(query, key, value, q_seg, kv_seg, causal=False,
                     scale=None, dropout_p=0.0):
    """Framework-level entry: Tensor in/out, tape-recorded.

    q_seg/kv_seg: [B, S] int segment ids (Tensor or array),
    non-decreasing per row; packed padding tokens carry their own
    trailing segment id so they only ever attend to each other.
    """
    from ..framework.tensor import apply_op, Tensor
    if scale is None:
        scale = 1.0 / (query.shape[-1] ** 0.5)
    qs = q_seg._value if isinstance(q_seg, Tensor) else jnp.asarray(q_seg)
    ks = kv_seg._value if isinstance(kv_seg, Tensor) else jnp.asarray(kv_seg)
    _check_monotonic(qs)
    _check_monotonic(ks)
    if dropout_p > 0.0:
        from ..framework import random as frandom
        key_ = frandom.get_rng_key()
        seed = jax.random.randint(key_, (), 0, np.int32(2 ** 31 - 1),
                                  dtype=jnp.int32)
    else:
        seed = jnp.zeros((), jnp.int32)
    return apply_op(
        "splash_attention",
        lambda q, k, v: splash_attention_raw(q, k, v, qs, ks, seed, causal,
                                             scale, dropout_p),
        (query, key, value), {})
