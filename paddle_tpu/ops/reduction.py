"""Reduction ops (reference `paddle/fluid/operators/reduce_ops/`)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from ..framework.tensor import Tensor, apply_op

__all__ = ["sum", "mean", "max", "min", "prod", "all", "any", "logsumexp",
           "std", "var", "amax", "amin", "nansum", "nanmean", "count_nonzero",
           "median", "nanmedian", "quantile"]


def _axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = None if dtype is None else to_jax_dtype(dtype)
    return apply_op("reduce_sum",
                    lambda v: jnp.sum(v, axis=_axis(axis), dtype=dt,
                                      keepdims=keepdim), (x,), {})


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op("reduce_mean",
                    lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {})


def max(x, axis=None, keepdim=False, name=None):
    return apply_op("reduce_max",
                    lambda v: jnp.max(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {})


def min(x, axis=None, keepdim=False, name=None):
    return apply_op("reduce_min",
                    lambda v: jnp.min(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {})


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    dt = None if dtype is None else to_jax_dtype(dtype)
    return apply_op("reduce_prod",
                    lambda v: jnp.prod(v, axis=_axis(axis), dtype=dt,
                                       keepdims=keepdim), (x,), {})


def all(x, axis=None, keepdim=False, name=None):
    return apply_op("reduce_all",
                    lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {})


def any(x, axis=None, keepdim=False, name=None):
    return apply_op("reduce_any",
                    lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim),
                    (x,), {})


def logsumexp(x, axis=None, keepdim=False, name=None):
    import jax
    return apply_op("logsumexp",
                    lambda v: jax.scipy.special.logsumexp(
                        v, axis=_axis(axis), keepdims=keepdim), (x,), {})


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("std",
                    lambda v: jnp.std(v, axis=_axis(axis),
                                      ddof=1 if unbiased else 0,
                                      keepdims=keepdim), (x,), {})


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("var",
                    lambda v: jnp.var(v, axis=_axis(axis),
                                      ddof=1 if unbiased else 0,
                                      keepdims=keepdim), (x,), {})


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = None if dtype is None else to_jax_dtype(dtype)
    return apply_op("nansum",
                    lambda v: jnp.nansum(v, axis=_axis(axis), dtype=dt,
                                         keepdims=keepdim), (x,), {})


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmean",
                    lambda v: jnp.nanmean(v, axis=_axis(axis),
                                          keepdims=keepdim), (x,), {})


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op("count_nonzero",
                    lambda v: jnp.count_nonzero(v, axis=_axis(axis),
                                                keepdims=keepdim).astype("int64"),
                    (x,), {})


def median(x, axis=None, keepdim=False, name=None):
    return apply_op("median",
                    lambda v: jnp.median(v, axis=_axis(axis),
                                         keepdims=keepdim), (x,), {})


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmedian",
                    lambda v: jnp.nanmedian(v, axis=_axis(axis),
                                            keepdims=keepdim), (x,), {})


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op("quantile",
                    lambda v: jnp.quantile(v, q, axis=_axis(axis),
                                           keepdims=keepdim), (x,), {})
