"""Paged KV-cache attention: TPU Pallas kernel dispatch + dense reference.

vLLM's PagedAttention insight, TPU-shaped: decode-time K/V lives in
fixed-size **pages** inside preallocated per-layer pools
(`[L, H, num_pages, page_size, D]`), and a per-sequence **page table**
maps logical token positions to physical pages — so sequences of wildly
different lengths share one pool with zero fragmentation beyond the last
partial page, and admission control is exact page arithmetic
(`serving/kv_cache.py`).

Two attention implementations over that layout, one math:

- **TPU** — `jax.experimental.pallas.ops.tpu.paged_attention` (the
  primitive SNIPPETS.md [3] shards along KV heads): reads pages in
  place, `lengths` masks per sequence. Flag-gated by
  `FLAGS_use_paged_attention`; tile = `FLAGS_paged_compute_block_pages`
  pages.
- **reference** (CPU / interpret parity) — gather the page table into a
  dense `[B, H, T, D]` buffer and run `cached_attention`, the EXACT
  masked-softmax expression `GPTModel.generate`'s fixed cache uses, so
  the generation engine's greedy decode is anchored to the same oracle
  as `tests/test_generate.py` (positions beyond `pos` mask to -1e30 →
  exp underflows to exactly 0.0, so page-tail junk and trash-page reads
  contribute +0.0 and numerics match the contiguous cache bit-for-bit
  within one compiled shape).

Both paths are trace-time choices (python `if` under `jax.jit`), counted
by `STAT_paged_attn_kernel` / `STAT_paged_attn_reference` — these count
**traces**, not calls, mirroring the exact-compile accounting everywhere
else in the serving stack.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import monitor
from ..framework.flags import flag

__all__ = ["cached_attention", "paged_attention", "paged_gather",
           "paged_gather_layers", "paged_gather_quantized",
           "paged_prefix_attention", "paged_write",
           "paged_write_quantized", "page_rows_for_positions",
           "sharded_paged_attention"]


def cached_attention(q, kb, vb, pos, scale):
    """Masked attention of one-position queries over a dense cache.

    q [B, H, D]; kb/vb [B, H, T, D]; pos scalar or [B] int (index of the
    LAST valid cache position — attention covers t <= pos, exactly
    `GPTModel.generate`'s decode mask). Returns [B, H, D]."""
    s = jnp.einsum("bhd,bhtd->bht", q, kb) * scale
    T = kb.shape[2]
    limit = pos[:, None, None] if jnp.ndim(pos) else pos
    s = jnp.where(jnp.arange(T)[None, None, :] <= limit, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bht,bhtd->bhd", p, vb)


def paged_gather(pages, page_table):
    """Materialize page-table rows as a dense cache view.

    pages [H, N, P, D] (one layer's pool); page_table [B, PP] int32.
    Returns [B, H, PP*P, D] — logical token order regardless of physical
    page placement."""
    H, _, P, D = pages.shape
    B, PP = page_table.shape
    kb = jnp.take(pages, page_table, axis=1)     # [H, B, PP, P, D]
    return jnp.moveaxis(kb, 1, 0).reshape(B, H, PP * P, D)


def page_rows_for_positions(page_table, positions, page_size):
    """(page_ids, offsets) physical coordinates for logical `positions`.

    page_table [PP] or [B, PP]; positions [S] (with a [PP] table), [B]
    (with a [B, PP] table — one position per row), or [B, S] (with a
    [B, PP] table — a block of positions per row, the speculative
    verify shape). Out-of-range page indices clamp onto the row's last
    entry (XLA gather semantics) — callers mask such coordinates to the
    scratch page before writing."""
    if page_table.ndim == 1:
        return page_table[positions // page_size], positions % page_size
    B = page_table.shape[0]
    if positions.ndim == 2:
        rows = jnp.arange(B)[:, None]
        return (page_table[rows, positions // page_size],
                positions % page_size)
    return (page_table[jnp.arange(B), positions // page_size],
            positions % page_size)


def paged_write(pages, layer, page_ids, offsets, values):
    """Scatter per-row K/V vectors into one layer of a paged pool.

    pages [L, H, N, P, D]; page_ids/offsets [B]; values [B, H, D] (the
    integer layer index joins the advanced block, which is then
    non-contiguous, so numpy indexing moves the batch dim to the
    front). `layer=None` writes all layers at once (prefill):
    page_ids/offsets [S], values [L, H, S, D] (adjacent advanced block
    stays in place)."""
    if layer is None:
        return pages.at[:, :, page_ids, offsets, :].set(values)
    return pages.at[layer, :, page_ids, offsets, :].set(values)


# -- int8 page mode ---------------------------------------------------------
#
# FLAGS_kv_cache_dtype=int8: pools store int8 with a parallel
# per-(layer, head, page) fp32 scale pool (symmetric abs-max; dequant =
# q * scale). Writes QUANTIZE on append; reads dequantize on gather. The
# quantization grid is per page: when a newly appended token's abs-max
# exceeds the page's current scale, the page's existing int8 content is
# REQUANTIZED onto the wider grid (round(q * old/new)) — shape-static,
# touches only the [P, D] page being appended to, and bounds the
# round-off to one extra rounding per scale growth. Scale 0 marks an
# empty page (zero-on-free resets both pools), so freed pages never leak
# a stale grid to their next owner.


def _q8(v, s):
    """Symmetric int8 quantization of `v` against per-slice scales `s`
    (broadcastable); s == 0 (empty/all-zero) maps to 0."""
    q = jnp.where(s > 0, v / jnp.where(s > 0, s, 1.0), 0.0)
    return jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)


def paged_gather_quantized(pages, scales, page_table, dtype=jnp.float32):
    """Dequantizing gather: int8 pages [H, N, P, D] + scales [H, N] →
    dense floating [B, H, PP*P, D] (only THIS batch's pages are ever
    materialized in floating form — the pools stay int8 in HBM)."""
    monitor.stat_add("STAT_kv_quant_reads")  # traces, not calls
    H, _, P, D = pages.shape
    B, PP = page_table.shape
    kb = jnp.take(pages, page_table, axis=1)        # [H, B, PP, P, D]
    sc = jnp.take(scales, page_table, axis=1)       # [H, B, PP]
    kb = kb.astype(dtype) * sc[..., None, None].astype(dtype)
    return jnp.moveaxis(kb, 1, 0).reshape(B, H, PP * P, D)


def paged_write_quantized(pages, scales, layer, page_ids, offsets, values,
                          requant=False):
    """Quantize-on-append into int8 pools; returns (pages, scales).

    Decode (`layer` an int): page_ids/offsets [B], values [B, H, D] —
    gathers each row's single page, grows its scale to cover the new
    token (requantizing existing content when it does), writes the
    quantized token. Duplicate page ids (inactive slots parked on the
    trash page) scatter last-writer-wins, which is fine for the same
    reason the fp32 path tolerates it: trash content is masked junk.

    Prefill (`layer=None`): page_ids/offsets [S], values [L, H, S, D] —
    scatter-max builds each target page's scale over every token landing
    in it, then all tokens quantize against their page's final scale.
    Assumes freshly zeroed target pages (scale 0 — exactly what
    zero-on-free guarantees for an alloc) UNLESS `requant=True` (a
    trace-time switch): the tail-prefill program (prefix cache,
    ISSUE 12) can write onto a copy-on-write split page that arrives
    with cloned content + a non-zero scale, so it additionally
    requantizes the target pages' existing content onto the (possibly
    widened) grid before the token writes land — growing the grid
    without requantizing would silently inflate every prior token on
    dequant. The full-prefill program keeps `requant=False` and skips
    that whole-page traffic (for zeroed pages it would rewrite zeros
    with zeros). The trash page (padded prefill tails) accumulates junk
    between frees, which dequantizes finite and is masked out, same as
    the fp32 contract."""
    monitor.stat_add("STAT_kv_quant_writes")  # traces, not calls
    if layer is None:
        a = jnp.max(jnp.abs(values), axis=-1) / 127.0        # [L, H, S]
        s_old = scales[:, :, page_ids]                       # [L, H, S]
        scales = scales.at[:, :, page_ids].max(a)            # dup-safe
        s_tok = scales[:, :, page_ids]                       # [L, H, S]
        if requant:
            # duplicate page ids are safe — s_old/s_tok are per-page,
            # so duplicates compute identical requantized pages and the
            # scatter's last-writer-wins is a no-op
            fdt = values.dtype
            pk = pages[:, :, page_ids]                       # [L,H,S,P,D]
            ratio = jnp.where(
                s_tok > 0, s_old / jnp.where(s_tok > 0, s_tok, 1.0), 1.0)
            pk = jnp.round(pk.astype(fdt) * ratio[..., None, None]) \
                .astype(jnp.int8)
            pages = pages.at[:, :, page_ids].set(pk)
        q = _q8(values, s_tok[..., None])
        return pages.at[:, :, page_ids, offsets, :].set(q), scales
    B = page_ids.shape[0]
    fdt = values.dtype
    a = jnp.max(jnp.abs(values), axis=-1) / 127.0            # [B, H]
    s_old = scales[layer][:, page_ids]                       # [H, B]
    s_new = jnp.maximum(s_old, a.T)                          # [H, B]
    pk = pages[layer][:, page_ids]                           # [H, B, P, D]
    ratio = jnp.where(s_new > 0,
                      s_old / jnp.where(s_new > 0, s_new, 1.0), 1.0)
    pk = jnp.round(pk.astype(fdt) * ratio[..., None, None]) \
        .astype(jnp.int8)
    q = _q8(values, jnp.moveaxis(s_new, 1, 0)[..., None])    # [B, H, D]
    pk = pk.at[:, jnp.arange(B), offsets, :].set(jnp.moveaxis(q, 0, 1))
    # scatter target: the scalar layer index joins the advanced block,
    # which is then non-contiguous, so the batch dim lands in FRONT
    # (same subtlety as paged_write's docstring) — move it there
    pages = pages.at[layer, :, page_ids, :, :].set(
        jnp.moveaxis(pk, 1, 0))                              # [B, H, P, D]
    scales = scales.at[layer, :, page_ids].set(s_new.T)      # [B, H]
    return pages, scales


def _use_kernel() -> bool:
    if not bool(flag("FLAGS_use_paged_attention")):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend not initialized yet
        return False


def paged_attention(q, k_pages, v_pages, page_table, pos, scale,
                    k_scales=None, v_scales=None):
    """One decode position of attention over a paged KV cache.

    q [B, H, D]; k_pages/v_pages [H, N, P, D] (ONE layer's pool);
    page_table [B, PP] int32; pos [B] int32 (last valid position, the
    token just written). Returns [B, H, D].

    TPU dispatches the Pallas kernel (pages read in place); everywhere
    else the reference gathers to dense and reuses `cached_attention` —
    the generate-anchored math.

    int8 pools pass k_scales/v_scales ([H, N] per-page scales): the
    Pallas kernel has no int8+scale-pool input layout, so quantized
    reads always take the dequantizing gather + dense reference (the
    gather materializes only this batch's pages in floating form; the
    pools stay int8 in HBM — on TPU and CPU alike)."""
    if k_scales is not None:
        monitor.stat_add("STAT_paged_attn_reference")  # traces, not calls
        kb = paged_gather_quantized(k_pages, k_scales, page_table, q.dtype)
        vb = paged_gather_quantized(v_pages, v_scales, page_table, q.dtype)
        return cached_attention(q, kb, vb, pos, scale)
    if _use_kernel():
        monitor.stat_add("STAT_paged_attn_kernel")  # traces, not calls
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention as _kernel)
        # the kernel takes no softmax-scale argument and applies none
        # internally: fold ours into q before the qk product
        out = _kernel(
            q * scale, k_pages, v_pages,
            lengths=(pos + 1).astype(jnp.int32),
            page_indices=page_table.astype(jnp.int32),
            pages_per_compute_block=int(
                flag("FLAGS_paged_compute_block_pages")))
        return out
    monitor.stat_add("STAT_paged_attn_reference")  # traces, not calls
    kb = paged_gather(k_pages, page_table)
    vb = paged_gather(v_pages, page_table)
    return cached_attention(q, kb, vb, pos, scale)


def sharded_paged_attention(mesh, scale, tp_axis="tp", quantized=False):
    """KV-head-sharded `paged_attention` over a tp mesh (ISSUE 19; the
    SNIPPETS [3] layout): one layer's pools enter
    `P(tp, None, None, None)` — sharded along the heads axis — with
    page table and positions replicated and q sharded on ITS head axis,
    and each shard dispatches `paged_attention` on its local head slice
    (Pallas kernel on TPU, dequantizing gather + dense reference
    elsewhere). GSPMD cannot partition a pallas_call, so the shard_map
    wrapper IS the multi-chip dispatch — without it pjit would gather
    the full pool onto every device.

    Returns a jitted
    `f(q, k_pages, v_pages, page_table, pos)` — or, with
    `quantized=True`,
    `f(q, k_pages, v_pages, k_scales, v_scales, page_table, pos)` —
    yielding [B, H, D] head-sharded like q."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.spmd import compat_shard_map
    hs = P(None, tp_axis, None)           # q / out [B, H, D]
    pool = P(tp_axis, None, None, None)   # one layer [H, N, Pg, D]
    spool = P(tp_axis, None)              # scale grid [H, N]
    rep = P()
    if quantized:
        def call(q, kp, vp, ks, vs, pt, pos):
            return paged_attention(q, kp, vp, pt, pos, scale,
                                   k_scales=ks, v_scales=vs)
        in_specs = (hs, pool, pool, spool, spool, rep, rep)
    else:
        def call(q, kp, vp, pt, pos):
            return paged_attention(q, kp, vp, pt, pos, scale)
        in_specs = (hs, pool, pool, rep, rep)
    return jax.jit(compat_shard_map(call, mesh=mesh, in_specs=in_specs,
                                    out_specs=hs, check=False))


def paged_gather_layers(pages, page_table, scales=None,
                        dtype=jnp.float32):
    """Materialize ONE sequence's page-table row as a dense view across
    ALL layers at once: pages [L, H, N, P, D] + page_table [PP] →
    [L, H, PP*P, D] (dequantized via per-page `scales` [L, H, N] in the
    int8 mode). One gather from the whole pool instead of a per-layer
    `pages[layer]` slice — slicing the [L, ...] pool per layer copies
    the full layer buffer each time, which dwarfs the tail prefill's
    actual compute; gathering first touches only this row's pages."""
    L, H, _, P, D = pages.shape
    PP = page_table.shape[0]
    kb = jnp.take(pages, page_table, axis=2)       # [L, H, PP, P, D]
    if scales is not None:
        sc = jnp.take(scales, page_table, axis=2)  # [L, H, PP]
        kb = kb.astype(dtype) * sc[..., None, None].astype(dtype)
    return kb.reshape(L, H, PP * P, D)


def paged_prefix_attention(q, kb, vb, k_tail, v_tail, prefix_len, scale):
    """Tail-prefill attention: multi-position queries over a cached
    prefix (pre-gathered from pages) plus the tail's own in-flight K/V.

    q / k_tail / v_tail [B, H, S, D]; kb/vb [B, H, T, D] — ONE layer of
    the `paged_gather_layers` view of the sequence's page-table row;
    prefix_len scalar int32, or [B] int32 for per-row context lengths
    (the speculative verify block, ISSUE 14 — every decode slot carries
    its own cache length) — cached positions t < prefix_len are
    attended, everything at or past it in the gathered view (fresh
    pages, table padding) masks to exact 0.0. Tail position j is
    attended by tail query i iff j <= i (causal within the tail; the
    tail K/V never round-trips through the pages, so the page gather
    stays READ-ONLY — pad tail positions are routed to the scratch page
    by the caller's WRITE, never read here). Returns [B, H, S, D].

    The joint softmax over [prefix ; tail] is the same masked-softmax
    expression as `cached_attention` (-1e30 → exact 0.0), so a tail
    prefill is anchored to the same oracle as the decode step."""
    monitor.stat_add("STAT_paged_attn_reference")  # traces, not calls
    T = kb.shape[2]
    S = q.shape[2]
    sp = jnp.einsum("bhsd,bhtd->bhst", q, kb) * scale
    limit = (prefix_len[:, None, None, None] if jnp.ndim(prefix_len)
             else prefix_len)
    sp = jnp.where(jnp.arange(T)[None, None, None, :] < limit,
                   sp, -1e30)
    st = jnp.einsum("bhsd,bhtd->bhst", q, k_tail) * scale
    causal = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
    st = jnp.where(causal[None, None], st, -1e30)
    p = jax.nn.softmax(jnp.concatenate([sp, st], axis=-1), axis=-1)
    return (jnp.einsum("bhst,bhtd->bhsd", p[..., :T], vb)
            + jnp.einsum("bhst,bhtd->bhsd", p[..., T:], v_tail))
