"""Attach the op surface to Tensor as methods + operator overloads.

Reference: `python/paddle/fluid/dygraph/math_op_patch.py` (monkey-patched
VarBase operators) — same approach, one place.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, apply_op
from . import creation, linalg, logic, manipulation, math, reduction, search

_METHOD_SOURCES = [
    (math, ["exp", "log", "sqrt", "rsqrt", "square", "abs", "sign", "floor",
            "ceil", "round", "trunc", "sin", "cos", "tan", "tanh", "sigmoid",
            "erf", "reciprocal", "scale", "clip", "cumsum", "cumprod",
            "isnan", "isinf", "isfinite", "add", "subtract", "multiply",
            "divide", "pow", "maximum", "minimum", "mod", "floor_divide",
            "remainder", "neg", "trace", "lerp", "addmm"]),
    (reduction, ["sum", "mean", "max", "min", "prod", "all", "any",
                 "logsumexp", "std", "var"]),
    (manipulation, ["reshape", "flatten", "transpose", "squeeze", "unsqueeze",
                    "split", "chunk", "tile", "expand", "expand_as",
                    "broadcast_to", "gather", "gather_nd", "scatter",
                    "index_select", "masked_select", "roll", "flip", "cast",
                    "unbind",
                    "repeat_interleave", "take_along_axis", "put_along_axis",
                    "unique", "nonzero", "diagonal", "masked_fill",
                    "moveaxis", "t"]),
    (linalg, ["matmul", "mm", "bmm", "dot", "norm", "dist", "cross",
              "cholesky", "inverse", "det", "matrix_power", "mv"]),
    (logic, ["equal", "not_equal", "less_than", "less_equal", "greater_than",
             "greater_equal", "logical_and", "logical_or", "logical_xor",
             "logical_not", "allclose", "isclose", "equal_all",
             "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not"]),
    (search, ["argmax", "argmin", "argsort", "sort", "topk", "kthvalue"]),
    (creation, ["tril", "triu"]),
]

for mod, names in _METHOD_SOURCES:
    for n in set(names):
        fn = getattr(mod, n, None)
        if fn is not None and not hasattr(Tensor, n):
            setattr(Tensor, n, fn)

# `astype` (paddle name for cast)
Tensor.astype = manipulation.cast


def _coerce(other):
    return other


def _binop(name, fn, reverse=False):
    def op(self, other):
        if isinstance(other, (list, tuple, np.ndarray)):
            other = Tensor(jnp.asarray(np.asarray(other)))
        a, b = (other, self) if reverse else (self, other)
        return apply_op(name, fn, (a, b), {})
    return op


Tensor.__add__ = _binop("add", jnp.add)
Tensor.__radd__ = _binop("add", jnp.add, reverse=True)
Tensor.__sub__ = _binop("subtract", jnp.subtract)
Tensor.__rsub__ = _binop("subtract", jnp.subtract, reverse=True)
Tensor.__mul__ = _binop("multiply", jnp.multiply)
Tensor.__rmul__ = _binop("multiply", jnp.multiply, reverse=True)
Tensor.__truediv__ = _binop("divide", jnp.divide)
Tensor.__rtruediv__ = _binop("divide", jnp.divide, reverse=True)
Tensor.__floordiv__ = _binop("floor_divide", jnp.floor_divide)
Tensor.__rfloordiv__ = _binop("floor_divide", jnp.floor_divide, reverse=True)
Tensor.__mod__ = _binop("mod", jnp.mod)
Tensor.__pow__ = _binop("pow", jnp.power)
Tensor.__rpow__ = _binop("pow", jnp.power, reverse=True)
Tensor.__matmul__ = _binop("matmul", jnp.matmul)
Tensor.__rmatmul__ = _binop("matmul", jnp.matmul, reverse=True)
Tensor.__neg__ = lambda self: apply_op("neg", jnp.negative, (self,), {})
Tensor.__abs__ = lambda self: apply_op("abs", jnp.abs, (self,), {})
Tensor.__invert__ = lambda self: apply_op("bitwise_not", jnp.bitwise_not,
                                          (self,), {})
Tensor.__and__ = _binop("bitwise_and", jnp.bitwise_and)
Tensor.__or__ = _binop("bitwise_or", jnp.bitwise_or)
Tensor.__xor__ = _binop("bitwise_xor", jnp.bitwise_xor)

Tensor.__eq__ = _binop("equal", jnp.equal)
Tensor.__ne__ = _binop("not_equal", jnp.not_equal)
Tensor.__lt__ = _binop("less_than", jnp.less)
Tensor.__le__ = _binop("less_equal", jnp.less_equal)
Tensor.__gt__ = _binop("greater_than", jnp.greater)
Tensor.__ge__ = _binop("greater_equal", jnp.greater_equal)


def _getitem(self, idx):
    def unwrap(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, tuple):
            return tuple(unwrap(j) for j in i)
        return i
    idx = unwrap(idx)
    return apply_op("getitem", lambda v: v[idx], (self,), {})


def _setitem(self, idx, value):
    def unwrap(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, tuple):
            return tuple(unwrap(j) for j in i)
        return i
    idx = unwrap(idx)
    if isinstance(value, Tensor):
        out = apply_op("setitem", lambda v, u: v.at[idx].set(u),
                       (self, value), {})
    else:
        out = apply_op("setitem", lambda v: v.at[idx].set(value), (self,), {})
    # in-place semantics: adopt the new value (and graph node) in place
    self._value = out._value
    self._node = out._node
    if out._node is not None:
        self.stop_gradient = False
    return self


Tensor.__getitem__ = _getitem
Tensor.__setitem__ = _setitem

# iteration over first axis
def _iter(self):
    for i in range(self.shape[0]):
        yield self[i]


Tensor.__iter__ = _iter


# --------------------------------------------------------------------------
# in-place variants (reference: `reshape_`, `scatter_`, `tanh_`… — eager-only
# mutation; under XLA "in-place" is adopt-the-new-functional-value, with
# donation letting the compiler reuse the buffer)

def _make_inplace(fn):
    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        self._value = out._value
        self._node = out._node
        if out._node is not None:
            self.stop_gradient = False
        return self
    return method


_INPLACE = {
    "reshape_": manipulation.reshape,
    "squeeze_": manipulation.squeeze,
    "unsqueeze_": manipulation.unsqueeze,
    "flatten_": manipulation.flatten,
    "scatter_": manipulation.scatter,
    "clip_": math.clip,
    "scale_": math.scale,
    "tanh_": math.tanh,
    "exp_": math.exp,
    "sqrt_": math.sqrt,
    "rsqrt_": math.rsqrt,
    "reciprocal_": math.reciprocal,
    "round_": math.round,
    "floor_": math.floor,
    "ceil_": math.ceil,
    "abs_": math.abs,
    "subtract_": math.subtract,
    "add_": math.add,
    "multiply_": math.multiply,
}

for _name, _fn in _INPLACE.items():
    if not hasattr(Tensor, _name):
        setattr(Tensor, _name, _make_inplace(_fn))


def _zero_(self):
    self._value = jnp.zeros_like(self._value)
    return self


def _fill_(self, value):
    self._value = jnp.full_like(self._value, value)
    return self


Tensor.zero_ = _zero_
Tensor.fill_ = _fill_
