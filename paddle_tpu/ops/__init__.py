"""Op library: the TPU-native replacement for the reference's 496-op
`paddle/fluid/operators/` — every op is a pure jnp/lax function lowered by
XLA (no hand-written kernels except Pallas hot ops)."""
from . import creation, legacy, linalg, logic, manipulation, math, random_ops, reduction, search
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .legacy import *  # noqa: F401,F403  (last: axis-aware elementwise_* win)

from . import tensor_methods  # noqa: F401  (patches Tensor)
