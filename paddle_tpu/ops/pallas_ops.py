"""Pallas TPU kernels for hot ops.

The reference ships hand-written CUDA for its hot paths
(`paddle/fluid/operators/fused/`, `math/`). The TPU equivalents are Pallas
kernels; everything else rides XLA fusion. Flagship kernel: flash attention
(online-softmax tiling, VMEM-resident K/V, in-kernel dropout via the TPU
PRNG), used by `F.scaled_dot_product_attention` / MultiHeadAttention.

Design (not from the reference — it has no fused attention):
  * forward: grid (batch*heads, q_blocks); K/V for the head stay in VMEM;
    inner fori_loop streams K blocks with the (m, l, acc) online-softmax
    recurrence; emits O and the per-row logsumexp (LSE).
  * backward: two Pallas kernels (dQ over q-blocks, dK/dV over k-blocks)
    that RECOMPUTE the probability tiles from (q, k, lse) block by block —
    no S×S matrix is ever materialized, so memory stays O(S·D) end to end.
  * masking: an additive key-padding bias [B, S] (the BERT/ERNIE padded
    -batch shape) plus an optional static causal mask.
  * dropout: per-(batch*head, q_block, k_block) reseeded TPU PRNG so the
    backward kernels regenerate bit-identical keep masks without storing
    them.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["flash_attention", "flash_attention_raw", "STATS"]

_BLOCK_MIN = 128        # alignment the kernels require of S_q / S_kv
_NEG_INF = -1e30


def _block_pref(flag_name):
    """Preferred tile size from a FLAGS_flash_block_* flag. The defaults
    (512/512) are the on-chip sweep result (tools/perf_flash_sweep.py,
    v5e, S=2048, bf16); with native-dtype MXU dots the GPT seq-2048
    bench runs 1.47x dense (bench.py). The splash path rides the same
    flags (tools/perf_splash_sweep.py re-runs the sweep for it)."""
    from ..framework.flags import flag
    # lint: allow(flag-in-trace): this IS the sanctioned snapshot point — flash/splash_attention_raw reads the tile flags once per outer trace and threads them through the custom_vjp as static args, so fwd and bwd can never desync (the PR 6 contract)
    pref = int(flag(flag_name))
    if pref < _BLOCK_MIN or pref % _BLOCK_MIN != 0:
        raise ValueError(
            f"{flag_name}={pref}: attention tile sizes must be positive "
            f"multiples of {_BLOCK_MIN}")
    return pref


def _pick_blocks(Sq, Sk):
    """Largest preferred tile that divides the sequence lengths, capped
    by the FLAGS_flash_block_q / FLAGS_flash_block_kv preferences."""
    for s in (Sq, Sk):
        if s % _BLOCK_MIN != 0:
            raise ValueError(
                f"flash: sequence length {s} must be a multiple of "
                f"{_BLOCK_MIN} (pad the sequence or route through dense "
                f"attention via flash_supported)")
    prefq = _block_pref("FLAGS_flash_block_q")
    prefk = _block_pref("FLAGS_flash_block_kv")
    bq = max(b for b in sorted({128, 256, 512, prefq})
             if Sq % b == 0 and b <= Sq and b <= prefq)
    bk = max(b for b in sorted({128, 256, 512, prefk})
             if Sk % b == 0 and b <= Sk and b <= prefk)
    return bq, bk

from ..framework.monitor import stat_add as _stat_add, stat_get as _stat_get


class _KernelStats:
    """Trace-time engagement counters (prove the kernel ran in a given
    program). Backed by the framework STAT registry
    (framework/monitor.py) so there is one source of truth."""

    _keys = {"flash_fwd": "STAT_flash_attention_fwd",
             "flash_bwd": "STAT_flash_attention_bwd"}

    def __getitem__(self, k):
        return _stat_get(self._keys[k])

    def bump(self, k):
        _stat_add(self._keys[k])


STATS = _KernelStats()

try:  # pallas availability is backend dependent
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _interpret():
    """Run kernels in interpreter mode off-TPU (CPU test meshes)."""
    from ..framework.flags import flag
    # lint: allow(flag-in-trace): interpret mode is lowering structure by definition — the flag selects HOW pallas_call compiles (TPU vs interpreter), re-read at every trace; there is no runtime value to thread
    if flag("FLAGS_flash_attention_interpret"):
        return True
    try:
        plats = {d.platform for d in jax.devices()}
    except Exception:
        return False
    return not ({"tpu", "axon"} & plats)


def _sdpa_reference(q, k, v, bias, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias[:, None, None, :]
    if causal:
        S, K = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((S, K), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


def _dropout_bits(seed, bh, qi, kb, shape, dropout_p):
    """Regenerable keep-mask for one (bh, q_block, k_block) tile.

    Mosaic allows at most two seed values, so the tile coordinates are
    packed into one int32 (wraps for astronomically large grids, but stays
    deterministic and identical across the fwd/dq/dkv kernels, which is
    the property the backward replay needs)."""
    tile = (bh * 1048576 + qi * 1024 + kb).astype(jnp.int32) \
        if hasattr(bh, "astype") else jnp.int32(bh * 1048576 + qi * 1024 + kb)
    pltpu.prng_seed(seed, tile)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    thresh = np.uint32(min(int(dropout_p * 4294967296.0), 4294967295))
    return bits >= thresh


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref, *,
                scale, causal, block_k, dropout_p):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    # MXU dots run on the INPUT dtype (bf16 in production — 4x the f32
    # path on v5e) with f32 accumulation; softmax stats stay f32
    q = q_ref[:]
    S, D = k_ref.shape
    bq = q_ref.shape[0]
    nkb = S // block_k
    q_offs = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    seed = seed_ref[0, 0]

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale
        s += b_ref[0, pl.ds(kb * block_k, block_k)][None, :]  # b_ref [1,S]
        if causal:
            k_offs = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_offs >= k_offs, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        if dropout_p > 0.0:
            keep = _dropout_bits(seed, bh, qi, kb, p.shape, dropout_p)
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        acc_new = alpha * acc + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, D), jnp.float32)
    if causal:
        last = jnp.minimum(nkb, ((qi + 1) * bq + block_k - 1) // block_k)
        m, l, acc = jax.lax.fori_loop(0, last, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, nkb, body, (m0, l0, acc0))
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l)


# ---------------------------------------------------------------------------
# backward: dQ kernel (grid over q blocks) and dK/dV kernel (over k blocks)
# ---------------------------------------------------------------------------

def _recompute_p(q, k_blk, bias_row, q_offs, k_offs, lse, scale, causal):
    s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale
    s += bias_row
    if causal:
        s = jnp.where(q_offs >= k_offs, s, _NEG_INF)
    return jnp.exp(s - lse)


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref,
               dl_ref, dq_ref, *, scale, causal, block_k, dropout_p):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[:]          # [bq, 1]
    delta = dl_ref[:]         # [bq, 1]
    S, D = k_ref.shape
    bq = q_ref.shape[0]
    nkb = S // block_k
    q_offs = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    seed = seed_ref[0, 0]
    inv_keep = 1.0 / (1.0 - dropout_p) if dropout_p > 0.0 else 1.0

    def body(kb, dq):
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
        k_offs = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        bias_row = b_ref[0, pl.ds(kb * block_k, block_k)][None, :]
        p = _recompute_p(q, k_blk, bias_row, q_offs, k_offs, lse, scale,
                         causal)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        if dropout_p > 0.0:
            keep = _dropout_bits(seed, bh, qi, kb, p.shape, dropout_p)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)

    dq0 = jnp.zeros((bq, D), jnp.float32)
    if causal:
        last = jnp.minimum(nkb, ((qi + 1) * bq + block_k - 1) // block_k)
        dq = jax.lax.fori_loop(0, last, body, dq0)
    else:
        dq = jax.lax.fori_loop(0, nkb, body, dq0)
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, b_ref, do_ref, lse_ref,
                dl_ref, dk_ref, dv_ref, *, scale, causal, block_q,
                dropout_p):
    bh = pl.program_id(0)
    kb = pl.program_id(1)
    k_blk = k_ref[:]                        # [bk, D]
    v_blk = v_ref[:]
    S, D = q_ref.shape
    bk = k_ref.shape[0]
    nqb = S // block_q
    k_offs = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    bias_row = b_ref[:]                     # [1, bk] (k-block slice)
    seed = seed_ref[0, 0]
    inv_keep = 1.0 / (1.0 - dropout_p) if dropout_p > 0.0 else 1.0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qi * block_q, block_q), :]
        do = do_ref[pl.ds(qi * block_q, block_q), :]
        lse = lse_ref[pl.ds(qi * block_q, block_q), :]
        delta = dl_ref[pl.ds(qi * block_q, block_q), :]
        q_offs = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        p = _recompute_p(q, k_blk, bias_row, q_offs, k_offs, lse, scale,
                         causal)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        if dropout_p > 0.0:
            keep = _dropout_bits(seed, bh, qi, kb, p.shape, dropout_p)
            pd = jnp.where(keep, p * inv_keep, 0.0)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        else:
            pd = p
        ds = p * (dp - delta)
        dv = dv + jax.lax.dot_general(pd.astype(do.dtype), do,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        dk = dk + jax.lax.dot_general(ds.astype(q.dtype), q,
                                      (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        return dk, dv

    dk0 = jnp.zeros((bk, D), jnp.float32)
    dv0 = jnp.zeros((bk, D), jnp.float32)
    if causal:
        first = (kb * bk) // block_q
        dk, dv = jax.lax.fori_loop(first, nqb, body, (dk0, dv0))
    else:
        dk, dv = jax.lax.fori_loop(0, nqb, body, (dk0, dv0))
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers
# ---------------------------------------------------------------------------

def _smem_scalar_spec():
    return pl.BlockSpec((1, 1), lambda *_: (0, 0), memory_space=pltpu.SMEM)


def _flash_call(q, k, v, bias, seed, causal, scale, dropout_p,
                block_q, block_k):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)
    bias3 = bias.reshape(B, 1, Sk)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, dropout_p=dropout_p)
    STATS.bump("flash_fwd")
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Sq // block_q),
        in_specs=[
            _smem_scalar_spec(),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, Sk), lambda b, i: (b // H, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(seed_arr, qr, kr, vr, bias3)
    return out.reshape(B, H, Sq, D), lse


def _flash_bwd_call(q, k, v, bias, seed, out, lse, g, causal, scale,
                    dropout_p, block_q, block_k):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)
    gr = g.reshape(B * H, Sq, D)
    bias3 = bias.reshape(B, 1, Sk)
    # delta = rowsum(dO ∘ O) — tiny elementwise+reduce, XLA fuses it
    delta = jnp.sum(gr.astype(jnp.float32)
                    * out.reshape(B * H, Sq, D).astype(jnp.float32),
                    axis=-1, keepdims=True)
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    STATS.bump("flash_bwd")

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, dropout_p=dropout_p),
        grid=(B * H, Sq // block_q),
        in_specs=[
            _smem_scalar_spec(),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, Sk), lambda b, i: (b // H, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        interpret=_interpret(),
    )(seed_arr, qr, kr, vr, bias3, gr, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, dropout_p=dropout_p),
        grid=(B * H, Sk // block_k),
        in_specs=[
            _smem_scalar_spec(),
            pl.BlockSpec((None, Sq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, 1, block_k), lambda b, i: (b // H, 0, i)),
            pl.BlockSpec((None, Sq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Sq, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Sq, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sk, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), q.dtype),
        ],
        interpret=_interpret(),
    )(seed_arr, qr, kr, vr, bias3, gr, lse, delta)
    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_raw_blocked(q, k, v, bias, seed, causal, scale, dropout_p,
                       block_q, block_k):
    out, _ = _flash_fwd_rule(q, k, v, bias, seed, causal, scale,
                             dropout_p, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, bias, seed, causal, scale, dropout_p,
                    block_q, block_k):
    out, lse = _flash_call(q, k, v, bias, seed, causal, scale, dropout_p,
                           block_q, block_k)
    return out, (q, k, v, bias, seed, out, lse)


def _flash_bwd_rule(causal, scale, dropout_p, block_q, block_k, res, g):
    q, k, v, bias, seed, out, lse = res
    dq, dk, dv = _flash_bwd_call(q, k, v, bias, seed, out, lse, g, causal,
                                 scale, dropout_p, block_q, block_k)
    dbias = jnp.zeros(bias.shape, jax.dtypes.float0) \
        if not jnp.issubdtype(bias.dtype, jnp.floating) \
        else jnp.zeros_like(bias)
    dseed = np.zeros((), jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash_raw_blocked.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_raw(q, k, v, bias, seed, causal, scale, dropout_p):
    """Flash attention with O(S·D) memory in fwd AND bwd.

    q/k/v: [B, H, S, D]; bias: additive key-padding mask [B, S] (zeros
    for no mask); seed: int32 scalar driving in-kernel dropout; causal/
    scale/dropout_p are static. bias and seed are non-differentiable.

    Tile sizes are snapshotted HERE and threaded through the custom_vjp
    as static args: the in-kernel dropout keep mask is reseeded per
    (bh, q_block, k_block) tile, so a FLAGS_flash_block_* change
    between an eager forward and its later backward must not let the
    two passes pick different tiles (the replayed masks would silently
    diverge and corrupt gradients).
    """
    bq, bk = _pick_blocks(q.shape[2], k.shape[2])
    return _flash_raw_blocked(q, k, v, bias, seed, causal, scale,
                              dropout_p, bq, bk)


def flash_supported(q_shape, k_shape=None, v_shape=None, mask=None,
                    is_causal=False, min_seq=None):
    """Static gate: shapes the kernels handle AND where they win.

    Below `min_seq` queries (default: FLAGS_flash_attention_min_seq, 512)
    XLA's fused dense attention beats the Pallas kernel on v5e — dense won
    the round-2/3 bench at seq 128 by ~25% — so short sequences are
    refused here and ride the jnp fallback.
    """
    if not _HAS_PALLAS or len(q_shape) != 4:
        return False
    B, H, Sq, D = q_shape
    k_shape = tuple(k_shape) if k_shape is not None else tuple(q_shape)
    v_shape = tuple(v_shape) if v_shape is not None else k_shape
    if len(k_shape) != 4 or k_shape != v_shape:
        return False
    Bk, Hk, Sk, Dk = k_shape
    if (Bk, Hk, Dk) != (B, H, D):
        return False
    if is_causal and Sk != Sq:  # causal ranges assume aligned diagonals
        return False
    if Sq % _BLOCK_MIN != 0 or Sk % _BLOCK_MIN != 0 or D % 8 != 0 \
            or D > 512:
        return False
    if min_seq is None:
        from ..framework.flags import flag
        min_seq = flag("FLAGS_flash_attention_min_seq")
    if Sq < min_seq:
        return False
    if mask is not None:
        ms = getattr(mask, "shape", None)
        if ms is None or len(ms) != 4 or ms[1] != 1 or ms[2] != 1 \
                or ms[0] != B or ms[3] != Sk:
            return False
    return True


def flash_attention(query, key, value, causal=False, scale=None,
                    attn_mask=None, dropout_p=0.0):
    """Framework-level entry: Tensor in/out, tape-recorded.

    attn_mask: None, or a [B, 1, 1, S_kv] additive (float) / boolean
    key-padding mask — the padded-batch BERT/ERNIE shape.
    """
    from ..framework.tensor import apply_op, Tensor
    if scale is None:
        scale = 1.0 / (query.shape[-1] ** 0.5)
    B, S = key.shape[0], key.shape[2]
    if attn_mask is None:
        bias = jnp.zeros((B, S), jnp.float32)
    else:
        mv = attn_mask._value if isinstance(attn_mask, Tensor) else attn_mask
        mv = mv.reshape(B, S)
        bias = jnp.where(mv, 0.0, _NEG_INF) if mv.dtype == jnp.bool_ \
            else mv.astype(jnp.float32)
    if dropout_p > 0.0:
        from ..framework import random as frandom
        key_ = frandom.get_rng_key()
        seed = jax.random.randint(key_, (), 0, np.int32(2 ** 31 - 1),
                                  dtype=jnp.int32)
    else:
        seed = jnp.zeros((), jnp.int32)
    return apply_op(
        "flash_attention",
        lambda q, k, v: flash_attention_raw(q, k, v, bias, seed, causal,
                                            scale, dropout_p),
        (query, key, value), {})
