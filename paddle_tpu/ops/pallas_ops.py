"""Pallas TPU kernels for hot ops.

The reference ships hand-written CUDA for its hot paths
(`paddle/fluid/operators/fused/`, `math/`). The TPU equivalents are Pallas
kernels; everything else rides XLA fusion. First kernel: flash attention
(online-softmax tiling, VMEM-resident running max/denominator), used by
`F.scaled_dot_product_attention` / MultiHeadAttention when on TPU.

Design (not from the reference — it has no fused attention):
  grid = (batch*heads, q_blocks); K/V for the head stay in VMEM; inner
  fori_loop streams K blocks with the usual (m, l, acc) online-softmax
  recurrence. Backward recomputes via the jnp reference inside a
  jax.custom_vjp (same FLOP trade flash makes anyway).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "flash_attention_raw"]

_BLOCK_Q = 128
_BLOCK_K = 128


def _sdpa_reference(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   precision=jax.lax.Precision.DEFAULT) * scale
    if causal:
        S, K = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((S, K), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v,
                      precision=jax.lax.Precision.DEFAULT)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k):
    # q_ref: [block_q, D]; k_ref/v_ref: [S, D]; o_ref: [block_q, D]
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale
    S = k_ref.shape[0]
    D = q_ref.shape[1]
    bq = q_ref.shape[0]
    nkb = S // block_k

    m0 = jnp.full((bq, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, D), jnp.float32)

    q_offs = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            k_offs = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_offs >= k_offs, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_new = alpha * acc + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only blocks with k_start <= q_end contribute
        last = jnp.minimum(nkb, (qi + 1) * bq // block_k + 1)
        m, l, acc = jax.lax.fori_loop(0, last, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, nkb, body, (m0, l0, acc0))
    o_ref[:] = (acc / l).astype(o_ref.dtype)


try:  # pallas availability is TPU/backend dependent
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _flash_call(q, k, v, causal, scale, block_q, block_k):
    B, H, S, D = q.shape
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, S, D)
    vr = v.reshape(B * H, S, D)

    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, S // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
    )(qr, kr, vr)
    return out.reshape(B, H, S, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_raw(q, k, v, causal, scale):
    return _flash_fwd(q, k, v, causal, scale)[0]


def _flash_fwd(q, k, v, causal, scale):
    S, D = q.shape[-2], q.shape[-1]
    ok = (_HAS_PALLAS and S % _BLOCK_Q == 0 and S % _BLOCK_K == 0
          and D % 128 == 0 and q.shape == k.shape == v.shape)
    if ok:
        try:
            out = _flash_call(q, k, v, causal, scale, _BLOCK_Q, _BLOCK_K)
            return out, (q, k, v)
        except Exception:
            pass
    return _sdpa_reference(q, k, v, causal, scale), (q, k, v)


def _flash_bwd(causal, scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _sdpa_reference(a, b, c, causal, scale),
                     q, k, v)
    return vjp(g)


flash_attention_raw.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(query, key, value, causal=False, scale=None):
    """Framework-level entry: Tensor in/out, tape-recorded."""
    from ..framework.tensor import apply_op
    if scale is None:
        scale = 1.0 / (query.shape[-1] ** 0.5)
    return apply_op("flash_attention",
                    lambda q, k, v: flash_attention_raw(q, k, v, causal,
                                                        scale),
                    (query, key, value), {})
