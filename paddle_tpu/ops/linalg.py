"""Linear algebra ops (reference `python/paddle/tensor/linalg.py`,
`operators/matmul_v2_op.*`). matmul is THE MXU op — everything routes to
jnp.matmul/einsum so XLA tiles it onto the systolic array."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, apply_op

__all__ = ["matmul", "mm", "bmm", "dot", "mv", "norm", "dist", "cross",
           "cholesky", "inverse", "det", "slogdet", "matrix_power", "svd",
           "qr", "eigh", "eigvalsh", "solve", "triangular_solve", "pinv",
           "lstsq", "einsum", "multi_dot", "matrix_rank", "histogram",
           "bincount", "cov", "corrcoef"]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def impl(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op("matmul", impl, (x, y), {})


def mm(input, mat2, name=None):
    return apply_op("mm", jnp.matmul, (input, mat2), {})


def bmm(x, y, name=None):
    return apply_op("bmm", jnp.matmul, (x, y), {})


def dot(x, y, name=None):
    def impl(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1)
    return apply_op("dot", impl, (x, y), {})


def mv(x, vec, name=None):
    return apply_op("mv", jnp.matmul, (x, vec), {})


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def impl(v):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(v * v))
        if axis is None:
            flat = v.reshape(-1)
            return jnp.linalg.norm(flat, ord=p)
        if isinstance(axis, (list, tuple)):
            return jnp.linalg.norm(v, ord="fro" if p == "fro" else p,
                                   axis=tuple(axis), keepdims=keepdim)
        if p == jnp.inf or p == float("inf"):
            return jnp.max(jnp.abs(v), axis=axis, keepdims=keepdim)
        if p == -jnp.inf or p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=axis,
                           keepdims=keepdim)
        return jnp.sum(jnp.abs(v) ** p, axis=axis,
                       keepdims=keepdim) ** (1.0 / p)
    return apply_op("norm", impl, (x,), {})


def dist(x, y, p=2, name=None):
    def impl(a, b):
        d = a - b
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype)).astype(d.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply_op("dist", impl, (x, y), {})


def cross(x, y, axis=9, name=None):
    def impl(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op("cross", impl, (x, y), {})


def cholesky(x, upper=False, name=None):
    def impl(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply_op("cholesky", impl, (x,), {})


def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, (x,), {})


def det(x, name=None):
    return apply_op("det", jnp.linalg.det, (x,), {})


def slogdet(x, name=None):
    def impl(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])
    return apply_op("slogdet", impl, (x,), {})


def matrix_power(x, n, name=None):
    return apply_op("matrix_power",
                    lambda v: jnp.linalg.matrix_power(v, n), (x,), {})


def svd(x, full_matrices=False, name=None):
    return apply_op("svd",
                    lambda v: jnp.linalg.svd(v, full_matrices=full_matrices),
                    (x,), {})


def qr(x, mode="reduced", name=None):
    return apply_op("qr", lambda v: jnp.linalg.qr(v, mode=mode), (x,), {})


def eigh(x, UPLO="L", name=None):
    return apply_op("eigh", lambda v: jnp.linalg.eigh(v, UPLO=UPLO), (x,), {})


def eigvalsh(x, UPLO="L", name=None):
    return apply_op("eigvalsh", lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO),
                    (x,), {})


def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, (x, y), {})


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return apply_op(
        "triangular_solve",
        lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular), (x, y), {})


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv",
                    lambda v: jnp.linalg.pinv(v, rtol=rcond,
                                              hermitian=hermitian), (x,), {})


def lstsq(x, y, rcond=None, driver=None, name=None):
    def impl(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply_op("lstsq", impl, (x, y), {})


def einsum(equation, *operands):
    return apply_op(
        "einsum",
        lambda *vs: jnp.einsum(equation, *vs, precision=jax.lax.Precision.HIGHEST),
        tuple(operands), {})


def multi_dot(x, name=None):
    return apply_op("multi_dot", lambda *vs: jnp.linalg.multi_dot(vs),
                    tuple(x), {})


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op("matrix_rank",
                    lambda v: jnp.linalg.matrix_rank(v, rtol=tol), (x,), {})


def histogram(input, bins=100, min=0, max=0, name=None):
    def impl(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
        h, _ = jnp.histogram(v, bins=bins, range=(lo, hi))
        return h.astype("int64")
    return apply_op("histogram", impl, (input,), {})


def bincount(x, weights=None, minlength=0, name=None):
    if weights is None:
        return apply_op("bincount",
                        lambda v: jnp.bincount(v, minlength=minlength,
                                               length=None), (x,), {})
    return apply_op("bincount",
                    lambda v, w: jnp.bincount(v, weights=w,
                                              minlength=minlength), (x, weights),
                    {})


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op("cov",
                    lambda v: jnp.cov(v, rowvar=rowvar,
                                      ddof=1 if ddof else 0), (x,), {})


def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar),
                    (x,), {})


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-norm distance (reference `paddle.cdist` /
    `operators/dist_op.cc` math). x: [..., P, M], y: [..., R, M] →
    [..., P, R]. The p=2 path uses one matmul (MXU) + row norms instead of
    the O(P·R·M) broadcast subtraction."""
    def impl(a, b):
        if p == 2.0 and compute_mode != "donot_use_mm_for_euclid_dist":
            a2 = jnp.sum(a * a, axis=-1)[..., :, None]
            b2 = jnp.sum(b * b, axis=-1)[..., None, :]
            ab = jnp.einsum("...pm,...rm->...pr", a, b)
            sq = jnp.maximum(a2 + b2 - 2.0 * ab, 0.0)
            return jnp.sqrt(sq + 1e-24)
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 0.0:
            return jnp.sum((d != 0).astype(a.dtype), axis=-1)
        if p == float("inf"):
            return jnp.max(jnp.abs(d), axis=-1)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return apply_op("cdist", impl, (x, y), {})


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization (reference `operators/lu_op.cc`). Returns
    (LU, pivots[, infos]) with 1-based pivots like the reference."""
    def impl(v):
        lu_mat, piv = jax.scipy.linalg.lu_factor(v)
        return lu_mat, (piv + 1).astype("int32")
    out = apply_op("lu", impl, (x,), {})
    if get_infos:
        infos = Tensor(jnp.zeros(x.shape[:-2] or (1,), "int32"))
        return out[0], out[1], infos
    return out


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack paddle.linalg.lu results into (P, L, U) (reference
    `operators/lu_unpack_op.cc`). Batched like the reference: leading
    dims are vmapped."""
    def one(lu_mat, piv):
        m, n = lu_mat.shape
        k = min(m, n)
        l_mat = jnp.tril(lu_mat[:, :k], -1) + jnp.eye(
            m, k, dtype=lu_mat.dtype)
        u_mat = jnp.triu(lu_mat[:k, :])
        # pivots (1-based sequential row swaps) → permutation matrix
        perm = jnp.arange(m)
        piv0 = piv.astype("int32") - 1

        def body(i, pr):
            j = piv0[i]
            pi, pj = pr[i], pr[j]
            return pr.at[i].set(pj).at[j].set(pi)
        perm = jax.lax.fori_loop(0, piv0.shape[-1], body, perm)
        p_mat = jnp.eye(m, dtype=lu_mat.dtype)[perm].T
        return p_mat, l_mat, u_mat

    def impl(lu_mat, piv):
        if lu_mat.ndim == 2:
            return one(lu_mat, piv)
        batch = lu_mat.shape[:-2]
        lu_f = lu_mat.reshape((-1,) + lu_mat.shape[-2:])
        piv_f = piv.reshape((-1, piv.shape[-1]))
        p, l, u = jax.vmap(one)(lu_f, piv_f)
        return (p.reshape(batch + p.shape[-2:]),
                l.reshape(batch + l.shape[-2:]),
                u.reshape(batch + u.shape[-2:]))
    return apply_op("lu_unpack", impl, (x, y), {})


def eig(x, name=None):
    """General (non-symmetric) eigendecomposition (reference
    `operators/eig_op.h`). XLA has no non-symmetric eig on TPU, so this
    runs as a host callback into LAPACK via numpy — the same
    CPU-kernel-only stance as the reference (eig_op registers CPU only).
    Returns (eigenvalues, eigenvectors), complex."""
    import numpy as _np

    def impl(v):
        cdt = jnp.complex64 if v.dtype in (jnp.float32, jnp.complex64) \
            else jnp.complex128
        n = v.shape[-1]
        out_shapes = (jax.ShapeDtypeStruct(v.shape[:-1], cdt),
                      jax.ShapeDtypeStruct(v.shape, cdt))

        def host_eig(a):
            w, vec = _np.linalg.eig(_np.asarray(a))
            return (_np.asarray(w, dtype=cdt),
                    _np.asarray(vec, dtype=cdt))
        return jax.pure_callback(host_eig, out_shapes, v, vmap_method="sequential")
    return apply_op("eig", impl, (x,), {})


def eigvals(x, name=None):
    return eig(x, name=name)[0]


__all__ += ["cdist", "lu", "lu_unpack", "eig", "eigvals"]
