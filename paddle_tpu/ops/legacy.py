"""Legacy (fluid-era) op aliases and tensor-array ops.

Reference surface: `python/paddle/fluid/layers/tensor.py` (fill_constant,
create_array/array_write/array_read, reverse, has_inf/has_nan),
`python/paddle/fluid/layers/nn.py` (reduce_* / elementwise_* families,
crop_tensor, shape, rank), `python/paddle/fluid/lod_tensor.py` (LoDTensor).
TPU-native design: all of these are thin jnp compositions over the modern op
library — one lowering path, no separate legacy kernels; LoD is carried as an
explicit offsets list next to a dense padded array (XLA needs static shapes).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dtype_mod
from ..framework.tensor import Tensor, apply_op, to_tensor
from . import creation, manipulation, math as _math, reduction

__all__ = [
    "add_n", "broadcast_shape", "crop_tensor", "fill_constant",
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_floordiv", "elementwise_mod", "elementwise_pow",
    "elementwise_max", "elementwise_min",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_all", "reduce_any", "has_inf", "has_nan", "rank", "shape",
    "reverse", "scatter_nd", "get_tensor_from_selected_rows",
    "merge_selected_rows", "create_array", "array_write", "array_read",
    "array_length", "tensor_array_to_tensor", "LoDTensor", "LoDTensorArray",
    "set_printoptions", "get_default_dtype", "set_default_dtype",
    "create_parameter", "create_global_var",
    # fluid-era op surface (round-5 gap closers; ops/extra_ops.py)
    "affine_channel", "row_conv", "conv_shift", "cvm", "data_norm",
    "space_to_depth", "pad_constant_like", "partial_concat", "partial_sum",
    "l1_norm", "squared_l2_norm", "rank_loss", "bpr_loss", "center_loss",
    "hinge_loss", "im2sequence", "linear_chain_crf", "shuffle_batch",
    "gather_tree", "affine_grid", "temporal_shift", "fsp",
    "cross_entropy2", "psroi_pool", "prroi_pool", "correlation", "nce",
    "deformable_conv", "lod_reset", "sequence_reshape", "sequence_slice",
    "sequence_scatter", "batch_fc", "sample_logits", "filter_by_instag",
    "var_conv_2d", "tree_conv", "bilateral_slice", "Print",
    "rank_attention", "search_pyramid_hash", "pyramid_hash",
]

from .extra_ops import (affine_channel, affine_grid, bpr_loss,  # noqa: E402
                        center_loss, conv_shift, correlation,
                        cross_entropy2, cvm, data_norm, deformable_conv,
                        fsp, gather_tree, hinge_loss, im2sequence,
                        l1_norm, linear_chain_crf, nce, pad_constant_like,
                        partial_concat, partial_sum, prroi_pool,
                        psroi_pool, rank_loss, row_conv, shuffle_batch,
                        space_to_depth, squared_l2_norm, temporal_shift)
from .extra_ops import (batch_fc, bilateral_slice,  # noqa: E402
                        filter_by_instag, rank_attention, sample_logits,
                        tree_conv, var_conv_2d)


# --------------------------------------------------------------------------
# default dtype (paddle.set_default_dtype)

def set_default_dtype(d):
    _dtype_mod.set_default_float_dtype(d)


def get_default_dtype():
    return _dtype_mod.default_float_dtype().name


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Reference: `python/paddle/tensor/to_string.py`. Maps onto numpy's
    printoptions — Tensor repr prints via numpy."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# --------------------------------------------------------------------------
# elementwise_* / reduce_* legacy names

def _axis_broadcast(x, y, axis):
    """fluid elementwise ops allowed mid-rank broadcast via `axis`."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    if axis != -1 and yv.ndim < xv.ndim:
        shape = [1] * xv.ndim
        shape[axis:axis + yv.ndim] = yv.shape
        y = manipulation.reshape(y, shape)
    return x, y


def _elementwise(name, fn):
    def op(x, y, axis=-1, act=None, name=None):
        x, y = _axis_broadcast(x, y, axis)
        out = apply_op(f"elementwise_{name}", fn, (x, y), {})
        if act is not None:
            from ..nn import functional as F
            out = getattr(F, act)(out)
        return out
    op.__name__ = f"elementwise_{name}"
    return op


elementwise_add = _elementwise("add", jnp.add)
elementwise_sub = _elementwise("sub", jnp.subtract)
elementwise_mul = _elementwise("mul", jnp.multiply)
elementwise_div = _elementwise("div", jnp.divide)
elementwise_floordiv = _elementwise("floordiv", jnp.floor_divide)
elementwise_mod = _elementwise("mod", jnp.mod)
elementwise_pow = _elementwise("pow", jnp.power)
elementwise_max = _elementwise("max", jnp.maximum)
elementwise_min = _elementwise("min", jnp.minimum)


def _reduce(new_fn):
    def op(input, dim=None, keep_dim=False, name=None):
        return new_fn(input, axis=dim, keepdim=keep_dim)
    return op


reduce_sum = _reduce(reduction.sum)
reduce_mean = _reduce(reduction.mean)
reduce_max = _reduce(reduction.max)
reduce_min = _reduce(reduction.min)
reduce_prod = _reduce(reduction.prod)
reduce_all = _reduce(reduction.all)
reduce_any = _reduce(reduction.any)


# --------------------------------------------------------------------------
# misc tensor ops

def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    def impl(*vs):
        out = vs[0]
        for v in vs[1:]:
            out = out + v
        return out
    return apply_op("add_n", impl, tuple(inputs), {})


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    t = creation.full(shape, value, dtype=dtype)
    if out is not None:
        out.set_value(t._value)
        return out
    return t


def crop_tensor(x, shape=None, offsets=None, name=None):
    xshape = list(x.shape)
    shape = list(shape) if shape is not None else xshape
    shape = [xshape[i] if s in (-1, None) else int(s)
             for i, s in enumerate(shape)]
    offsets = list(offsets) if offsets is not None else [0] * len(xshape)
    def impl(v):
        sl = tuple(slice(int(o), int(o) + int(s))
                   for o, s in zip(offsets, shape))
        return v[sl]
    return apply_op("crop_tensor", impl, (x,), {})


def has_inf(x, name=None):
    return apply_op("has_inf", lambda v: jnp.isinf(v).any(), (x,), {})


def has_nan(x, name=None):
    return apply_op("has_nan", lambda v: jnp.isnan(v).any(), (x,), {})


def rank(input, name=None):
    return to_tensor(np.asarray(input.ndim, np.int32))


def shape(input, name=None):
    return to_tensor(np.asarray(input.shape, np.int32))


def reverse(x, axis, name=None):
    return manipulation.flip(x, axis)


def scatter_nd(index, updates, shape, name=None):
    zeros = creation.zeros(shape, dtype=updates.dtype)
    return manipulation.scatter_nd_add(zeros, index, updates)


def get_tensor_from_selected_rows(x, name=None):
    """reference `operators/get_tensor_from_selected_rows_op.cc`:
    materialize a SelectedRows into its dense tensor. Dense tensors pass
    through (the in-jit path never produces SelectedRows — scatter-add
    into dense is what XLA fuses)."""
    from ..framework.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        return Tensor(jnp.asarray(x.to_dense()))
    return x


def merge_selected_rows(x, name=None):
    """reference `operators/merge_selected_rows_op.cc`: sum duplicate
    row ids."""
    from ..framework.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        return x.merge()
    return x


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference: `python/paddle/fluid/layers/tensor.py` create_parameter."""
    from ..framework.tensor import Parameter
    from ..nn import initializer as init
    ini = default_initializer
    if ini is None:
        ini = init.Constant(0.0) if is_bias else init.XavierNormal()
    val = ini(shape, dtype)
    v = val._value if isinstance(val, Tensor) else jnp.asarray(val)
    return Parameter(v, name=name)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..static import program as _prog
    t = creation.full(shape, value, dtype=dtype)
    t.persistable = persistable
    if name:
        t.name = name
    return t


# --------------------------------------------------------------------------
# tensor arrays (reference: LoDTensorArray + layers/control_flow array ops)

class LoDTensorArray(list):
    """Python-list-backed tensor array. The reference used a C++
    vector<LoDTensor> variable type for while-loop state; under XLA, loop
    state must be a fixed pytree, so eager mode keeps a list and
    `tensor_array_to_tensor` materialises it for compiled code."""


class LoDTensor(Tensor):
    """Dense tensor + LoD offsets (`framework/lod_tensor.h:114`). Kept for
    API parity; variable-length batches on TPU use padded dense + mask."""

    def __init__(self, value=None, lod=None):
        if value is None:
            value = np.zeros((0,), np.float32)
        super().__init__(jnp.asarray(value))
        self._lod = lod or []

    def lod(self):
        return self._lod

    def set_lod(self, lod):
        self._lod = lod

    def recursive_sequence_lengths(self):
        return [[b - a for a, b in zip(level[:-1], level[1:])]
                for level in self._lod]


def create_array(dtype="float32", initialized_list=None):
    arr = LoDTensorArray()
    if initialized_list:
        arr.extend(initialized_list)
    return arr


def array_write(x, i, array=None):
    if array is None:
        array = create_array()
    idx = int(i)
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    return array[int(i)]


def array_length(array):
    return to_tensor(np.asarray(len(array), np.int64))


def tensor_array_to_tensor(input, axis=1, use_stack=False, name=None):
    op = manipulation.stack if use_stack else manipulation.concat
    out = op(list(input), axis=axis)
    sizes = np.asarray([t.shape[axis] if not use_stack else 1
                        for t in input], np.int32)
    return out, to_tensor(sizes)


# ---------------------------------------------------------------------------
# LoD sequence ops (reference `operators/sequence_ops/*.cc`). Fluid-era
# models run these eagerly over LoDTensor (concat-of-sequences + offsets);
# compiled TPU models use padded-dense + sequence_mask instead, so these
# are host-side conveniences, not jit surfaces.
# ---------------------------------------------------------------------------

def _seq_offsets(x):
    lod = x.lod() if isinstance(x, LoDTensor) else []
    if not lod:
        raise ValueError("sequence op needs a LoDTensor with level-0 LoD")
    return list(lod[0])


def sequence_pad(x, pad_value=0.0, maxlen=None, name=None):
    """(LoDTensor rows) → (padded [N, maxlen, ...], lengths [N])
    (reference `sequence_pad_op.cc`)."""
    offs = _seq_offsets(x)
    v = np.asarray(x._value)
    lens = [b - a for a, b in zip(offs[:-1], offs[1:])]
    m = maxlen or max(lens)
    out = np.full((len(lens), m) + v.shape[1:], pad_value, v.dtype)
    for i, (a, b) in enumerate(zip(offs[:-1], offs[1:])):
        out[i, :b - a] = v[a:b]
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.asarray(lens, np.int64))))


def sequence_unpad(x, length, name=None):
    """Inverse of sequence_pad (reference `sequence_unpad_op.cc`)."""
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    lens = np.asarray(length._value if isinstance(length, Tensor)
                      else length).astype(np.int64)
    rows = np.concatenate([v[i, :l] for i, l in enumerate(lens)], axis=0)
    offs = np.concatenate([[0], np.cumsum(lens)]).tolist()
    return LoDTensor(rows, lod=[offs])


def sequence_pool(input, pool_type="average", name=None):
    """Per-sequence pooling (reference `sequence_pool_op.cc`):
    sum/average/sqrt/max/min/last/first."""
    offs = _seq_offsets(input)
    v = np.asarray(input._value)
    p = pool_type.lower()
    if p not in ("sum", "average", "mean", "sqrt", "max", "min", "last",
                 "first"):
        raise ValueError(f"unknown pool_type {pool_type!r}")
    outs = []
    for a, b in zip(offs[:-1], offs[1:]):
        if b == a:
            # empty sequences are legal LoD; reference pads them with 0.0
            outs.append(np.zeros(v.shape[1:], v.dtype))
            continue
        seg = v[a:b]
        if p == "sum":
            outs.append(seg.sum(0))
        elif p in ("average", "mean"):
            outs.append(seg.mean(0))
        elif p == "sqrt":
            outs.append(seg.sum(0) / np.sqrt(b - a))
        elif p == "max":
            outs.append(seg.max(0))
        elif p == "min":
            outs.append(seg.min(0))
        elif p == "last":
            outs.append(seg[-1])
        elif p == "first":
            outs.append(seg[0])
    return Tensor(jnp.asarray(np.stack(outs)))


def sequence_softmax(input, name=None):
    """Softmax within each sequence (reference
    `sequence_softmax_op.cc`)."""
    offs = _seq_offsets(input)
    v = np.asarray(input._value, np.float32)
    if v.ndim > 1 and v.shape[-1] != 1:
        # reference sequence_softmax_op enforces width-1 input
        raise ValueError(
            f"sequence_softmax requires input width 1, got {v.shape}")
    out = np.empty_like(v)
    for a, b in zip(offs[:-1], offs[1:]):
        if b == a:
            continue
        e = np.exp(v[a:b] - v[a:b].max())
        out[a:b] = e / e.sum()
    return LoDTensor(out, lod=input.lod())


def sequence_reverse(x, name=None):
    """Reverse rows inside each sequence (reference
    `sequence_reverse_op.h`)."""
    offs = _seq_offsets(x)
    v = np.asarray(x._value).copy()
    for a, b in zip(offs[:-1], offs[1:]):
        v[a:b] = v[a:b][::-1]
    return LoDTensor(v, lod=x.lod())


def sequence_concat(input, name=None):
    """Concatenate LoDTensors sequence-by-sequence (reference
    `sequence_concat_op.cc`)."""
    all_offs = [_seq_offsets(t) for t in input]
    n = len(all_offs[0]) - 1
    vals = [np.asarray(t._value) for t in input]
    rows, offs = [], [0]
    for i in range(n):
        for v, of in zip(vals, all_offs):
            rows.append(v[of[i]:of[i + 1]])
        offs.append(offs[-1] + sum(of[i + 1] - of[i] for of in all_offs))
    if not rows:
        return LoDTensor(np.zeros((0,) + vals[0].shape[1:],
                                  vals[0].dtype), lod=[offs])
    return LoDTensor(np.concatenate(rows, 0), lod=[offs])


def sequence_expand(x, y, ref_level=0, name=None):
    """Repeat each sequence of x to match y's LoD at ref_level
    (reference `sequence_expand_op.cc`)."""
    x_offs = _seq_offsets(x) if isinstance(x, LoDTensor) and x.lod() \
        else None
    y_offs = list(y.lod()[ref_level])
    v = np.asarray(x._value)
    n = len(y_offs) - 1
    rows, offs = [], [0]
    for i in range(n):
        reps = y_offs[i + 1] - y_offs[i]
        seg = v[x_offs[i]:x_offs[i + 1]] if x_offs is not None \
            else v[i:i + 1]
        for _ in range(reps):
            rows.append(seg)
        offs.append(offs[-1] + reps * seg.shape[0])
    if not rows:
        return LoDTensor(np.zeros((0,) + v.shape[1:], v.dtype), lod=[offs])
    return LoDTensor(np.concatenate(rows, 0), lod=[offs])


__all__ += ["sequence_pad", "sequence_unpad", "sequence_pool",
            "sequence_softmax", "sequence_reverse", "sequence_concat",
            "sequence_expand"]


def lod_reset(x, y=None, target_lod=None):
    """reference `lod_reset_op.cc`: replace x's LoD with y's (or the
    given offsets)."""
    if y is not None:
        lod = [_seq_offsets(y)] if isinstance(y, LoDTensor) else \
            [list(np.asarray(y.numpy()).astype(int))]
    elif target_lod is not None:
        lod = [list(target_lod)]
    else:
        raise ValueError("lod_reset needs y or target_lod")
    return LoDTensor(x._value if isinstance(x, Tensor) else x, lod)


def sequence_reshape(input, new_dim):
    """reference `sequence_reshape_op.cc`: re-chunk each sequence's
    flattened payload to rows of new_dim."""
    offs = _seq_offsets(input)
    v = np.asarray(input._value)
    old_dim = v.shape[1]
    new_offs = [0]
    rows = []
    for a, b in zip(offs[:-1], offs[1:]):
        payload = v[a:b].reshape(-1)
        assert payload.size % new_dim == 0, \
            "sequence payload not divisible by new_dim"
        rows.append(payload.reshape(-1, new_dim))
        new_offs.append(new_offs[-1] + rows[-1].shape[0])
    return LoDTensor(jnp.asarray(np.concatenate(rows, 0)), [new_offs])


def sequence_slice(input, offset, length):
    """reference `sequence_slice_op.cc`: per-sequence [offset, length)
    slices."""
    offs = _seq_offsets(input)
    v = np.asarray(input._value)
    off = np.asarray(offset.numpy() if isinstance(offset, Tensor)
                     else offset).reshape(-1).astype(int)
    ln = np.asarray(length.numpy() if isinstance(length, Tensor)
                    else length).reshape(-1).astype(int)
    rows = []
    new_offs = [0]
    for i, (a, b) in enumerate(zip(offs[:-1], offs[1:])):
        rows.append(v[a + off[i]:a + off[i] + ln[i]])
        new_offs.append(new_offs[-1] + rows[-1].shape[0])
    return LoDTensor(jnp.asarray(np.concatenate(rows, 0)), [new_offs])


def sequence_scatter(input, index, updates):
    """reference `sequence_scatter_op.cc`: add `updates` rows into
    `input` at per-sequence `index` positions (sequence i of the LoD
    pair addresses row i of the dense input)."""
    out = np.array(np.asarray(input._value), copy=True)
    offs = _seq_offsets(index)
    iv = np.asarray(index._value).reshape(-1).astype(int)
    uv = np.asarray(updates._value)
    for i, (a, b) in enumerate(zip(offs[:-1], offs[1:])):
        # np.add.at accumulates duplicate indices (fancy += would not)
        np.add.at(out[i], iv[a:b],
                  uv[a:b] if uv.ndim == 1 else uv[a:b, 0])
    return Tensor(jnp.asarray(out))


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both", name=None):
    """reference `operators/print_op.cc` / fluid.layers.Print: log the
    tensor value as a side effect and pass it through, honoring first_n
    (max print count) and summarize (max elements shown).

    Eager values print directly. Traced values (inside jit / a lowered
    static Program) print shape/dtype once at trace time WITHOUT runtime
    values: the axon TPU runtime rejects host callbacks
    (io_callback/debug.callback UNIMPLEMENTED), so a callback-based
    runtime print would crash compiled programs on the chip."""
    import jax

    msg = str(message or getattr(input, "name", None) or "var")
    state = {"n": 0}

    def fmt(arr_like, values=None):
        parts = [msg] if print_tensor_name else []
        if print_tensor_shape:
            parts.append(f"shape={tuple(arr_like.shape)}")
        if print_tensor_type:
            parts.append(f"dtype={arr_like.dtype}")
        head = " ".join(parts)
        return head if values is None else f"{head} value={values}"

    def impl(v):
        from ..static import program as _prog
        if not isinstance(v, jax.core.Tracer) and _prog.in_static_mode():
            # Program-build placeholder pass: stay silent, don't count
            return v
        if 0 <= first_n <= state["n"]:
            return v
        state["n"] += 1
        if isinstance(v, jax.core.Tracer):
            print(fmt(v) + " (traced: values print is unavailable — the "
                  "axon runtime has no host callbacks)", flush=True)
        else:
            arr = np.asarray(v)
            # reference contract: negative summarize means "print all"
            flat = arr.ravel() if summarize < 0 \
                else arr.ravel()[:summarize]
            print(fmt(arr, flat), flush=True)
        return v
    return apply_op("print", impl, (input,), {})


def _xxh32(data: bytes, seed: int = 0) -> int:
    """XXH32 (public spec) — the hash pyramid_hash_op.h uses via
    <xxhash.h>; pure-Python so the op works with zero native deps."""
    P1, P2, P3, P4, P5 = (2654435761, 2246822519, 3266489917,
                          668265263, 374761393)
    M = 0xFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (32 - r))) & M

    n = len(data)
    i = 0
    if n >= 16:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed & M
        v4 = (seed - P1) & M
        while i <= n - 16:
            for j, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + 4 * j:i + 4 * j + 4],
                                      "little")
                v = (v + lane * P2) & M
                v = (rotl(v, 13) * P1) & M
                if j == 0:
                    v1 = v
                elif j == 1:
                    v2 = v
                elif j == 2:
                    v3 = v
                else:
                    v4 = v
            i += 16
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i <= n - 4:
        h = (h + int.from_bytes(data[i:i + 4], "little") * P3) & M
        h = (rotl(h, 17) * P4) & M
        i += 4
    while i < n:
        h = (h + data[i] * P5) & M
        h = (rotl(h, 11) * P1) & M
        i += 1
    h ^= h >> 15
    h = (h * P2) & M
    h ^= h >> 13
    h = (h * P3) & M
    h ^= h >> 16
    return h


_PYRAMID_RNGS = {}


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,
                        drop_out_percent=0.0, is_training=False,
                        use_filter=False, white_list=None, black_list=None,
                        seed=0, weights=None, name=None):
    """reference `operators/pyramid_hash_op.cc`
    (fluid.contrib.layers.search_pyramid_hash): hash every n-gram window
    (lengths 2..pyramid_layer) of an int-id LoD sequence with XXH32 and
    assemble a num_emb embedding from rand_len-wide chunks of the flat
    weight table at the chained hash offsets — the massive-vocabulary
    embedding trick of the text-matching models.

    Returns a LoDTensor with one embedding row per surviving n-gram;
    gradients flow to `weights` (the hash positions are host-computed,
    the gather is a recorded differentiable op). Deviation from the
    reference: white/black lists filter by EXACT membership of the
    n-gram hash instead of a bloom filter (no false positives;
    documented simplification)."""
    assert num_emb % rand_len == 0, "num_emb must be divisible by rand_len"
    w_t = weights if isinstance(weights, Tensor) else \
        Tensor(jnp.asarray(np.asarray(weights, np.float32).reshape(-1)))
    W_len = int(np.prod(w_t.shape))
    assert W_len >= space_len + rand_len, \
        "weights must hold space_len + rand_len floats"
    offs = _seq_offsets(input)
    ids = np.asarray(input._value).reshape(-1).astype(np.int32)
    white = set(int(x) for x in np.asarray(white_list).ravel()) \
        if (use_filter and white_list is not None) else None
    black = set(int(x) for x in np.asarray(black_list).ravel()) \
        if (use_filter and black_list is not None) else None
    # persistent per-seed RNG (the reference advances a member seed with
    # rand_r across calls — a fresh RandomState per call would drop the
    # SAME grams every training step)
    rng = _PYRAMID_RNGS.setdefault(int(seed),
                                   np.random.RandomState(int(seed) or 1))

    gather_rows, new_offs = [], [0]
    for a, b in zip(offs[:-1], offs[1:]):
        seq = ids[a:b]
        count = 0
        for win in range(2, int(pyramid_layer) + 1):
            for st in range(0, len(seq) - win + 1):
                gram = seq[st:st + win].astype(np.float32).tobytes()
                key = _xxh32(gram, 0)
                if white is not None and key not in white:
                    continue
                if black is not None and key in black:
                    continue
                # reference scale: drop_out_percent is 0-100
                # (rand % 100 > percent keeps the gram)
                if is_training and drop_out_percent > 0 and \
                        not rng.randint(0, 100) > drop_out_percent:
                    continue
                idx = np.empty(num_emb, np.int64)
                pos1 = key % space_len
                pos2 = _xxh32(gram, rand_len) % space_len
                for j in range(0, num_emb, rand_len):
                    pos3 = _xxh32(gram, j + 2 * rand_len) % space_len
                    idx[j:j + rand_len] = np.arange(pos1, pos1 + rand_len)
                    pos1, pos2 = pos2, pos3
                gather_rows.append(idx)
                count += 1
        new_offs.append(new_offs[-1] + count)

    if gather_rows:
        idx_mat = jnp.asarray(np.stack(gather_rows))

        def impl(w):
            return jnp.take(w.reshape(-1), idx_mat, axis=0)
        out = apply_op("pyramid_hash", impl, (w_t,), {})
    else:
        out = Tensor(jnp.zeros((0, num_emb), jnp.float32))
    # keep the autograd tape: re-class the op output instead of
    # constructing a fresh LoDTensor from raw values
    out.__class__ = LoDTensor
    out._lod = [new_offs]
    return out


pyramid_hash = search_pyramid_hash
