"""Legacy (fluid-era) op aliases and tensor-array ops.

Reference surface: `python/paddle/fluid/layers/tensor.py` (fill_constant,
create_array/array_write/array_read, reverse, has_inf/has_nan),
`python/paddle/fluid/layers/nn.py` (reduce_* / elementwise_* families,
crop_tensor, shape, rank), `python/paddle/fluid/lod_tensor.py` (LoDTensor).
TPU-native design: all of these are thin jnp compositions over the modern op
library — one lowering path, no separate legacy kernels; LoD is carried as an
explicit offsets list next to a dense padded array (XLA needs static shapes).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as _dtype_mod
from ..framework.tensor import Tensor, apply_op, to_tensor
from . import creation, manipulation, math as _math, reduction

__all__ = [
    "add_n", "broadcast_shape", "crop_tensor", "fill_constant",
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_floordiv", "elementwise_mod", "elementwise_pow",
    "elementwise_max", "elementwise_min",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_all", "reduce_any", "has_inf", "has_nan", "rank", "shape",
    "reverse", "scatter_nd", "get_tensor_from_selected_rows",
    "merge_selected_rows", "create_array", "array_write", "array_read",
    "array_length", "tensor_array_to_tensor", "LoDTensor", "LoDTensorArray",
    "set_printoptions", "get_default_dtype", "set_default_dtype",
    "create_parameter", "create_global_var",
]


# --------------------------------------------------------------------------
# default dtype (paddle.set_default_dtype)

def set_default_dtype(d):
    _dtype_mod.set_default_float_dtype(d)


def get_default_dtype():
    return _dtype_mod.default_float_dtype().name


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Reference: `python/paddle/tensor/to_string.py`. Maps onto numpy's
    printoptions — Tensor repr prints via numpy."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# --------------------------------------------------------------------------
# elementwise_* / reduce_* legacy names

def _axis_broadcast(x, y, axis):
    """fluid elementwise ops allowed mid-rank broadcast via `axis`."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    if axis != -1 and yv.ndim < xv.ndim:
        shape = [1] * xv.ndim
        shape[axis:axis + yv.ndim] = yv.shape
        y = manipulation.reshape(y, shape)
    return x, y


def _elementwise(name, fn):
    def op(x, y, axis=-1, act=None, name=None):
        x, y = _axis_broadcast(x, y, axis)
        out = apply_op(f"elementwise_{name}", fn, (x, y), {})
        if act is not None:
            from ..nn import functional as F
            out = getattr(F, act)(out)
        return out
    op.__name__ = f"elementwise_{name}"
    return op


elementwise_add = _elementwise("add", jnp.add)
elementwise_sub = _elementwise("sub", jnp.subtract)
elementwise_mul = _elementwise("mul", jnp.multiply)
elementwise_div = _elementwise("div", jnp.divide)
elementwise_floordiv = _elementwise("floordiv", jnp.floor_divide)
elementwise_mod = _elementwise("mod", jnp.mod)
elementwise_pow = _elementwise("pow", jnp.power)
elementwise_max = _elementwise("max", jnp.maximum)
elementwise_min = _elementwise("min", jnp.minimum)


def _reduce(new_fn):
    def op(input, dim=None, keep_dim=False, name=None):
        return new_fn(input, axis=dim, keepdim=keep_dim)
    return op


reduce_sum = _reduce(reduction.sum)
reduce_mean = _reduce(reduction.mean)
reduce_max = _reduce(reduction.max)
reduce_min = _reduce(reduction.min)
reduce_prod = _reduce(reduction.prod)
reduce_all = _reduce(reduction.all)
reduce_any = _reduce(reduction.any)


# --------------------------------------------------------------------------
# misc tensor ops

def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    def impl(*vs):
        out = vs[0]
        for v in vs[1:]:
            out = out + v
        return out
    return apply_op("add_n", impl, tuple(inputs), {})


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    t = creation.full(shape, value, dtype=dtype)
    if out is not None:
        out.set_value(t._value)
        return out
    return t


def crop_tensor(x, shape=None, offsets=None, name=None):
    xshape = list(x.shape)
    shape = list(shape) if shape is not None else xshape
    shape = [xshape[i] if s in (-1, None) else int(s)
             for i, s in enumerate(shape)]
    offsets = list(offsets) if offsets is not None else [0] * len(xshape)
    def impl(v):
        sl = tuple(slice(int(o), int(o) + int(s))
                   for o, s in zip(offsets, shape))
        return v[sl]
    return apply_op("crop_tensor", impl, (x,), {})


def has_inf(x, name=None):
    return apply_op("has_inf", lambda v: jnp.isinf(v).any(), (x,), {})


def has_nan(x, name=None):
    return apply_op("has_nan", lambda v: jnp.isnan(v).any(), (x,), {})


def rank(input, name=None):
    return to_tensor(np.asarray(input.ndim, np.int32))


def shape(input, name=None):
    return to_tensor(np.asarray(input.shape, np.int32))


def reverse(x, axis, name=None):
    return manipulation.flip(x, axis)


def scatter_nd(index, updates, shape, name=None):
    zeros = creation.zeros(shape, dtype=updates.dtype)
    return manipulation.scatter_nd_add(zeros, index, updates)


def get_tensor_from_selected_rows(x, name=None):
    """SelectedRows (`framework/selected_rows.h`) was CUDA-side sparse-row
    storage; here sparse grads are dense-with-zero-rows, so this is identity."""
    return x


def merge_selected_rows(x, name=None):
    return x


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference: `python/paddle/fluid/layers/tensor.py` create_parameter."""
    from ..framework.tensor import Parameter
    from ..nn import initializer as init
    ini = default_initializer
    if ini is None:
        ini = init.Constant(0.0) if is_bias else init.XavierNormal()
    val = ini(shape, dtype)
    v = val._value if isinstance(val, Tensor) else jnp.asarray(val)
    return Parameter(v, name=name)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..static import program as _prog
    t = creation.full(shape, value, dtype=dtype)
    t.persistable = persistable
    if name:
        t.name = name
    return t


# --------------------------------------------------------------------------
# tensor arrays (reference: LoDTensorArray + layers/control_flow array ops)

class LoDTensorArray(list):
    """Python-list-backed tensor array. The reference used a C++
    vector<LoDTensor> variable type for while-loop state; under XLA, loop
    state must be a fixed pytree, so eager mode keeps a list and
    `tensor_array_to_tensor` materialises it for compiled code."""


class LoDTensor(Tensor):
    """Dense tensor + LoD offsets (`framework/lod_tensor.h:114`). Kept for
    API parity; variable-length batches on TPU use padded dense + mask."""

    def __init__(self, value=None, lod=None):
        if value is None:
            value = np.zeros((0,), np.float32)
        super().__init__(jnp.asarray(value))
        self._lod = lod or []

    def lod(self):
        return self._lod

    def set_lod(self, lod):
        self._lod = lod

    def recursive_sequence_lengths(self):
        return [[b - a for a, b in zip(level[:-1], level[1:])]
                for level in self._lod]


def create_array(dtype="float32", initialized_list=None):
    arr = LoDTensorArray()
    if initialized_list:
        arr.extend(initialized_list)
    return arr


def array_write(x, i, array=None):
    if array is None:
        array = create_array()
    idx = int(i)
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    return array[int(i)]


def array_length(array):
    return to_tensor(np.asarray(len(array), np.int64))


def tensor_array_to_tensor(input, axis=1, use_stack=False, name=None):
    op = manipulation.stack if use_stack else manipulation.concat
    out = op(list(input), axis=axis)
    sizes = np.asarray([t.shape[axis] if not use_stack else 1
                        for t in input], np.int32)
    return out, to_tensor(sizes)
