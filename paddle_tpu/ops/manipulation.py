"""Shape/layout manipulation ops (reference
`python/paddle/tensor/manipulation.py`, kernels across
`paddle/fluid/operators/`). All static-shape friendly ⇒ jit/pjit-safe,
except the documented dynamic-shape ops (nonzero/unique/masked_select)
which are eager-only, mirroring the reference's LoD-style dynamism."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.dtype import to_jax_dtype
from ..framework.tensor import Tensor, apply_op

__all__ = [
    "reshape", "flatten", "transpose", "squeeze", "unsqueeze", "concat",
    "stack", "split", "chunk", "tile", "expand", "expand_as", "broadcast_to",
    "gather", "gather_nd", "scatter", "scatter_nd_add", "index_select",
    "masked_select", "where", "roll", "flip", "cast", "t", "moveaxis",
    "unbind", "repeat_interleave", "take_along_axis", "put_along_axis",
    "slice", "strided_slice", "unique", "nonzero", "pad", "flip", "rot90",
    "unstack", "crop", "shard_index", "broadcast_tensors", "atleast_1d",
    "as_real", "as_complex", "tensordot", "masked_fill", "index_put",
    "index_add", "diagonal", "one_hot",
]


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(x) for x in np.asarray(v._value))
    if isinstance(v, (int, np.integer)):
        return (int(v),)
    return tuple(int(x.item() if isinstance(x, Tensor) else x) for x in v)


def reshape(x, shape, name=None):
    return apply_op("reshape", lambda v: jnp.reshape(v, _ints(shape)), (x,), {})


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def impl(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, new)
    return apply_op("flatten", impl, (x,), {})


def transpose(x, perm, name=None):
    return apply_op("transpose", lambda v: jnp.transpose(v, _ints(perm)),
                    (x,), {})


def t(x, name=None):
    def impl(v):
        if v.ndim < 2:
            return v
        return jnp.swapaxes(v, -1, -2)
    return apply_op("t", impl, (x,), {})


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis",
                    lambda v: jnp.moveaxis(v, source, destination), (x,), {})


def squeeze(x, axis=None, name=None):
    def impl(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = _ints(axis)
        axes = tuple(a % v.ndim for a in axes)
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v
    return apply_op("squeeze", impl, (x,), {})


def unsqueeze(x, axis, name=None):
    def impl(v):
        out = v
        for a in sorted(_ints(axis)):
            out = jnp.expand_dims(out, a)
        return out
    return apply_op("unsqueeze", impl, (x,), {})


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    tensors = list(x)
    return apply_op("concat",
                    lambda *vs: jnp.concatenate(vs, axis=axis), tuple(tensors),
                    {})


def stack(x, axis=0, name=None):
    return apply_op("stack", lambda *vs: jnp.stack(vs, axis=axis),
                    tuple(x), {})


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    return list(apply_op(
        "unbind",
        lambda v: tuple(jnp.moveaxis(v, axis, 0)[i] for i in range(n)),
        (x,), {}))


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    return apply_op("unstack",
                    lambda v: tuple(jnp.moveaxis(v, axis, 0)[i]
                                    for i in range(n)), (x,), {})


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def impl(v):
        dim = v.shape[axis]
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=axis))
        secs = [s.item() if isinstance(s, Tensor) else s
                for s in num_or_sections]
        known = [s for s in secs if s != -1]
        secs = [s if s != -1 else dim - int(np.sum(known)) for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        return tuple(jnp.split(v, idx, axis=axis))
    return apply_op("split", impl, (x,), {})


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tile(x, repeat_times, name=None):
    return apply_op("tile", lambda v: jnp.tile(v, _ints(repeat_times)),
                    (x,), {})


def broadcast_to(x, shape, name=None):
    return apply_op("broadcast_to",
                    lambda v: jnp.broadcast_to(v, _ints(shape)), (x,), {})


def expand(x, shape, name=None):
    def impl(v):
        target = list(_ints(shape))
        # paddle expand: -1 keeps original dim
        nd = len(target)
        vshape = (1,) * (nd - v.ndim) + v.shape
        target = [vs if t == -1 else t for t, vs in zip(target, vshape)]
        return jnp.broadcast_to(jnp.reshape(v, vshape), target)
    return apply_op("expand", impl, (x,), {})


def expand_as(x, y, name=None):
    return apply_op("expand_as",
                    lambda v, w: jnp.broadcast_to(v, w.shape), (x, y), {})


def cast(x, dtype):
    dt = to_jax_dtype(dtype)
    return apply_op("cast", lambda v: v.astype(dt), (x,), {})


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op("gather",
                    lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i,
                                          axis=axis), (x, index), {})


def gather_nd(x, index, name=None):
    def impl(v, idx):
        # reference operators/gather_nd_op: idx last dim indexes leading dims
        return v[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply_op("gather_nd", impl, (x, index), {})


def scatter(x, index, updates, overwrite=True, name=None):
    def impl(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            # paddle semantics: later rows win; .set gives that
            return v.at[i].set(u)
        base = v.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)
    return apply_op("scatter", impl, (x, index, updates), {})


def scatter_nd_add(x, index, updates, name=None):
    def impl(v, i, u):
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply_op("scatter_nd_add", impl, (x, index, updates), {})


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select",
                    lambda v, i: jnp.take(v, i, axis=axis), (x, index), {})


def index_add(x, index, axis, value, name=None):
    def impl(v, i, u):
        return jnp.apply_along_axis  # placeholder never hit
    def impl2(v, i, u):
        vm = jnp.moveaxis(v, axis, 0)
        um = jnp.moveaxis(u, axis, 0)
        out = vm.at[i].add(um)
        return jnp.moveaxis(out, 0, axis)
    return apply_op("index_add", impl2, (x, index, value), {})


def index_put(x, indices, value, accumulate=False, name=None):
    def impl(v, u, *idx):
        ref = v.at[tuple(idx)]
        return ref.add(u) if accumulate else ref.set(u)
    return apply_op("index_put", impl, (x, value, *indices), {})


def masked_select(x, mask, name=None):
    # dynamic output shape ⇒ the mask must be concrete (eager-only), but
    # once known the pick indices are static — gradient flows via take
    m = np.asarray(mask._value if isinstance(mask, Tensor) else mask)
    m = np.broadcast_to(m, np.shape(x._value))
    picks = np.flatnonzero(m.reshape(-1))
    return apply_op("masked_select",
                    lambda v: jnp.take(v.reshape(-1), picks), (x,), {})


def masked_fill(x, mask, value, name=None):
    val = value._value if isinstance(value, Tensor) else value
    return apply_op("masked_fill",
                    lambda v, m: jnp.where(m, jnp.asarray(val, v.dtype), v),
                    (x, mask), {})


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply_op("where",
                    lambda c, a, b: jnp.where(c, a, b), (condition, x, y), {})


def nonzero(x, as_tuple=False):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    nz = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.reshape(-1, 1))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    v = np.asarray(x._value)
    res = np.unique(v, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(out)


def roll(x, shifts, axis=None, name=None):
    return apply_op("roll", lambda v: jnp.roll(v, shifts, axis=axis), (x,), {})


def flip(x, axis, name=None):
    ax = _ints(axis) if not isinstance(axis, int) else (axis,)
    return apply_op("flip", lambda v: jnp.flip(v, axis=ax), (x,), {})


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)),
                    (x,), {})


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats._value if isinstance(repeats, Tensor) else repeats
    return apply_op("repeat_interleave",
                    lambda v: jnp.repeat(v, r, axis=axis), (x,), {})


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op("take_along_axis",
                    lambda v, i: jnp.take_along_axis(v, i, axis=axis),
                    (arr, indices), {})


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def impl(v, i, u):
        u = jnp.broadcast_to(jnp.asarray(u, v.dtype), i.shape)
        vm = jnp.moveaxis(v, axis, 0)
        im = jnp.moveaxis(i, axis, 0)
        um = jnp.moveaxis(u, axis, 0)
        grid = jnp.indices(im.shape)[1:]
        ref = vm.at[(im, *grid)]
        out = ref.add(um) if reduce == "add" else (
            ref.multiply(um) if reduce == "mul" else ref.set(um))
        return jnp.moveaxis(out, 0, axis)
    vals = values if isinstance(values, Tensor) else Tensor(jnp.asarray(values))
    return apply_op("put_along_axis", impl, (arr, indices, vals), {})


import builtins

builtins_slice = builtins.slice


def slice(input, axes, starts, ends, name=None):
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)

    def impl(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins_slice(s, e)
        return v[tuple(idx)]
    return apply_op("slice", impl, (input,), {})


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes, starts, ends, strides = map(_ints, (axes, starts, ends, strides))

    def impl(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins_slice(s, e, st)
        return v[tuple(idx)]
    return apply_op("strided_slice", impl, (x,), {})


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pads = _ints(pad)

    def impl(v):
        nd = v.ndim
        if len(pads) == 2 * nd:
            width = [(pads[2 * i], pads[2 * i + 1]) for i in range(nd)]
        else:
            # paddle: pad applies to last len(pads)//2 spatial dims (NCHW/NHWC)
            width = [(0, 0)] * nd
            spatial = len(pads) // 2
            if data_format.endswith("C"):  # NHWC / NLC / NDHWC
                dims = list(range(1, 1 + spatial))
            else:
                dims = list(range(nd - spatial, nd))
            for j, d in enumerate(dims):
                width[d] = (pads[2 * j], pads[2 * j + 1])
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, width, mode="constant", constant_values=value)
        return jnp.pad(v, width, mode=jmode)
    return apply_op("pad", impl, (x,), {})


def crop(x, shape=None, offsets=None, name=None):
    shp = _ints(shape)
    offs = _ints(offsets) if offsets is not None else (0,) * len(shp)

    def impl(v):
        idx = tuple(builtins_slice(o, o + s) for o, s in zip(offs, shp))
        return v[idx]
    return apply_op("crop", impl, (x,), {})


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """reference `operators/shard_index_op` (used by parallel embedding)."""
    def impl(i):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        hi = lo + shard_size
        in_shard = (i >= lo) & (i < hi)
        return jnp.where(in_shard, i - lo, ignore_value)
    return apply_op("shard_index", impl, (input,), {})


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    target = np.broadcast_shapes(*shapes)
    return [broadcast_to(t, target) for t in inputs]


def atleast_1d(*inputs):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, (x,), {}) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def as_real(x, name=None):
    return apply_op("as_real",
                    lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], -1),
                    (x,), {})


def as_complex(x, name=None):
    return apply_op("as_complex",
                    lambda v: jax.lax.complex(v[..., 0], v[..., 1]), (x,), {})


def tensordot(x, y, axes=2, name=None):
    return apply_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes),
                    (x, y), {})


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal",
                    lambda v: jnp.diagonal(v, offset=offset, axis1=axis1,
                                           axis2=axis2), (x,), {})


def one_hot(x, num_classes, name=None):
    return apply_op("one_hot",
                    lambda v: jax.nn.one_hot(v, num_classes, dtype="float32"),
                    (x,), {})


def index_fill(x, index, axis, value, name=None):
    """reference `paddle.index_fill`."""
    def impl(v, i):
        moved = jnp.moveaxis(v, axis, 0)
        moved = moved.at[i].set(value)
        return jnp.moveaxis(moved, 0, axis)
    return apply_op("index_fill", impl, (x, index), {})


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """reference `paddle.diagonal_scatter`: write y into the diagonal."""
    def impl(v, w):
        n, m = v.shape[axis1], v.shape[axis2]
        rows = jnp.arange(max(n, m))
        if offset >= 0:
            r, c = rows[:min(n, m - offset)], rows[:min(n, m - offset)] + offset
        else:
            r, c = rows[:min(n + offset, m)] - offset, rows[:min(n + offset, m)]
        moved = jnp.moveaxis(v, (axis1, axis2), (0, 1))
        moved = moved.at[r, c].set(jnp.moveaxis(
            w, -1, 0) if w.ndim > 1 else w)
        return jnp.moveaxis(moved, (0, 1), (axis1, axis2))
    return apply_op("diagonal_scatter", impl, (x, y), {})


__all__ += ["index_fill", "diagonal_scatter"]
