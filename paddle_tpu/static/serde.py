"""Program IR serialization.

Reference: ProgramDesc ⊃ BlockDesc ⊃ OpDesc protobuf
(`paddle/fluid/framework/framework.proto:43-207`) and
`fluid/io.py:1199` save/load_inference_model.

TPU-native redesign: the op-level IR document is JSON — one entry per
recorded op with its type name, inspectable attrs, SSA slot wiring and
variable shapes/dtypes — and each op's *computation* is a serialized
`jax.export` StableHLO artifact (exported with vjp_order=1, so
`append_backward`/`jax.grad` still differentiate a loaded Program). That
replaces the reference's OpDesc+registered-kernel pair: the portable unit
on TPU is StableHLO, not a kernel name. The document round-trips across
processes: save → new interpreter → load → identical outputs.
"""
from __future__ import annotations

import base64
import io
import json
from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = ["save_program", "load_program", "program_to_doc",
           "program_from_doc"]

_VERSION = 1


def _npy_b64(arr) -> Dict[str, str]:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return {"npy_b64": base64.b64encode(buf.getvalue()).decode("ascii")}


def _npy_unb64(doc) -> np.ndarray:
    return np.load(io.BytesIO(base64.b64decode(doc["npy_b64"])),
                   allow_pickle=False)


def _json_safe_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in (attrs or {}).items():
        if isinstance(v, (bool, int, float, str, type(None))):
            out[k] = v
        elif isinstance(v, (list, tuple)) and all(
                isinstance(x, (bool, int, float, str)) for x in v):
            out[k] = list(v)
        else:
            out[k] = repr(v)
    return out


def _aval_of(value):
    import jax
    return jax.ShapeDtypeStruct(tuple(value.shape), value.dtype)


def program_to_doc(program, scope: Optional[Dict[str, np.ndarray]] = None,
                   include_params: bool = True) -> Dict[str, Any]:
    """Program → JSON-serializable document (OpDesc-level inspectable)."""
    import jax
    from jax import export as jexport

    var_docs = {}

    def note_var(slot):
        if slot in var_docs:
            return
        v = program.vars[slot]
        var_docs[slot] = {
            "name": getattr(v, "name", None),
            "shape": list(v._value.shape),
            "dtype": str(v._value.dtype),
            "is_param": bool(getattr(v, "is_param", False)),
            "is_feed": bool(getattr(v, "is_feed", False)),
        }

    # feeds/params must survive even when no recorded op consumes them yet
    # (e.g. a label feed declared for a later loss)
    for v in list(program.feed_vars.values()) + \
            list(program.param_vars.values()):
        note_var(v.slot)

    ops = []
    for op in program.ops:
        avals, in_docs = [], []
        for tag, ref in op.in_refs:
            if tag == "s":
                note_var(ref)
                avals.append(_aval_of(program.vars[ref]._value))
                in_docs.append(["s", ref])
            else:
                avals.append(_aval_of(ref))
                in_docs.append(["c", _npy_b64(ref)])
        for s in op.out_slots:
            note_var(s)
        exported = jexport.export(jax.jit(op.fn))(*avals)
        try:
            blob = exported.serialize(vjp_order=1)
        except Exception as e:
            # lax.while_loop has no reverse-mode rule — forward-only is
            # expected for `while` ops. Anything else is a lossy export
            # the user must hear about now, not at load+grad time.
            if op.name != "while":
                import warnings
                warnings.warn(
                    f"op '{op.name}' exported WITHOUT gradient support "
                    f"(vjp serialization failed: {e}); append_backward "
                    "on the loaded Program will not differentiate it")
            blob = exported.serialize(vjp_order=0)
        ops.append({
            "type": op.name,
            "attrs": _json_safe_attrs(getattr(op, "attrs", None)),
            "inputs": in_docs,
            "outputs": list(op.out_slots),
            "stablehlo_b64": base64.b64encode(blob).decode("ascii"),
        })

    doc = {
        "version": _VERSION,
        "ops": ops,
        "vars": {str(s): d for s, d in var_docs.items()},
        "feed_vars": {n: v.slot for n, v in program.feed_vars.items()},
        "param_vars": {n: v.slot for n, v in program.param_vars.items()},
    }
    # control-flow sub-blocks (reference BlockDesc nesting): structural
    # mirror only — execution replays block 0, whose fused lax op already
    # contains the branch computations
    if getattr(program, "num_blocks", 1) > 1:
        doc["blocks"] = [{
            "idx": b.idx,
            "parent_idx": b.parent_idx,
            "ops": [{
                "type": op.name,
                "attrs": _json_safe_attrs(op.attrs),
                "inputs": [["s", ref] if tag == "s" else
                           ["c", _npy_b64(ref)] for tag, ref in op.in_refs],
                "outputs": list(op.out_slots),
            } for op in b.ops],
        } for b in program.blocks[1:]]
    if hasattr(program, "_loss_slot"):
        doc["loss_slot"] = program._loss_slot
    if include_params and scope is not None:
        doc["params"] = {n: _npy_b64(scope[n])
                         for n in program.param_vars if n in scope}
    return doc


def program_from_doc(doc) -> Tuple[Any, Dict[str, np.ndarray]]:
    """JSON document → (Program, params_scope). Inverse of program_to_doc."""
    import jax.numpy as jnp
    from jax import export as jexport

    from ..framework.dtype import to_jax_dtype
    from .program import Program, Variable, _Op

    if doc.get("version") != _VERSION:
        raise ValueError(f"unsupported program doc version: "
                         f"{doc.get('version')!r}")
    from .program import _slot_counter

    program = Program()
    slot_to_var: Dict[int, Variable] = {}
    if doc["vars"]:
        # keep future slot allocations clear of the preserved ids so ops
        # recorded on the loaded program can't collide with loaded vars
        _slot_counter.advance_past(max(int(s) for s in doc["vars"]))
    for s_str, vd in doc["vars"].items():
        slot = int(s_str)
        v = Variable(jnp.zeros(tuple(vd["shape"]),
                               to_jax_dtype(vd["dtype"])),
                     name=vd.get("name"), is_param=vd["is_param"],
                     is_feed=vd["is_feed"])
        v.slot = slot   # preserve the saved SSA wiring
        slot_to_var[slot] = v
        program.vars[slot] = v
    for n, slot in doc["feed_vars"].items():
        program.feed_vars[n] = slot_to_var[slot]
    for n, slot in doc["param_vars"].items():
        program.param_vars[n] = slot_to_var[slot]
    if "loss_slot" in doc:
        program._loss_slot = doc["loss_slot"]

    for od in doc["ops"]:
        exported = jexport.deserialize(
            base64.b64decode(od["stablehlo_b64"]))
        in_refs = []
        for tag, ref in od["inputs"]:
            if tag == "s":
                in_refs.append(("s", int(ref)))
            else:
                in_refs.append(("c", jnp.asarray(_npy_unb64(ref))))
        op = _Op(od["type"], exported.call, in_refs, list(od["outputs"]))
        op.attrs = od.get("attrs") or {}
        program.ops.append(op)

    from .program import Block
    for bd in doc.get("blocks") or []:
        blk = Block(program, bd["idx"], bd["parent_idx"])
        for od in bd["ops"]:
            in_refs = [("s", int(r)) if t == "s" else
                       ("c", jnp.asarray(_npy_unb64(r)))
                       for t, r in od["inputs"]]
            op = _Op(od["type"], None, in_refs, list(od["outputs"]),
                     od.get("attrs") or {})
            blk.ops.append(op)
        program.blocks.append(blk)

    params = {n: _npy_unb64(d) for n, d in (doc.get("params") or {}).items()}
    program._doc_extra = doc.get("extra") or {}
    return program, params


def save_program(program, path: str, scope=None,
                 include_params: bool = True, extra=None) -> None:
    """Serialize a Program (and optionally its parameter values) to `path`
    (reference ProgramDesc.SerializeToString + save_persistables)."""
    from .program import global_scope
    scope = scope if scope is not None else global_scope()
    doc = program_to_doc(program, scope, include_params)
    if extra:
        doc["extra"] = extra
    with open(path, "w") as f:
        json.dump(doc, f)


def load_program(path: str):
    """Load a Program saved by save_program → (Program, params dict).
    Feed the params into a scope (or global_scope()) before Executor.run."""
    with open(path) as f:
        doc = json.load(f)
    return program_from_doc(doc)
