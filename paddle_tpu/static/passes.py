"""Program-level IR passes (reference `paddle/fluid/framework/ir/`
pass registry + `inference/analysis/` pass pipeline).

TPU stance: the heavy rewrites the reference implements as IR passes —
op fusion, layout, memory planning — are XLA's job after lowering, so
they are deliberately absent. What remains meaningful at THIS level is
graph hygiene on the op list before lowering/serialization:
constant folding (fewer ops to export in .ptprog) and dead-code
elimination (Program.prune). The PassManager mirrors the reference's
apply-in-sequence contract so tooling can be ported."""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

__all__ = ["PassManager", "constant_folding_pass",
           "dead_code_elimination_pass", "register_pass", "get_pass"]

_PASSES: Dict[str, Callable] = {}


def register_pass(name: str):
    def deco(fn):
        _PASSES[name] = fn
        return fn
    return deco


def get_pass(name: str) -> Callable:
    if name not in _PASSES:
        from ..framework.errors import NotFoundError
        raise NotFoundError(f"unknown pass {name!r}; have "
                            f"{sorted(_PASSES)}")
    return _PASSES[name]


@register_pass("constant_folding_pass")
def constant_folding_pass(program, targets=None):
    """Evaluate ops whose inputs are all constants and splice the results
    in as constants (reference
    `framework/ir/constant_folding_pass.cc`). Runs eagerly on host — only
    touches ops that depend on no feed/param."""
    folded_vals = {}
    new_ops = []
    for op in program.ops:
        ins = []
        all_const = True
        for tag, ref in op.in_refs:
            if tag == "c":
                ins.append(ref)
            elif ref in folded_vals:
                ins.append(folded_vals[ref])
            else:
                all_const = False
                break
        if not all_const:
            # rewrite any input slots that earlier folding produced
            refs = [("c", folded_vals[r]) if t == "s" and r in folded_vals
                    else (t, r) for t, r in op.in_refs]
            op.in_refs = refs
            new_ops.append(op)
            continue
        outs = op.fn(*ins)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        for s, v in zip(op.out_slots, outs):
            folded_vals[s] = v
            if s in program.vars:      # keep the fetch fallback in sync
                program.vars[s]._value = v
    program.ops = new_ops
    return program


@register_pass("dead_code_elimination_pass")
def dead_code_elimination_pass(program, targets=None):
    """Backward-slice to the target vars (Program.prune; reference
    `framework/prune.cc`). Without targets this is the identity — an op
    list has no other notion of liveness. Mutates `program` IN PLACE like
    every pass (callers commonly ignore apply()'s return value)."""
    if not targets:
        return program
    pruned = program.prune(targets)
    program.ops = pruned.ops
    program.feed_vars = pruned.feed_vars
    program.param_vars = pruned.param_vars
    return program


class PassManager:
    """Apply named passes in sequence (reference ir::PassRegistry +
    analysis Argument pipeline)."""

    def __init__(self, passes: List[str] = None):
        self.passes = list(passes or [])

    def apply(self, program, targets=None):
        for name in self.passes:
            program = get_pass(name)(program, targets=targets) or program
        return program
