"""paddle.static.nn — static-graph layer API (reference
`python/paddle/static/nn/` re-exporting `fluid/layers/nn.py` fc/conv2d/…).
Each builds the same Layers the dygraph API uses; in static mode their ops
record into the current Program."""
from __future__ import annotations

import contextlib
import weakref

import jax.numpy as jnp

from .. import nn as _nn
from ..framework.dtype import to_jax_dtype
from ..nn import functional as F

__all__ = ["fc", "conv2d", "conv3d", "batch_norm", "embedding", "dropout",
           "layer_norm", "conv2d_transpose", "cond", "while_loop",
           "switch_case", "case"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from ..ops.manipulation import flatten, reshape
    inp = x
    if num_flatten_dims > 1 or len(x.shape) > 2:
        inp = flatten(x, num_flatten_dims, -1) if num_flatten_dims >= 1 \
            else x
    layer = _nn.Linear(inp.shape[-1], size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    out = layer(inp)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None, use_cudnn=True):
    ch_axis = 1 if data_format == "NCHW" else -1
    layer = _nn.Conv2D(input.shape[ch_axis], num_filters, filter_size,
                       stride, padding, dilation, groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCDHW", name=None):
    layer = _nn.Conv3D(input.shape[1], num_filters, filter_size, stride,
                       padding, dilation, groups, weight_attr=param_attr,
                       bias_attr=bias_attr, data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None):
    layer = _nn.Conv2DTranspose(input.shape[1], num_filters, filter_size,
                                stride, padding, dilation=dilation,
                                groups=groups, weight_attr=param_attr,
                                bias_attr=bias_attr,
                                data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None, **kwargs):
    ch = input.shape[1 if data_layout == "NCHW" else -1]
    layer = _nn.BatchNorm(ch, act=act, momentum=momentum, epsilon=epsilon,
                          param_attr=param_attr, bias_attr=bias_attr,
                          data_layout=data_layout)
    if is_test:
        layer.eval()
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = input.shape[begin_norm_axis:]
    layer = _nn.LayerNorm(shape, epsilon=epsilon,
                          weight_attr=param_attr if scale else False,
                          bias_attr=bias_attr if shift else False)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          sparse=is_sparse, weight_attr=param_attr)
    return layer(input)


def dropout(x, dropout_prob=0.5, is_test=False, name=None, **kwargs):
    return F.dropout(x, dropout_prob, training=not is_test)


# ---------------------------------------------------------------------------
# control flow (reference `fluid/layers/control_flow.py` cond/While →
# conditional_block_op / while_op). TPU-native: lax.cond / lax.while_loop —
# the same restriction the reference's AST transformer enforces (both
# branches traced; carried shapes static).
# ---------------------------------------------------------------------------

def _maybe_sub_blocks(branches):
    """In static mode, trace each branch into a child Block of the current
    Program (reference conditional_block/while ops carry a `sub_block`
    BlockDesc index) so the nested structure is inspectable/serializable.
    Execution still lowers the fused lax op recorded in the parent block.

    Returns (attrs, external_vars): the sub_block attr dict plus the
    parent-scope Variables the branches capture — the caller must pass
    those as explicit op inputs (reference conditional_block Input(X)) and
    substitute their values at trace time via `_substituted`, otherwise
    the lowered op would bake in the build-time placeholder values."""
    from ..framework import autograd
    from .program import default_main_program, in_static_mode
    if not in_static_mode() or autograd.in_trace_mode():
        return {}, []
    prog = default_main_program()
    attrs, ext = {}, {}
    for name, fn in branches:
        idx, blk_ext = prog._record_sub_block(fn)
        attrs[name] = idx
        ext.update(blk_ext)
    return attrs, list(ext.values())


@contextlib.contextmanager
def _substituted(ext_vars, values):
    """Temporarily swap the captured Variables' placeholder values for the
    traced/fed values while lax traces the branch closures."""
    saved = [(v, v._value) for v in ext_vars]
    for v, val in zip(ext_vars, values):
        v._value = val
    try:
        yield
    finally:
        for v, old in saved:
            v._value = old


def cond(pred, true_fn=None, false_fn=None, name=None):
    import jax
    from ..framework.autograd import trace_mode
    from ..framework.functional import tree_unwrap
    from ..framework.tensor import apply_op

    attrs, ext = _maybe_sub_blocks([("sub_block", true_fn),
                                    ("sub_block_false", false_fn)])

    def impl(p, *ext_vals, **_attrs):
        def tf(_):
            with trace_mode():
                return tree_unwrap(true_fn())

        def ff(_):
            with trace_mode():
                return tree_unwrap(false_fn())
        with _substituted(ext, ext_vals):
            return jax.lax.cond(p, tf, ff, operand=None)

    return apply_op("cond", impl, (pred, *ext), attrs)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    import jax
    from ..framework.autograd import trace_mode
    from ..framework.functional import tree_unwrap, tree_wrap
    from ..framework.tensor import Tensor, apply_op
    from .program import in_static_mode

    raw = tree_unwrap(loop_vars)

    def c(state):
        with trace_mode():
            out = cond_fn(*tree_wrap(state))
        return out._value if isinstance(out, Tensor) else out

    def b(state):
        with trace_mode():
            out = body_fn(*tree_wrap(state))
        return tree_unwrap(out)

    from ..framework import autograd
    if in_static_mode() and not autograd.in_trace_mode():
        # record ONE `while` op into the Program (plus sub-blocks mirroring
        # body/condition) — replay through Executor.run stays feed-
        # dependent; the old direct-eager path would bake the placeholder
        # result in as a constant
        flat, treedef = jax.tree_util.tree_flatten(
            tuple(loop_vars), is_leaf=lambda x: isinstance(x, Tensor))
        attrs, ext = _maybe_sub_blocks([
            ("sub_block", lambda: body_fn(*loop_vars)),
            ("cond_block", lambda: cond_fn(*loop_vars))])
        loop_slots = {getattr(t, "slot", None) for t in flat}
        ext = [v for v in ext if v.slot not in loop_slots]
        n = len(flat)

        def impl(*vals, **_attrs):
            state = jax.tree_util.tree_unflatten(treedef, vals[:n])
            ext_vals = vals[n:]

            # fresh closures per trace: lax caches the cond/body jaxpr by
            # function identity, so reusing `c`/`b` across impl calls
            # would bake the first trace's captured values in as consts
            def c2(st):
                with _substituted(ext, ext_vals):
                    return c(st)

            def b2(st):
                with _substituted(ext, ext_vals):
                    return b(st)
            out = jax.lax.while_loop(c2, b2, state)
            return tuple(jax.tree_util.tree_leaves(out))
        outs = apply_op("while", impl, (*flat, *ext), attrs)
        outs = outs if isinstance(outs, tuple) else (outs,)
        return jax.tree_util.tree_unflatten(treedef, list(outs))

    out = jax.lax.while_loop(c, b, tuple(raw))
    return tree_wrap(out)


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        if bool(pred):
            return fn()
    return default() if default else None


def switch_case(branch_index, branch_fns, default=None, name=None):
    import jax
    from ..framework.functional import tree_unwrap
    from ..framework.tensor import apply_op
    fns = branch_fns
    if isinstance(branch_fns, dict):
        fns = [branch_fns[k] for k in sorted(branch_fns)]
    elif fns and isinstance(fns[0], tuple):
        fns = [f for _, f in sorted(fns)]

    attrs, ext = _maybe_sub_blocks([(f"sub_block_{i}", f)
                                    for i, f in enumerate(fns)])

    from ..framework.autograd import trace_mode

    def _branch(f):
        def run(_):
            with trace_mode():
                return tree_unwrap(f())
        return run

    def impl(idx, *ext_vals, **_attrs):
        with _substituted(ext, ext_vals):
            return jax.lax.switch(idx, [_branch(f) for f in fns], None)

    return apply_op("switch_case", impl, (branch_index, *ext), attrs)


# ---------------------------------------------------------------------------
# fluid.layers-style wrappers (reference `fluid/layers/nn.py` — the static
# builder API; each delegates to the shared functional/op surface, which
# records into the current Program in static mode)
# ---------------------------------------------------------------------------

# name -> parameter caches, scoped PER default program (WeakKeyDictionary:
# dropping a Program drops its share cache) so unrelated models/tests
# never silently share weights through a colliding param_attr name.
_shared_params = weakref.WeakKeyDictionary()


def _validate_shared(p, shape, dtype, name):
    p_shape = tuple(int(s) for s in p.shape)
    if p_shape != tuple(int(s) for s in shape):
        raise ValueError(
            f"shared_parameter {name!r}: existing parameter has shape "
            f"{list(p_shape)}, requested {list(shape)} — a param_attr name "
            f"shares storage, so call sites must agree on the geometry")
    want = to_jax_dtype(dtype)
    have = getattr(getattr(p, "_value", None), "dtype", None)
    if have is not None and jnp.dtype(have) != jnp.dtype(want):
        raise ValueError(
            f"shared_parameter {name!r}: existing parameter has dtype "
            f"{have}, requested {want}")
    return p


def shared_parameter(shape, dtype, attr=None, is_bias=False,
                     default_name=None):
    """fluid LayerHelper contract: a param_attr WITH a name shares the
    parameter across call sites (reference `fluid/layer_helper_base.py`
    create_parameter); unnamed attrs create fresh parameters per call.
    Sharing is scoped to the current default program and a shape/dtype
    mismatch under the same name raises instead of silently aliasing."""
    from ..ops.legacy import create_parameter
    name = getattr(attr, "name", attr if isinstance(attr, str) else None)
    if name is None:
        return create_parameter(list(shape), dtype, attr=attr,
                                is_bias=is_bias, name=default_name)
    from .program import default_main_program, in_static_mode
    prog = default_main_program()
    if in_static_mode():
        reg = prog.param_vars
        if name in reg:
            return _validate_shared(reg[name], shape, dtype, name)
    cache = _shared_params.setdefault(prog, {})
    if name in cache:
        return _validate_shared(cache[name], shape, dtype, name)
    p = create_parameter(list(shape), dtype, attr=attr, is_bias=is_bias,
                         name=name)
    cache[name] = p
    return p

def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           data_format="NCHW", name=None):
    if global_pooling:
        axis = (2, 3) if data_format == "NCHW" else (1, 2)
        from ..ops import reduction
        red = reduction.max if pool_type == "max" else reduction.mean
        return red(input, axis=axis, keepdim=True)
    fn = F.max_pool2d if pool_type == "max" else F.avg_pool2d
    return fn(input, pool_size, pool_stride, pool_padding,
              ceil_mode=ceil_mode, data_format=data_format)


def relu(x, name=None):
    return F.relu(x)


def softmax(input, axis=-1, name=None):
    return F.softmax(input, axis=axis)


def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  name=None):
    """fluid.layers.cross_entropy contract: `input` is POST-softmax
    probabilities (-log p[label]); use_softmax=False avoids the silent
    double-softmax a ported fluid model would otherwise get."""
    return F.cross_entropy(input, label, soft_label=soft_label,
                           ignore_index=ignore_index, reduction="none",
                           use_softmax=False)


def mean(x, name=None):
    from ..ops import reduction
    return reduction.mean(x)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    from ..ops.linalg import matmul
    from ..ops.manipulation import reshape
    xs, ys = list(x.shape), list(y.shape)
    if len(xs) > 2:
        import numpy as _np
        x = reshape(x, [int(_np.prod(xs[:x_num_col_dims])), -1])
    if len(ys) > 2:
        import numpy as _np
        y = reshape(y, [int(_np.prod(ys[:y_num_col_dims])), -1])
    return matmul(x, y)


def concat(input, axis=0, name=None):
    from ..ops.manipulation import concat as _concat
    return _concat(input, axis)


def accuracy(input, label, k=1, name=None):
    import jax.numpy as jnp

    from ..framework.tensor import apply_op

    def impl(pred, lab):
        idx = jnp.argsort(-pred, axis=-1)[:, :k]
        hit = (idx == lab.reshape(-1, 1)).any(axis=1)
        return hit.astype(jnp.float32).mean()
    return apply_op("accuracy", impl, (input, label), {})


def topk(input, k, name=None):
    from ..ops.search import topk as _topk
    return _topk(input, k)


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    return F.normalize(x, p=2, axis=axis, epsilon=epsilon)


def one_hot(input, depth, name=None):
    return F.one_hot(input, depth)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    from ..ops import reduction
    return reduction.sum(input, axis=dim, keepdim=keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    from ..ops import reduction
    return reduction.mean(input, axis=dim, keepdim=keep_dim)


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    """reference `sigmoid_cross_entropy_with_logits_op.cc`: elementwise
    BCE-with-logits where label==ignore_index contributes 0; normalize
    divides by the non-ignored count."""
    import jax
    import jax.numpy as jnp

    from ..framework.tensor import apply_op

    def impl(lv, yv):
        loss = jnp.maximum(lv, 0.0) - lv * yv + jnp.log1p(
            jnp.exp(-jnp.abs(lv)))
        keep = yv != ignore_index
        loss = jnp.where(keep, loss, 0.0)
        if normalize:
            loss = loss / jnp.maximum(keep.sum().astype(loss.dtype), 1.0)
        return loss
    return apply_op("sigmoid_cross_entropy_with_logits", impl,
                    (x, label), {})


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference `fluid/layers/nn.py` lstm_unit / `lstm_unit_op.cc`:
    FC(concat(x, h)) -> i,f,c̃,o with forget_bias added to the forget
    gate pre-activation; returns (hidden, cell)."""
    import jax.numpy as jnp

    from ..framework.tensor import apply_op

    D = hidden_t_prev.shape[-1]
    w = shared_parameter([x_t.shape[-1] + D, 4 * D], "float32",
                         attr=param_attr)
    b = shared_parameter([4 * D], "float32", attr=bias_attr,
                         is_bias=True)

    def impl(x, h, c, wv, bv):
        z = jnp.concatenate([x, h], axis=-1) @ wv + bv
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f + forget_bias)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        return o * jnp.tanh(c_new), c_new
    import jax
    h, c = apply_op("lstm_unit", impl,
                    (x_t, hidden_t_prev, cell_t_prev, w, b), {})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    """reference `gru_unit_op.cc`: input is the PRE-PROJECTED [B, 3*D]
    tensor (an fc output, D = size//3); hidden weights [D, 3*D] live in
    this op. Returns (hidden, reset_hidden_prev, gate) like the
    reference's 3-output contract."""
    import jax
    import jax.numpy as jnp

    from ..framework.tensor import apply_op

    D = size // 3
    w = shared_parameter([D, 3 * D], "float32", attr=param_attr)
    b = shared_parameter([3 * D], "float32", attr=bias_attr, is_bias=True)

    act = jnp.tanh if activation == "tanh" else jax.nn.relu

    def impl(x, h, wv, bv):
        x = x + bv
        xu, xr, xc = jnp.split(x, 3, axis=-1)
        wu, wr, wc = jnp.split(wv, 3, axis=-1)
        u = jax.nn.sigmoid(xu + h @ wu)
        r = jax.nn.sigmoid(xr + h @ wr)
        rh = r * h
        c = act(xc + rh @ wc)
        h_new = (1.0 - u) * h + u * c
        gate = jnp.concatenate([u, r, c], axis=-1)
        return h_new, rh, gate
    return apply_op("gru_unit", impl, (input, hidden, w, b), {})


__all__ += ["pool2d", "relu", "softmax", "cross_entropy", "mean", "mul",
            "concat", "accuracy", "topk", "l2_normalize", "one_hot",
            "reduce_sum", "reduce_mean",
            "sigmoid_cross_entropy_with_logits", "lstm_unit", "gru_unit"]
