"""paddle.static.nn — static-graph layer API (reference
`python/paddle/static/nn/` re-exporting `fluid/layers/nn.py` fc/conv2d/…).
Each builds the same Layers the dygraph API uses; in static mode their ops
record into the current Program."""
from __future__ import annotations

import contextlib

from .. import nn as _nn
from ..nn import functional as F

__all__ = ["fc", "conv2d", "conv3d", "batch_norm", "embedding", "dropout",
           "layer_norm", "conv2d_transpose", "cond", "while_loop",
           "switch_case", "case"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from ..ops.manipulation import flatten, reshape
    inp = x
    if num_flatten_dims > 1 or len(x.shape) > 2:
        inp = flatten(x, num_flatten_dims, -1) if num_flatten_dims >= 1 \
            else x
    layer = _nn.Linear(inp.shape[-1], size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    out = layer(inp)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None, use_cudnn=True):
    ch_axis = 1 if data_format == "NCHW" else -1
    layer = _nn.Conv2D(input.shape[ch_axis], num_filters, filter_size,
                       stride, padding, dilation, groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCDHW", name=None):
    layer = _nn.Conv3D(input.shape[1], num_filters, filter_size, stride,
                       padding, dilation, groups, weight_attr=param_attr,
                       bias_attr=bias_attr, data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None):
    layer = _nn.Conv2DTranspose(input.shape[1], num_filters, filter_size,
                                stride, padding, dilation=dilation,
                                groups=groups, weight_attr=param_attr,
                                bias_attr=bias_attr,
                                data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None, **kwargs):
    ch = input.shape[1 if data_layout == "NCHW" else -1]
    layer = _nn.BatchNorm(ch, act=act, momentum=momentum, epsilon=epsilon,
                          param_attr=param_attr, bias_attr=bias_attr,
                          data_layout=data_layout)
    if is_test:
        layer.eval()
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = input.shape[begin_norm_axis:]
    layer = _nn.LayerNorm(shape, epsilon=epsilon,
                          weight_attr=param_attr if scale else False,
                          bias_attr=bias_attr if shift else False)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          sparse=is_sparse, weight_attr=param_attr)
    return layer(input)


def dropout(x, dropout_prob=0.5, is_test=False, name=None, **kwargs):
    return F.dropout(x, dropout_prob, training=not is_test)


# ---------------------------------------------------------------------------
# control flow (reference `fluid/layers/control_flow.py` cond/While →
# conditional_block_op / while_op). TPU-native: lax.cond / lax.while_loop —
# the same restriction the reference's AST transformer enforces (both
# branches traced; carried shapes static).
# ---------------------------------------------------------------------------

def _maybe_sub_blocks(branches):
    """In static mode, trace each branch into a child Block of the current
    Program (reference conditional_block/while ops carry a `sub_block`
    BlockDesc index) so the nested structure is inspectable/serializable.
    Execution still lowers the fused lax op recorded in the parent block.

    Returns (attrs, external_vars): the sub_block attr dict plus the
    parent-scope Variables the branches capture — the caller must pass
    those as explicit op inputs (reference conditional_block Input(X)) and
    substitute their values at trace time via `_substituted`, otherwise
    the lowered op would bake in the build-time placeholder values."""
    from ..framework import autograd
    from .program import default_main_program, in_static_mode
    if not in_static_mode() or autograd.in_trace_mode():
        return {}, []
    prog = default_main_program()
    attrs, ext = {}, {}
    for name, fn in branches:
        idx, blk_ext = prog._record_sub_block(fn)
        attrs[name] = idx
        ext.update(blk_ext)
    return attrs, list(ext.values())


@contextlib.contextmanager
def _substituted(ext_vars, values):
    """Temporarily swap the captured Variables' placeholder values for the
    traced/fed values while lax traces the branch closures."""
    saved = [(v, v._value) for v in ext_vars]
    for v, val in zip(ext_vars, values):
        v._value = val
    try:
        yield
    finally:
        for v, old in saved:
            v._value = old


def cond(pred, true_fn=None, false_fn=None, name=None):
    import jax
    from ..framework.autograd import trace_mode
    from ..framework.functional import tree_unwrap
    from ..framework.tensor import apply_op

    attrs, ext = _maybe_sub_blocks([("sub_block", true_fn),
                                    ("sub_block_false", false_fn)])

    def impl(p, *ext_vals, **_attrs):
        def tf(_):
            with trace_mode():
                return tree_unwrap(true_fn())

        def ff(_):
            with trace_mode():
                return tree_unwrap(false_fn())
        with _substituted(ext, ext_vals):
            return jax.lax.cond(p, tf, ff, operand=None)

    return apply_op("cond", impl, (pred, *ext), attrs)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    import jax
    from ..framework.autograd import trace_mode
    from ..framework.functional import tree_unwrap, tree_wrap
    from ..framework.tensor import Tensor, apply_op
    from .program import in_static_mode

    raw = tree_unwrap(loop_vars)

    def c(state):
        with trace_mode():
            out = cond_fn(*tree_wrap(state))
        return out._value if isinstance(out, Tensor) else out

    def b(state):
        with trace_mode():
            out = body_fn(*tree_wrap(state))
        return tree_unwrap(out)

    from ..framework import autograd
    if in_static_mode() and not autograd.in_trace_mode():
        # record ONE `while` op into the Program (plus sub-blocks mirroring
        # body/condition) — replay through Executor.run stays feed-
        # dependent; the old direct-eager path would bake the placeholder
        # result in as a constant
        flat, treedef = jax.tree_util.tree_flatten(
            tuple(loop_vars), is_leaf=lambda x: isinstance(x, Tensor))
        attrs, ext = _maybe_sub_blocks([
            ("sub_block", lambda: body_fn(*loop_vars)),
            ("cond_block", lambda: cond_fn(*loop_vars))])
        loop_slots = {getattr(t, "slot", None) for t in flat}
        ext = [v for v in ext if v.slot not in loop_slots]
        n = len(flat)

        def impl(*vals, **_attrs):
            state = jax.tree_util.tree_unflatten(treedef, vals[:n])
            ext_vals = vals[n:]

            # fresh closures per trace: lax caches the cond/body jaxpr by
            # function identity, so reusing `c`/`b` across impl calls
            # would bake the first trace's captured values in as consts
            def c2(st):
                with _substituted(ext, ext_vals):
                    return c(st)

            def b2(st):
                with _substituted(ext, ext_vals):
                    return b(st)
            out = jax.lax.while_loop(c2, b2, state)
            return tuple(jax.tree_util.tree_leaves(out))
        outs = apply_op("while", impl, (*flat, *ext), attrs)
        outs = outs if isinstance(outs, tuple) else (outs,)
        return jax.tree_util.tree_unflatten(treedef, list(outs))

    out = jax.lax.while_loop(c, b, tuple(raw))
    return tree_wrap(out)


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        if bool(pred):
            return fn()
    return default() if default else None


def switch_case(branch_index, branch_fns, default=None, name=None):
    import jax
    from ..framework.functional import tree_unwrap
    from ..framework.tensor import apply_op
    fns = branch_fns
    if isinstance(branch_fns, dict):
        fns = [branch_fns[k] for k in sorted(branch_fns)]
    elif fns and isinstance(fns[0], tuple):
        fns = [f for _, f in sorted(fns)]

    attrs, ext = _maybe_sub_blocks([(f"sub_block_{i}", f)
                                    for i, f in enumerate(fns)])

    from ..framework.autograd import trace_mode

    def _branch(f):
        def run(_):
            with trace_mode():
                return tree_unwrap(f())
        return run

    def impl(idx, *ext_vals, **_attrs):
        with _substituted(ext, ext_vals):
            return jax.lax.switch(idx, [_branch(f) for f in fns], None)

    return apply_op("switch_case", impl, (branch_index, *ext), attrs)
