"""paddle.static.amp — mixed precision for static Programs.

Reference: `fluid/contrib/mixed_precision/` (`decorate` wraps the
optimizer in OptimizerWithMixedPrecision; `fp16_utils.rewrite_program`
walks the ops inserting casts per the black/white lists;
`fp16_lists.AutoMixedPrecisionLists`).

TPU redesign: the rewrite wraps each recorded op's fn with dtype casts —
white-listed ops (matmul/conv) compute in bfloat16 on the MXU,
black-listed ops (softmax/norms/reductions) are pinned to float32 —
mirroring what dygraph auto_cast does at dispatch time. bf16 needs no
loss scaling (f32 exponent range), so decorate() accepts and ignores the
reference's loss-scaling knobs when dest dtype is bfloat16.
"""
from __future__ import annotations

import warnings
from typing import Optional, Sequence

__all__ = ["decorate", "rewrite_program", "AutoMixedPrecisionLists",
           "CustomOpLists", "OptimizerWithMixedPrecision"]


class AutoMixedPrecisionLists:
    """reference `fp16_lists.py:20`."""

    def __init__(self, custom_white_list: Optional[Sequence[str]] = None,
                 custom_black_list: Optional[Sequence[str]] = None):
        from ..amp import BLACK_LIST, WHITE_LIST
        cw = set(custom_white_list or ())
        cb = set(custom_black_list or ())
        if cw & cb:
            raise ValueError(f"ops in both custom lists: {cw & cb}")
        # custom entries override the defaults (reference fp16_lists)
        self.white_list = (set(WHITE_LIST) | cw) - cb
        self.black_list = (set(BLACK_LIST) | cb) - self.white_list


CustomOpLists = AutoMixedPrecisionLists


def rewrite_program(program, amp_lists: Optional[
        AutoMixedPrecisionLists] = None, dest_dtype: str = "bfloat16"):
    """reference `fp16_utils.py:468` rewrite_program: wrap each op so
    white-listed ones compute in `dest_dtype` and black-listed ones in
    float32. In-place; bumps the program version so Executor jit caches
    refresh."""
    import jax.numpy as jnp

    lists = amp_lists or AutoMixedPrecisionLists()
    dt = jnp.bfloat16 if dest_dtype in ("bfloat16", "bf16") \
        else jnp.float16

    def cast_wrap(fn, to):
        def wrapped(*args, _fn=fn, _to=to):
            cargs = [a.astype(_to)
                     if hasattr(a, "dtype")
                     and jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                     else a for a in args]
            return _fn(*cargs)
        return wrapped

    for op in program.ops:
        if op.attrs.get("amp_dtype"):
            continue
        if op.name in lists.white_list:
            op.fn = cast_wrap(op.fn, dt)
            op.attrs["amp_dtype"] = str(dest_dtype)
        elif op.name in lists.black_list:
            op.fn = cast_wrap(op.fn, jnp.float32)
            op.attrs["amp_dtype"] = "float32"
    program._version = getattr(program, "_version", 0) + 1
    return program


class OptimizerWithMixedPrecision:
    """reference `decorator.py:36`: delegates to the inner optimizer and
    rewrites the main program after backward is appended."""

    def __init__(self, optimizer, amp_lists, dest_dtype):
        self._opt = optimizer
        self._lists = amp_lists
        self._dest = dest_dtype

    def __getattr__(self, name):
        return getattr(self._opt, name)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        ret = self._opt.minimize(loss, startup_program, parameters,
                                 no_grad_set)
        from .program import default_main_program
        rewrite_program(default_main_program(), self._lists, self._dest)
        return ret

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        """reference decorator.amp_init — master-weight setup; bf16
        keeps f32 master weights in the optimizer state already."""


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
             use_dynamic_loss_scaling=True, dest_dtype="bfloat16",
             **kwargs):
    """reference `decorator.py` decorate()."""
    # bf16 has float32's exponent range, so the loss-scaling knobs are
    # intentionally unused for the default dest dtype
    if dest_dtype == "float16":
        warnings.warn("float16 static AMP uses the bf16 path's cast "
                      "rewrite; GradScaler-based loss scaling is the "
                      "dygraph API (paddle.amp.GradScaler)")
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists or AutoMixedPrecisionLists(), dest_dtype)
