"""Static graph: Program / Executor / program_guard.

Reference: `python/paddle/fluid/framework.py` (Program/Block/Operator),
`fluid/executor.py:916` Executor.run, `framework/executor.cc:460` op loop.

TPU-native redesign: a Program is a recorded op list (each entry: the raw
XLA-lowerable fn + SSA slot ids). `Executor.run` lowers the whole program
(feed slots + parameter slots → fetch slots) into ONE jax.jit computation —
the reference's per-op interpreter loop is replaced by whole-program XLA
compilation, which is the only sane execution model on TPU. append_backward
differentiates that same lowered function with jax.grad, so static autodiff
needs no per-op grad makers.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.monitor import STAT_ADD
from ..framework.tensor import Tensor

__all__ = ["Program", "Executor", "program_guard", "default_main_program",
           "default_startup_program", "enable_static", "disable_static",
           "in_static_mode", "data", "scope_guard", "global_scope",
           "Variable", "append_backward"]

class _SlotCounter:
    """SSA slot allocator; advance_past() keeps fresh slots clear of ids
    preserved by a loaded Program (serde.program_from_doc)."""

    def __init__(self):
        self._n = 0

    def __next__(self):
        n = self._n
        self._n += 1
        return n

    def advance_past(self, n):
        self._n = max(self._n, n + 1)


_slot_counter = _SlotCounter()


def _flatten_tensors(obj):
    """Tensor leaves of a branch-fn return, via the canonical pytree
    traversal."""
    from ..framework.tensor import Tensor
    leaves = jax.tree_util.tree_leaves(
        obj, is_leaf=lambda x: isinstance(x, Tensor))
    return [x for x in leaves if isinstance(x, Tensor)]


class Variable(Tensor):
    """A static-graph variable: a Tensor whose value is a placeholder zeros
    array (for shape/dtype propagation during graph building) plus an SSA
    slot id used at execution time."""

    def __init__(self, value, name=None, is_param=False, is_feed=False):
        super().__init__(value, stop_gradient=not is_param, name=name)
        self.slot = next(_slot_counter)
        self.is_param = is_param
        self.is_feed = is_feed


class _Op:
    """One recorded op (the OpDesc analogue: reference
    `framework/op_desc.h:32` — type + attrs + input/output wiring)."""

    __slots__ = ("name", "fn", "in_refs", "out_slots", "attrs")

    def __init__(self, name, fn, in_refs, out_slots, attrs=None):
        self.name = name
        self.fn = fn
        self.in_refs = in_refs  # list of ("s", slot) | ("c", const_array)
        self.out_slots = out_slots
        self.attrs = attrs or {}  # inspectable op attributes (OpDesc parity)

    # OpDesc-parity introspection surface
    @property
    def type(self):
        return self.name

    def has_attr(self, name):
        return name in self.attrs

    def attr(self, name):
        return self.attrs[name]

    def all_attrs(self):
        return dict(self.attrs)

    @property
    def input_slots(self):
        return [ref for tag, ref in self.in_refs if tag == "s"]

    def __repr__(self):
        return f"_Op({self.name}: {self.input_slots} -> {self.out_slots})"


class Block:
    """reference `framework/block_desc.h:40` / Python `fluid/framework.py`
    Block: an op list + a variable table, with parent nesting. Block 0 is
    the executed program; sub-blocks mirror control-flow branches
    (conditional_block/while sub_block attrs in the reference) for
    introspection and serialization — execution stays whole-program XLA."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.ops: List[_Op] = []
        self.vars: Dict[int, Variable] = {}

    def var(self, name):
        for v in self.vars.values():
            if getattr(v, "name", None) == name:
                return v
        raise ValueError(f"no variable named {name!r} in block {self.idx}")

    def all_parameters(self):
        return self.program.all_parameters()

    @property
    def parent_block(self):
        return (self.program.blocks[self.parent_idx]
                if self.parent_idx >= 0 else None)

    def __repr__(self):
        return (f"Block(idx={self.idx}, parent={self.parent_idx}, "
                f"{len(self.ops)} ops)")


class Program:
    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._cur_block_idx = 0
        self.feed_vars: Dict[str, Variable] = {}
        self.param_vars: Dict[str, Variable] = {}
        self.random_ops = False
        self._opt_hooks: List[Callable] = []
        # bumped by program-rewriting passes so Executor jit caches
        # keyed on this program invalidate (quant_pass, etc.)
        self._version = 0

    # ops/vars live on block 0 (the executed block); properties keep the
    # flat-program view every consumer (lowering, passes, serde) uses
    @property
    def ops(self) -> List[_Op]:
        return self.blocks[0].ops

    @ops.setter
    def ops(self, value):
        self.blocks[0].ops = value

    @property
    def vars(self) -> Dict[int, Variable]:
        return self.blocks[0].vars

    @vars.setter
    def vars(self, value):
        self.blocks[0].vars = value

    @property
    def num_blocks(self):
        return len(self.blocks)

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self._cur_block_idx]

    def _record_sub_block(self, fn, args=()):
        """Trace `fn` with recording redirected into a fresh child Block.

        Returns (block_idx, external_vars): the block index (the
        reference's sub_block attr value) and the parent-block Variables
        the branch consumes or returns — the control-flow op must take
        those as explicit inputs (reference conditional_block's Input(X))
        so lowering substitutes fed/updated values for the placeholders
        the branch closures captured."""
        blk = Block(self, len(self.blocks), parent_idx=self._cur_block_idx)
        self.blocks.append(blk)
        prev = self._cur_block_idx
        self._cur_block_idx = blk.idx
        try:
            ret = fn(*args)
        finally:
            self._cur_block_idx = prev
        produced = {s for op in blk.ops for s in op.out_slots}
        ext: Dict[int, Variable] = {}
        for op in blk.ops:
            for tag, ref in op.in_refs:
                if tag == "s" and ref not in produced:
                    # captured Parameters promote into block 0, not the
                    # sub-block — search both so branch weights become
                    # explicit inputs (else optimizer updates would never
                    # reach the lowered branch)
                    v = blk.vars.get(ref)
                    if v is None:
                        v = self._find_var(ref)
                    if v is not None:
                        ext[ref] = v
        for leaf in _flatten_tensors(ret):
            if hasattr(leaf, "slot") and leaf.slot not in produced:
                ext[leaf.slot] = leaf
        return blk.idx, ext

    def _find_var(self, slot):
        for b in self.blocks:
            if slot in b.vars:
                return b.vars[slot]
        return None

    def record(self, name, fn, inputs, output_tensors, attrs=None):
        from ..framework.tensor import Parameter
        blk = self.current_block()
        in_refs = []
        for t in inputs:
            if isinstance(t, Parameter):
                # lazily promote eager Parameters used in static graphs
                if not hasattr(t, "slot"):
                    t.slot = next(_slot_counter)
                    self.param_vars[t.name] = t
                    self.blocks[0].vars[t.slot] = t
                    _state.scope[t.name] = np.asarray(t._value)
                in_refs.append(("s", t.slot))
            elif isinstance(t, Variable):
                in_refs.append(("s", t.slot))
                blk.vars[t.slot] = t
            else:
                in_refs.append(("c", t._value))
        out_slots = [t.slot for t in output_tensors]
        for t in output_tensors:
            blk.vars[t.slot] = t
        blk.ops.append(_Op(name, fn, in_refs, out_slots, attrs))

    def clone(self, for_test=False):
        return self

    def global_block(self):
        return self.blocks[0]

    def all_parameters(self):
        return list(self.param_vars.values())

    def prune(self, targets):
        """Backward-slice the op list to what the target Variables need
        (reference `framework/prune.cc` Prune + `Program._prune_with_input`
        used by save_inference_model). Returns a NEW Program sharing
        Variables but holding only the live ops."""
        targets = targets if isinstance(targets, (list, tuple)) else \
            [targets]
        live = {t.slot for t in targets}
        keep = []
        for op in reversed(self.ops):
            if any(s in live for s in op.out_slots):
                keep.append(op)
                for tag, ref in op.in_refs:
                    if tag == "s":
                        live.add(ref)
        keep.reverse()
        out = Program()
        out.ops = keep
        out.vars = dict(self.vars)
        out.feed_vars = {n: v for n, v in self.feed_vars.items()
                         if v.slot in live}
        out.param_vars = {n: v for n, v in self.param_vars.items()
                          if v.slot in live}
        out._opt_hooks = list(self._opt_hooks)
        # kept control-flow ops hold sub_block indices — carry all
        # sub-blocks so those attrs stay resolvable (indices must not
        # shift, so none are dropped even if their op was pruned)
        for b in self.blocks[1:]:
            nb = Block(out, b.idx, b.parent_idx)
            nb.ops = list(b.ops)
            nb.vars = dict(b.vars)
            out.blocks.append(nb)
        return out

    # -- serialization (reference ProgramDesc.SerializeToString) ----------
    def to_doc(self, scope=None, include_params=True):
        from .serde import program_to_doc
        return program_to_doc(self, scope if scope is not None
                              else _state.scope, include_params)

    @classmethod
    def from_doc(cls, doc):
        from .serde import program_from_doc
        return program_from_doc(doc)

    def save(self, path, scope=None, include_params=True):
        from .serde import save_program
        save_program(self, path, scope, include_params)

    @classmethod
    def load(cls, path):
        from .serde import load_program
        return load_program(path)

    def __repr__(self):
        lines = [f"Program({len(self.ops)} ops)"]
        for op in self.ops[:50]:
            ins = [r if t == "s" else "const" for t, r in op.in_refs]
            lines.append(f"  {op.name}: {ins} -> {op.out_slots}")
        return "\n".join(lines)


class _StaticState(threading.local):
    def __init__(self):
        self.enabled = False
        self.main: Program = Program()
        self.startup: Program = Program()
        self.scope: Dict[str, np.ndarray] = {}


_state = _StaticState()


def in_static_mode() -> bool:
    return _state.enabled


def enable_static():
    _state.enabled = True


def disable_static(place=None):
    _state.enabled = False


def default_main_program() -> Program:
    return _state.main


def default_startup_program() -> Program:
    return _state.startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_m, prev_s = _state.main, _state.startup
    _state.main = main_program
    if startup_program is not None:
        _state.startup = startup_program
    try:
        yield
    finally:
        _state.main, _state.startup = prev_m, prev_s


def global_scope():
    return _state.scope


@contextlib.contextmanager
def scope_guard(scope):
    prev = _state.scope
    _state.scope = scope
    try:
        yield
    finally:
        _state.scope = prev


def data(name, shape, dtype="float32", lod_level=0):
    from ..framework.dtype import to_jax_dtype
    shape = [1 if (s is None or s == -1) else int(s) for s in shape]
    v = Variable(jnp.zeros(shape, to_jax_dtype(dtype)), name=name,
                 is_feed=True)
    _state.main.feed_vars[name] = v
    _state.main.vars[v.slot] = v
    return v


def make_parameter(name, value):
    """Called by static-mode create_parameter: registers the param in the
    scope and returns its Variable."""
    v = Variable(value, name=name, is_param=True)
    _state.main.param_vars[name] = v
    _state.main.vars[v.slot] = v
    _state.scope[name] = np.asarray(value)
    return v


def record_op(name, fn, inputs, outputs, attrs=None):
    hint = getattr(_state, "device_hint", None)
    if hint is not None:
        attrs = dict(attrs or {})
        attrs["op_device"] = hint   # reference device_guard attr name
    _state.main.record(name, fn, inputs, outputs, attrs)


class _Lowered:
    """program → one jittable function (feeds, params) -> fetches."""

    def __init__(self, program: Program, fetch_slots: Sequence[int]):
        self.program = program
        self.fetch_slots = list(fetch_slots)
        feed_items = sorted(program.feed_vars.items())
        self.feed_names = [n for n, _ in feed_items]
        self.feed_slots = [v.slot for _, v in feed_items]
        param_items = sorted(program.param_vars.items())
        self.param_names = [n for n, _ in param_items]
        self.param_slots = [v.slot for _, v in param_items]

    def __call__(self, feed_list, param_list):
        env: Dict[int, Any] = {}
        for s, v in zip(self.feed_slots, feed_list):
            env[s] = v
        for s, v in zip(self.param_slots, param_list):
            env[s] = v
        for op in self.program.ops:
            args = []
            for tag, ref in op.in_refs:
                if tag == "c":
                    args.append(ref)
                elif ref in env:
                    args.append(env[ref])
                else:
                    args.append(self.program.vars[ref]._value)
            outs = op.fn(*args)
            if not isinstance(outs, (tuple, list)):
                outs = (outs,)
            for s, o in zip(op.out_slots, outs):
                env[s] = o
        return [env[s] if s in env else self.program.vars[s]._value
                for s in self.fetch_slots]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Marks the program for gradient computation (reference
    `fluid/backward.py:1337`). Actual differentiation happens at lowering
    time via jax.grad over the lowered function."""
    prog = _state.main
    prog._loss_slot = loss.slot
    params = parameter_list or list(prog.param_vars.values())
    return [(p, None) for p in params]


class Executor:
    """reference `fluid/executor.py:916`. One jit per (program, fetch) key."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Callable] = {}

    def run(self, program=None, feed=None, fetch_list=None,
            scope=None, return_numpy=True, use_program_cache=True):
        STAT_ADD("STAT_executor_runs")
        program = program or _state.main
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope if scope is not None else _state.scope

        if program is _state.startup or not fetch_list and not feed:
            # startup program: parameters were already initialized eagerly at
            # build time (make_parameter); nothing to execute.
            if program.ops:
                self._run_plain(program, scope)
            return []

        fetch_vars = [f for f in fetch_list]
        fetch_slots = [f.slot for f in fetch_vars]
        lowered = _Lowered(program, fetch_slots)

        feed_arrays = []
        for n in lowered.feed_names:
            if n in feed:
                arr = feed[n]
                arr = arr.numpy() if isinstance(arr, Tensor) else np.asarray(arr)
                feed_arrays.append(jnp.asarray(arr))
            else:
                feed_arrays.append(program.feed_vars[n]._value)
        param_arrays = [jnp.asarray(scope[n]) for n in lowered.param_names]

        train = hasattr(program, "_loss_slot") and program._opt_hooks
        key = (id(program), getattr(program, "_version", 0),
               tuple(fetch_slots),
               tuple((tuple(a.shape), str(a.dtype)) for a in feed_arrays),
               bool(train), len(program.ops))
        fn = self._cache.get(key)
        if fn is None:
            if train:
                opt = program._opt_hooks[-1]

                def step(feeds, params_vals, opt_state, step_no, lr):
                    def loss_fn(pvals):
                        loss_lowered = _Lowered(program,
                                                [program._loss_slot])
                        return loss_lowered(feeds, pvals)[0]
                    grads = jax.grad(loss_fn)(params_vals)
                    new_params, new_state = opt.apply_gradients_pytree(
                        grads, params_vals, opt_state, lr, step_no)
                    outs = _Lowered(program, fetch_slots)(feeds, params_vals)
                    return outs, new_params, new_state
                fn = jax.jit(step)
            else:
                fn = jax.jit(lambda feeds, params_vals: lowered(
                    feeds, params_vals))
            self._cache[key] = fn

        if train:
            opt = program._opt_hooks[-1]
            if not hasattr(program, "_opt_state"):
                program._opt_state = [opt._init_state(p)
                                      for p in param_arrays]
                program._step_no = 0
            outs, new_params, new_state = fn(
                feed_arrays, param_arrays, program._opt_state,
                jnp.asarray(program._step_no + 1, "int32"),
                jnp.asarray(opt.get_lr(), "float32"))
            program._opt_state = new_state
            program._step_no += 1
            for n, v in zip(lowered.param_names, new_params):
                scope[n] = v
        else:
            outs = fn(feed_arrays, param_arrays)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def train_from_dataset(self, program, dataset, fetch_list=None,
                           fetch_info=None, print_period=100, debug=False):
        """reference `framework/trainer.h` MultiTrainer /
        `executor.cc:152` RunFromDataset: drive the program from an
        InMemoryDataset/QueueDataset batch stream."""
        feed_names = sorted(program.feed_vars.keys())
        results = []
        for step, batch in enumerate(dataset):
            feed = {n: b for n, b in zip(feed_names, batch)}
            out = self.run(program, feed=feed, fetch_list=fetch_list or [])
            if fetch_list:
                results.append(out)
            if debug and step % print_period == 0:
                print(f"[train_from_dataset] step {step}: {out}")
        return results

    def infer_from_dataset(self, program, dataset, fetch_list=None, **kw):
        return self.train_from_dataset(program, dataset, fetch_list, **kw)

    def _run_plain(self, program, scope):
        lowered = _Lowered(program, [])
        feed_arrays = [program.feed_vars[n]._value
                       for n in lowered.feed_names]
        param_arrays = [jnp.asarray(scope.get(n, program.param_vars[n]._value))
                        for n in lowered.param_names]
        lowered(feed_arrays, param_arrays)

    def close(self):
        pass
