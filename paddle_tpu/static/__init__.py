"""paddle.static namespace (reference `python/paddle/static/`)."""
from ..nn import functional as _F  # noqa: F401
from .input_spec import InputSpec
from .program import (Executor, Program, Variable, append_backward, data,
                      default_main_program, default_startup_program,
                      disable_static, enable_static, global_scope,
                      in_static_mode, program_guard, scope_guard)
from .passes import PassManager, get_pass, register_pass
from .serde import load_program, save_program


import contextlib as _contextlib


@_contextlib.contextmanager
def device_guard(device=None):
    """reference `fluid/framework.py device_guard`: pins ops to a device
    in the reference's per-op executor. Under whole-program XLA the
    compiler owns placement, so this records the hint as an op attr for
    inspection and otherwise lets GSPMD decide."""
    from .program import _state
    prev = getattr(_state, "device_hint", None)
    _state.device_hint = device
    try:
        yield
    finally:
        _state.device_hint = prev

# static layer API (paddle.static.nn)
from . import nn  # noqa: F401
from .nn import cond, while_loop  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """reference `fluid/io.py:1199` save_inference_model — exports the
    pruned feed→fetch computation as the StableHLO serving artifact
    (.pdmodel) + weights (.pdiparams), loadable by inference.Predictor."""
    import pickle

    import jax
    import numpy as np

    from .program import _Lowered, default_main_program, global_scope
    program = program or default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else \
        [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else \
        [fetch_vars]
    # backward-slice to the serving subgraph (reference framework/prune.cc)
    program = program.prune(fetch_vars)
    lowered = _Lowered(program, [v.slot for v in fetch_vars])
    scope = global_scope()
    params = [np.asarray(scope[n]) for n in lowered.param_names]

    def infer(*feeds):
        outs = lowered(list(feeds), [jax.numpy.asarray(p) for p in params])
        return tuple(outs) if len(outs) > 1 else outs[0]

    sds = [jax.ShapeDtypeStruct(tuple(program.feed_vars[n]._value.shape),
                                program.feed_vars[n]._value.dtype)
           for n in lowered.feed_names]
    exported = jax.export.export(jax.jit(infer))(*sds)
    import os
    os.makedirs(os.path.dirname(os.path.abspath(path_prefix)) or ".",
                exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({n: p for n, p in zip(lowered.param_names, params)}, f,
                    protocol=4)
    # also persist the op-level Program IR so the graph itself (not just
    # the fused serving artifact) round-trips — reference ProgramDesc
    try:
        save_program(program, path_prefix + ".ptprog",
                     scope=scope, include_params=True,
                     extra={"fetch_slots": [v.slot for v in fetch_vars],
                            "fetch_names": [getattr(v, "name", None)
                                            for v in fetch_vars]})
    except Exception as e:  # programs with non-exportable ops (e.g. host
        import warnings      # callbacks) still get the fused .pdmodel
        warnings.warn(f"op-level .ptprog export failed ({e!r}); "
                      f"load_inference_model will fall back to the fused "
                      f"StableHLO predictor")
    return [v.name for v in fetch_vars]


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (program, feed_names, fetch_names).

    When the op-level `.ptprog` IR is present (written by
    save_inference_model), a real Program is rebuilt — inspectable,
    re-executable through Executor, and differentiable. Otherwise falls
    back to the fused StableHLO predictor."""
    import os

    from .program import global_scope
    if os.path.exists(path_prefix + ".ptprog"):
        program, params = load_program(path_prefix + ".ptprog")
        global_scope().update(params)
        feed_names = sorted(program.feed_vars.keys())
        extra = getattr(program, "_doc_extra", {})
        program._fetch_slots = extra.get("fetch_slots", [])
        fetch_names = extra.get("fetch_names", [])
        return program, feed_names, fetch_names
    from ..inference import Config, create_predictor
    pred = create_predictor(Config(path_prefix))
    return pred, pred.get_input_names(), ["output_0"]


def save(program, model_path, **kwargs):
    import pickle
    import numpy as np
    from .program import global_scope
    state = {k: np.asarray(v) for k, v in global_scope().items()}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=4)


def load(program, model_path, executor=None, var_list=None):
    import pickle
    from .program import global_scope
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    global_scope().update(state)
from . import amp  # noqa: F401,E402  (paddle.static.amp.decorate)
