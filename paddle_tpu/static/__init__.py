"""paddle.static namespace (reference `python/paddle/static/`)."""
from ..nn import functional as _F  # noqa: F401
from .input_spec import InputSpec
from .program import (Executor, Program, Variable, append_backward, data,
                      default_main_program, default_startup_program,
                      disable_static, enable_static, global_scope,
                      in_static_mode, program_guard, scope_guard)

# static layer API (paddle.static.nn)
from . import nn  # noqa: F401
from .nn import cond, while_loop  # noqa: F401


def save(program, model_path, **kwargs):
    import pickle
    import numpy as np
    from .program import global_scope
    state = {k: np.asarray(v) for k, v in global_scope().items()}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=4)


def load(program, model_path, executor=None, var_list=None):
    import pickle
    from .program import global_scope
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    global_scope().update(state)
