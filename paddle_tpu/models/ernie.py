"""ERNIE/BERT-family encoder (capability target: PaddleNLP ERNIE-base on
the reference stack — built here from paddle_tpu.nn.TransformerEncoder;
reference layer semantics per `python/paddle/nn/layer/transformer.py`).

TPU-first: bf16-friendly (AMP autocast covers the MXU ops), flash-attention
via F.scaled_dot_product_attention, and `tp_annotate` lays Megatron-style
GSPMD partition specs onto the encoder weights so the same model runs
dense, TP, or TP+DP+SP purely by mesh choice.
"""
from __future__ import annotations

from .. import nn
from ..framework.tensor import Tensor
from ..nn import initializer as I
from ..ops import creation, manipulation

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ErnieForPretraining", "ErniePooler", "tp_annotate"]


class ErnieConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, pad_token_id=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.pad_token_id = pad_token_id

    @classmethod
    def base(cls):
        return cls()

    @classmethod
    def tiny(cls):
        return cls(vocab_size=1024, hidden_size=128, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=256,
                   max_position_embeddings=128)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=init)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size,
                                                weight_attr=init)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size,
                                                  weight_attr=init)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        seq_len = input_ids.shape[1]
        if position_ids is None:
            position_ids = creation.arange(0, seq_len, dtype="int64")
            position_ids = manipulation.expand(
                manipulation.reshape(position_ids, [1, seq_len]),
                [input_ids.shape[0], seq_len])
        if token_type_ids is None:
            token_type_ids = creation.zeros_like(input_ids)
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class ErniePooler(nn.Layer):
    def __init__(self, hidden_size):
        super().__init__()
        self.dense = nn.Linear(hidden_size, hidden_size)
        self.activation = nn.Tanh()

    def forward(self, hidden_states):
        return self.activation(self.dense(hidden_states[:, 0]))


class ErnieModel(nn.Layer):
    def __init__(self, cfg: ErnieConfig = None, **kwargs):
        super().__init__()
        cfg = cfg or ErnieConfig(**kwargs)
        self.config = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob, act_dropout=0.0)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             cfg.num_hidden_layers)
        self.pooler = ErniePooler(cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] 1/0 → additive [B, 1, 1, S]
            am = manipulation.unsqueeze(attention_mask, [1, 2])
            attention_mask = (1.0 - manipulation.cast(am, "float32")) * -1e4
        out = self.encoder(emb, attention_mask)
        pooled = self.pooler(out)
        return out, pooled


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, cfg: ErnieConfig = None, num_classes=2, dropout=None,
                 **kwargs):
        super().__init__()
        self.ernie = ErnieModel(cfg, **kwargs)
        c = self.ernie.config
        self.dropout = nn.Dropout(dropout if dropout is not None
                                  else c.hidden_dropout_prob)
        self.classifier = nn.Linear(c.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask)
        return self.classifier(self.dropout(pooled))


class ErnieForPretraining(nn.Layer):
    """MLM + NSP heads."""

    def __init__(self, cfg: ErnieConfig = None, **kwargs):
        super().__init__()
        self.ernie = ErnieModel(cfg, **kwargs)
        c = self.ernie.config
        self.mlm_transform = nn.Linear(c.hidden_size, c.hidden_size)
        self.mlm_norm = nn.LayerNorm(c.hidden_size, epsilon=1e-12)
        self.mlm_bias = self.create_parameter([c.vocab_size], is_bias=True)
        self.nsp = nn.Linear(c.hidden_size, 2)
        self.act = nn.GELU()

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                 attention_mask)
        h = self.mlm_norm(self.act(self.mlm_transform(seq)))
        # tied output embedding: h @ E^T (one more MXU matmul)
        from ..ops.linalg import matmul
        logits = matmul(h, self.ernie.embeddings.word_embeddings.weight,
                        transpose_y=True) + self.mlm_bias
        return logits, self.nsp(pooled)


def tp_annotate(layer):
    """Megatron-style GSPMD specs on a Transformer(-Encoder/Decoder) stack:
    q/k/v & FFN-up weights column-parallel ('mp' on out dim), out_proj &
    FFN-down row-parallel ('mp' on in dim), embeddings vocab-parallel.
    The forward stays dense; XLA partitions (reference equivalent:
    `distributed/collective.py:566` split + hand-inserted collectives)."""
    from ..distributed.tensor_parallel import mark_sharding
    for name, p in layer.named_parameters():
        ln = name.lower()
        if p.ndim == 2:
            if any(k in ln for k in ("q_proj.weight", "k_proj.weight",
                                     "v_proj.weight", "linear1.weight")):
                mark_sharding(p, None, "mp")
            elif any(k in ln for k in ("out_proj.weight", "linear2.weight")):
                mark_sharding(p, "mp", None)
            elif "word_embeddings.weight" in ln or "embed_tokens" in ln:
                mark_sharding(p, "mp", None)
        elif p.ndim == 1:
            if any(k in ln for k in ("q_proj.bias", "k_proj.bias",
                                     "v_proj.bias", "linear1.bias")):
                mark_sharding(p, "mp")
    return layer
