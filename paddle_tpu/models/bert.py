"""BERT model family (reference PaddleNLP `transformers/bert/modeling.py`;
the in-repo reference op surface is the same encoder ERNIE uses —
`python/paddle/nn/layer/transformer.py`).

BERT and ERNIE share the identical encoder architecture (the difference
is pretraining data/objectives, not graph structure), so the BERT classes
are thin configuration aliases over the ERNIE tower — same fused-QKV
attention, same TP annotations. Kept as a separate namespace because the
reference ships them as distinct model families with distinct configs."""
from __future__ import annotations

from .ernie import (ErnieConfig, ErnieForPretraining,
                    ErnieForSequenceClassification, ErnieModel)

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForPretraining"]


class BertConfig(ErnieConfig):
    @classmethod
    def base(cls):
        return cls(vocab_size=30522, hidden_size=768,
                   num_hidden_layers=12, num_attention_heads=12,
                   intermediate_size=3072)

    @classmethod
    def large(cls):
        return cls(vocab_size=30522, hidden_size=1024,
                   num_hidden_layers=24, num_attention_heads=16,
                   intermediate_size=4096)


class BertModel(ErnieModel):
    pass


class BertForSequenceClassification(ErnieForSequenceClassification):
    pass


class BertForPretraining(ErnieForPretraining):
    pass
