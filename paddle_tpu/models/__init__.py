from .bert import (BertConfig, BertForPretraining,
                   BertForSequenceClassification, BertModel)
from .ernie import (ErnieConfig, ErnieForPretraining,
                    ErnieForSequenceClassification, ErnieModel, tp_annotate)
from .gpt import GPTConfig, GPTForCausalLM, GPTModel, MoEFeedForward
