"""GPT-style causal decoder (capability target: PaddleNLP GPT / ERNIE-3.0
decoder stacks on the reference). TPU-first: causal flash attention,
optional ring-attention sequence parallelism, optional MoE FFN with
expert parallelism over the 'ep' mesh axis."""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..framework.tensor import Tensor, apply_op
from ..nn import functional as F
from ..nn import initializer as I
from ..ops import creation, manipulation

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "MoEFeedForward"]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072,
                 max_position_embeddings=1024, dropout=0.1,
                 use_moe=False, num_experts=8, moe_top_k=1,
                 initializer_range=0.02):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.dropout = dropout
        self.use_moe = use_moe
        self.num_experts = num_experts
        self.moe_top_k = moe_top_k
        self.initializer_range = initializer_range

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                 intermediate_size=128, max_position_embeddings=128)
        d.update(kw)
        return cls(**d)


class CausalSelfAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.q_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.k_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.v_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.out_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.dropout = cfg.dropout

    def forward(self, x, use_ring=False):
        b, s, e = x.shape
        def shape(t):
            t = manipulation.reshape(t, [b, s, self.num_heads, self.head_dim])
            return manipulation.transpose(t, [0, 2, 1, 3])
        q, k, v = shape(self.q_proj(x)), shape(self.k_proj(x)), \
            shape(self.v_proj(x))
        if use_ring:
            from ..parallel.mesh import get_mesh
            from ..parallel.ring_attention import shard_map_ring_attention
            mesh = get_mesh()
            out = apply_op(
                "ring_attention",
                lambda qq, kk, vv: shard_map_ring_attention(
                    qq, kk, vv, mesh, causal=True), (q, k, v), {})
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.dropout,
                training=self.training)
        out = manipulation.transpose(out, [0, 2, 1, 3])
        out = manipulation.reshape(out, [b, s, e])
        return self.out_proj(out)


class MoEFeedForward(nn.Layer):
    """Expert-parallel MoE FFN (new subsystem — absent in the reference;
    designed GSPMD-style: expert weights [E, d, f] sharded over 'ep',
    tokens dispatched with a dense one-hot combine so the whole layer is
    einsums XLA can partition; top-1 switch routing)."""

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 top_k=1):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        init = I.XavierUniform()
        self.gate = nn.Linear(hidden_size, num_experts)
        self.w_up = self.create_parameter(
            [num_experts, hidden_size, intermediate_size],
            default_initializer=init)
        self.w_down = self.create_parameter(
            [num_experts, intermediate_size, hidden_size],
            default_initializer=init)
        from ..distributed.tensor_parallel import mark_sharding
        mark_sharding(self.w_up, "ep", None, None)
        mark_sharding(self.w_down, "ep", None, None)

    def forward(self, x):
        def impl(h, wu, wd, gate_w, gate_b):
            import jax
            b, s, d = h.shape
            logits = h @ gate_w + gate_b  # [b,s,E]
            probs = jax.nn.softmax(logits, axis=-1)
            idx = jnp.argmax(probs, axis=-1)  # top-1 switch
            onehot = jax.nn.one_hot(idx, wu.shape[0], dtype=h.dtype)
            gatev = jnp.sum(probs * onehot, axis=-1, keepdims=True)
            # dense dispatch: [b,s,E,d] routed tokens (zero elsewhere)
            up = jnp.einsum("bse,bsd,edf->bsef", onehot, h, wu)
            act = jax.nn.gelu(up)
            down = jnp.einsum("bsef,efd->bsd", act, wd)
            return down * gatev
        return apply_op("moe_ffn", impl,
                        (x, self.w_up, self.w_down, self.gate.weight,
                         self.gate.bias), {})


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = CausalSelfAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        if cfg.use_moe:
            self.mlp = MoEFeedForward(cfg.hidden_size, cfg.intermediate_size,
                                      cfg.num_experts, cfg.moe_top_k)
        else:
            self.mlp = nn.Sequential(
                nn.Linear(cfg.hidden_size, cfg.intermediate_size),
                nn.GELU(),
                nn.Linear(cfg.intermediate_size, cfg.hidden_size))
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, use_ring=False):
        x = x + self.dropout(self.attn(self.ln1(x), use_ring=use_ring))
        x = x + self.dropout(self.mlp(self.ln2(x)))
        return x


class _GPTEmbeddingStage(nn.Layer):
    """Pipeline pre-section: token+position embedding (shares the GPT
    model's parameter Tensors; see parallel/pipeline.py)."""

    def __init__(self, gpt):
        super().__init__()
        self.wte = gpt.wte
        self.wpe = gpt.wpe
        self.drop = gpt.drop

    def forward(self, input_ids):
        b, s = input_ids.shape
        pos = creation.arange(0, s, dtype="int64")
        pos = manipulation.reshape(pos, [1, s])
        return self.drop(self.wte(input_ids) + self.wpe(pos))


class _GPTHeadStage(nn.Layer):
    """Pipeline post-section: final LN (+ tied LM head when lm=True)."""

    def __init__(self, gpt, lm):
        super().__init__()
        self.ln_f = gpt.ln_f
        self._lm = lm
        if lm:
            self.wte = gpt.wte  # tied head; dedup'd by named_parameters

    def forward(self, h):
        h = self.ln_f(h)
        if not self._lm:
            return h
        from ..ops.linalg import matmul
        return matmul(h, self.wte.weight, transpose_y=True)


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig = None, **kwargs):
        super().__init__()
        cfg = cfg or GPTConfig(**kwargs)
        self.config = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=init)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                                weight_attr=init)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, use_ring=False):
        b, s = input_ids.shape
        pos = creation.arange(0, s, dtype="int64")
        pos = manipulation.reshape(pos, [1, s])
        h = self.drop(self.wte(input_ids) + self.wpe(pos))
        for blk in self.blocks:
            h = blk(h, use_ring=use_ring)
        return self.ln_f(h)

    def pipeline_sections(self):
        """(pre, blocks, post) for heterogeneous pipeline parallelism
        (reference PipelineOptimizer splits a Program by device_guard,
        `fluid/optimizer.py:3718`; here the model declares its stages)."""
        return (_GPTEmbeddingStage(self), self.blocks,
                _GPTHeadStage(self, lm=False))


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg: GPTConfig = None, **kwargs):
        super().__init__()
        self.gpt = GPTModel(cfg, **kwargs)

    def forward(self, input_ids, use_ring=False):
        h = self.gpt(input_ids, use_ring=use_ring)
        from ..ops.linalg import matmul
        return matmul(h, self.gpt.wte.weight, transpose_y=True)

    def pipeline_sections(self):
        return (_GPTEmbeddingStage(self.gpt), self.gpt.blocks,
                _GPTHeadStage(self.gpt, lm=True))
