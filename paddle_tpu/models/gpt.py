"""GPT-style causal decoder (capability target: PaddleNLP GPT / ERNIE-3.0
decoder stacks on the reference). TPU-first: causal flash attention,
optional ring-attention sequence parallelism, optional MoE FFN with
expert parallelism over the 'ep' mesh axis."""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..framework.tensor import Tensor, apply_op
from ..nn import functional as F
from ..nn import initializer as I
from ..ops import creation, manipulation

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "MoEFeedForward",
           "gpt_prefill", "gpt_prefill_extend", "gpt_decode_step",
           "gpt_spec_verify", "gpt_logits", "dense_cache_write",
           "dense_cache_attend", "decode_weight_specs",
           "shard_decode_weights"]


# -- shared decode math (generate() AND serving.GenerationEngine) -----------
#
# One anchored re-expression of the Layer forward, cache-layout-agnostic:
# `gpt_prefill` runs the batched causal pass and RETURNS per-layer K/V
# (the caller writes them into its cache — contiguous [L,B,H,T,D]
# buffers for generate(), paged pools for the generation engine), and
# `gpt_decode_step` advances one position through caller-supplied
# `write_kv`/`attend` hooks. Keeping both consumers on these exact
# expressions is what makes the engine's greedy decode bit-anchored to
# tests/test_generate.py's full-forward oracle (within one compiled
# shape; cross-shape is float tolerance, the standard XLA caveat).


def _gen_ln(x, w, b):
    m = jnp.mean(x, -1, keepdims=True)
    v = jnp.var(x, -1, keepdims=True)
    return (x - m) / jnp.sqrt(v + 1e-5) * w + b


def _gen_w(w, dtype):
    """Resolve one decode-weight leaf: a raw array passes through; a
    weight-only-quantized leaf `(q_int8 [in,out], scale [out])` —
    produced by decode_weights() for quantization.WeightOnlyLinear
    projections — dequantizes HERE, inside the traced math, so the
    HBM-resident form stays int8 and XLA fuses convert+mul into the
    consuming matmul (the fp32 weight is a fused temporary only)."""
    if isinstance(w, tuple):
        q, s = w
        return q.astype(dtype) * s.astype(dtype)
    return w


def gpt_logits(W, h):
    """Final LN + tied LM head over hidden states `h` [..., E]."""
    lnfw, lnfb = W["lnf"]
    return _gen_ln(h, lnfw, lnfb) @ W["wte"].T


def _gen_block_pass(W, h, attend, *, num_heads, reduce=None):
    """The ONE batched transformer-block loop both prefill flavors run:
    LN → QKV heads → `attend(layer, q, k, v)` → output proj + MLP
    residuals, collecting per-layer K/V. The attention expression is
    the only thing that differs between a full prefill (causal within
    the batch) and a tail prefill (cached context + within-tail) — it
    lives in the caller's hook, so the `_gen_w` quant hooks, gelu
    flavor and head-reshape discipline can never diverge between the
    two paths. Returns `(h, ks, vs)`.

    Tensor parallel (ISSUE 19): under a shard_map body the projection
    leaves are head-sharded SLICES — wq/wk/wv/w1 column-parallel
    (num_heads is then the LOCAL head count), wo/w2 row-parallel — and
    `reduce` is the per-block partial-sum reduction (lax.psum over the
    'tp' axis), applied to the row-parallel matmul outputs BEFORE the
    replicated bias + residual add, the Megatron discipline that keeps
    bo/b2 counted exactly once. Head/hidden reshapes derive the local
    width from the tensors (-1), never from the replicated E."""
    import jax

    B, S = h.shape[:2]
    H = num_heads
    ks, vs = [], []
    for i, (l1w, l1b, wq, bq, wk, bk, wv, bv, wo, bo, l2w, l2b,
            w1, b1, w2, b2) in enumerate(W["blocks"]):
        x = _gen_ln(h, l1w, l1b)

        def heads(t):
            return t.reshape(B, S, H, -1).transpose(0, 2, 1, 3)
        q = heads(x @ _gen_w(wq, x.dtype) + bq)
        k = heads(x @ _gen_w(wk, x.dtype) + bk)
        v = heads(x @ _gen_w(wv, x.dtype) + bv)
        ks.append(k)
        vs.append(v)
        o = attend(i, q, k, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, -1)
        ow = o @ _gen_w(wo, h.dtype)
        if reduce is not None:
            ow = reduce(ow)
        h = h + (ow + bo)
        x2 = _gen_ln(h, l2w, l2b)
        mw = jax.nn.gelu(x2 @ _gen_w(w1, h.dtype) + b1,
                         approximate=False) @ _gen_w(w2, h.dtype)
        if reduce is not None:
            mw = reduce(mw)
        h = h + (mw + b2)
    return h, jnp.stack(ks), jnp.stack(vs)


def gpt_prefill(W, ids, *, num_heads, scale, reduce=None):
    """One batched causal pass over the whole prompt — the MXU sees
    [B,S,E] matmuls, not S tiny ones. Returns `(h, ks, vs)`: `h` [B,S,E]
    post-blocks pre-ln_f hidden states (project the position you need
    through `gpt_logits`), `ks`/`vs` [L,B,H,S,D] per-layer K/V for the
    caller's cache. Right-padded prompts are safe: causal masking keeps
    pad positions out of every real position's softmax (exact -1e30 →
    0.0), so the last REAL position's logits are pad-invariant within
    one compiled shape."""
    import jax

    _, S = ids.shape
    h = W["wte"][ids] + W["wpe"][jnp.arange(S)][None]

    def attend(layer, q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        causal = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(causal, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    return _gen_block_pass(W, h, attend, num_heads=num_heads,
                           reduce=reduce)


def gpt_prefill_extend(W, ids, positions, ctx_attend, *, num_heads,
                       scale, reduce=None):
    """Batched causal pass over a prompt TAIL whose prefix K/V already
    lives in an external cache (the prefix-cache hit path, ISSUE 12).

    ids [B, S_t] tail token ids at absolute positions `positions` [S_t]
    (the caller clamps pad positions into range); attention is
    delegated per layer to

        ctx_attend(layer, q, k, v) -> [B, H, S_t, D]

    with q/k/v the tail's own projections — the hook attends each tail
    query over (external cached context + the given within-tail K/V)
    and owns the cache layout, masks AND the softmax scale, the same
    seam discipline as `gpt_decode_step`'s write_kv/attend. Returns
    `(h, ks, vs)` exactly like `gpt_prefill` ([B,S_t,E] hidden states,
    [L,B,H,S_t,D] per-layer tail K/V for the caller's cache writes) —
    both flavors share `_gen_block_pass`, so the block math literally
    cannot diverge from the full-prefill oracle."""
    del scale  # the ctx_attend hook owns the scale (kept for symmetry)
    h = W["wte"][ids] + W["wpe"][positions][None]
    return _gen_block_pass(W, h, ctx_attend, num_heads=num_heads,
                           reduce=reduce)


def gpt_spec_verify(W, toks, positions, ctx_attend, *, num_heads,
                    reduce=None):
    """Batched multi-position decode block for speculative verification
    (ISSUE 14): score a [B, K+1] block of tokens — each row's current
    token followed by K draft tokens — at PER-ROW absolute positions
    [B, K+1] in one `_gen_block_pass`, so verifying K drafts costs one
    forward over K+1 positions instead of K+1 decode dispatches.

    Attention is delegated per layer to

        ctx_attend(layer, q, k, v) -> [B, H, K+1, D]

    with q/k/v the block's own projections — the hook attends each
    block query over (cached context + the given within-block K/V) and
    owns the cache layout, masks AND the softmax scale, exactly the
    `gpt_prefill_extend` seam batched over rows. Returns `(h, ks, vs)`
    ([B,K+1,E] hidden states, [L,B,H,K+1,D] per-layer block K/V for the
    caller's — acceptance-masked — cache writes). Sharing
    `_gen_block_pass` is what anchors verification to the decode-step
    oracle: the block math literally cannot diverge."""
    h = W["wte"][toks] + W["wpe"][positions]
    return _gen_block_pass(W, h, ctx_attend, num_heads=num_heads,
                           reduce=reduce)


def gpt_decode_step(W, tok, pos, cache, write_kv, attend, *, num_heads,
                    scale, reduce=None):
    """Single-position forward against an abstract KV cache.

    tok [B] int32; pos scalar or [B] int32 (THIS token's position —
    written before attending, so attention covers t <= pos). The cache
    is an opaque pytree threaded functionally through the hooks:

        write_kv(cache, layer, k, v, pos) -> cache     (k/v [B, H, D])
        attend(cache, layer, q, pos)      -> [B, H, D]

    Returns (logits [B, V], cache). Under tensor parallelism
    `num_heads` is the LOCAL head count and `reduce` the per-block
    psum — the `_gen_block_pass` contract, same placement."""
    import jax

    B = tok.shape[0]
    H = num_heads
    h = W["wte"][tok] + W["wpe"][pos]
    for i, (l1w, l1b, wq, bq, wk, bk, wv, bv, wo, bo, l2w, l2b,
            w1, b1, w2, b2) in enumerate(W["blocks"]):
        x = _gen_ln(h, l1w, l1b)
        q = (x @ _gen_w(wq, x.dtype) + bq).reshape(B, H, -1)
        k = (x @ _gen_w(wk, x.dtype) + bk).reshape(B, H, -1)
        v = (x @ _gen_w(wv, x.dtype) + bv).reshape(B, H, -1)
        cache = write_kv(cache, i, k, v, pos)
        o = attend(cache, i, q, pos).reshape(B, -1)
        ow = o @ _gen_w(wo, h.dtype)
        if reduce is not None:
            ow = reduce(ow)
        h = h + (ow + bo)
        x2 = _gen_ln(h, l2w, l2b)
        mw = jax.nn.gelu(x2 @ _gen_w(w1, h.dtype) + b1,
                         approximate=False) @ _gen_w(w2, h.dtype)
        if reduce is not None:
            mw = reduce(mw)
        h = h + (mw + b2)
    return gpt_logits(W, h), cache


def decode_weight_specs(W, axis="tp"):
    """PartitionSpec pytree matching a `decode_weights()` pytree, for
    head-sharded tensor parallelism over mesh axis `axis` (ISSUE 19,
    Megatron layout): wq/wk/wv/w1 column-parallel (output dim — the
    heads axis, since E = H*D — sharded, so their biases shard too),
    wo/w2 row-parallel (input dim sharded, biases replicated: they are
    added once AFTER the psum), embeddings/LNs replicated. A
    weight-only-quantized `(q_int8 [in,out], scale [out])` leaf shards
    its scale with the output dim it scales: split for column-parallel,
    replicated for row-parallel. The same tree serves as shard_map
    in_specs and as NamedSharding specs for the one-time device_put."""
    from jax.sharding import PartitionSpec as P
    rep = P()

    def col(w):
        return ((P(None, axis), P(axis)) if isinstance(w, tuple)
                else P(None, axis))

    def row(w):
        return ((P(axis, None), rep) if isinstance(w, tuple)
                else P(axis, None))

    blocks = [
        (rep, rep, col(wq), P(axis), col(wk), P(axis), col(wv), P(axis),
         row(wo), rep, rep, rep, col(w1), P(axis), row(w2), rep)
        for (l1w, l1b, wq, bq, wk, bk, wv, bv, wo, bo, l2w, l2b,
             w1, b1, w2, b2) in W["blocks"]]
    return {"wte": rep, "wpe": rep, "lnf": (rep, rep), "blocks": blocks}


def shard_decode_weights(W, mesh, axis="tp"):
    """One-time `device_put` of a `decode_weights()` pytree onto `mesh`
    under the `decode_weight_specs` layout. Explicit recursion instead
    of tree_map: a quantized `(q_int8, scale)` leaf is a tuple — the
    same container `lnf` uses — so structure-blind mapping can't tell
    a two-leaf container from a paired leaf."""
    import jax
    from jax.sharding import NamedSharding
    specs = decode_weight_specs(W, axis=axis)

    def put(w, s):
        if isinstance(w, tuple):
            return tuple(jax.device_put(x, NamedSharding(mesh, ss))
                         for x, ss in zip(w, s))
        return jax.device_put(w, NamedSharding(mesh, s))

    return {
        "wte": put(W["wte"], specs["wte"]),
        "wpe": put(W["wpe"], specs["wpe"]),
        "lnf": tuple(put(w, s) for w, s in zip(W["lnf"], specs["lnf"])),
        "blocks": [tuple(put(w, s) for w, s in zip(blk, sblk))
                   for blk, sblk in zip(W["blocks"], specs["blocks"])],
    }


def dense_cache_write(cache, layer, k, v, pos):
    """Contiguous-buffer cache hook: cache = (kbufs, vbufs) with shape
    [L,B,H,T,D], scalar `pos` (the whole batch decodes in lockstep —
    generate()'s layout)."""
    import jax

    kb, vb = cache
    kb = jax.lax.dynamic_update_slice(
        kb, k[None, :, :, None, :], (layer, 0, 0, pos, 0))
    vb = jax.lax.dynamic_update_slice(
        vb, v[None, :, :, None, :], (layer, 0, 0, pos, 0))
    return kb, vb


def dense_cache_attend(scale):
    """Attend hook over the contiguous cache (masked softmax over every
    position <= pos; same expression the paged reference gathers into —
    ops/paged_ops.cached_attention)."""
    from ..ops.paged_ops import cached_attention

    def attend(cache, layer, q, pos):
        kb, vb = cache
        return cached_attention(q, kb[layer], vb[layer], pos, scale)
    return attend


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072,
                 max_position_embeddings=1024, dropout=0.1,
                 use_moe=False, num_experts=8, moe_top_k=1,
                 initializer_range=0.02):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.dropout = dropout
        self.use_moe = use_moe
        self.num_experts = num_experts
        self.moe_top_k = moe_top_k
        self.initializer_range = initializer_range

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                 intermediate_size=128, max_position_embeddings=128)
        d.update(kw)
        return cls(**d)


class CausalSelfAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.q_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.k_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.v_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.out_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.dropout = cfg.dropout

    def forward(self, x, use_ring=False):
        b, s, e = x.shape
        def shape(t):
            t = manipulation.reshape(t, [b, s, self.num_heads, self.head_dim])
            return manipulation.transpose(t, [0, 2, 1, 3])
        q, k, v = shape(self.q_proj(x)), shape(self.k_proj(x)), \
            shape(self.v_proj(x))
        if use_ring:
            from ..parallel.mesh import get_mesh
            from ..parallel.ring_attention import shard_map_ring_attention
            mesh = get_mesh()
            out = apply_op(
                "ring_attention",
                lambda qq, kk, vv: shard_map_ring_attention(
                    qq, kk, vv, mesh, causal=True), (q, k, v), {})
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.dropout,
                training=self.training)
        out = manipulation.transpose(out, [0, 2, 1, 3])
        out = manipulation.reshape(out, [b, s, e])
        return self.out_proj(out)


class MoEFeedForward(nn.Layer):
    """Expert-parallel MoE FFN (new subsystem — absent in the reference;
    designed GSPMD-style: expert weights [E, d, f] sharded over 'ep',
    tokens dispatched with a dense one-hot combine so the whole layer is
    einsums XLA can partition; top-1 switch routing)."""

    def __init__(self, hidden_size, intermediate_size, num_experts,
                 top_k=1):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        init = I.XavierUniform()
        self.gate = nn.Linear(hidden_size, num_experts)
        self.w_up = self.create_parameter(
            [num_experts, hidden_size, intermediate_size],
            default_initializer=init)
        self.w_down = self.create_parameter(
            [num_experts, intermediate_size, hidden_size],
            default_initializer=init)
        from ..distributed.tensor_parallel import mark_sharding
        mark_sharding(self.w_up, "ep", None, None)
        mark_sharding(self.w_down, "ep", None, None)

    def forward(self, x):
        def impl(h, wu, wd, gate_w, gate_b):
            import jax
            b, s, d = h.shape
            logits = h @ gate_w + gate_b  # [b,s,E]
            probs = jax.nn.softmax(logits, axis=-1)
            idx = jnp.argmax(probs, axis=-1)  # top-1 switch
            onehot = jax.nn.one_hot(idx, wu.shape[0], dtype=h.dtype)
            gatev = jnp.sum(probs * onehot, axis=-1, keepdims=True)
            # dense dispatch: [b,s,E,d] routed tokens (zero elsewhere)
            up = jnp.einsum("bse,bsd,edf->bsef", onehot, h, wu)
            act = jax.nn.gelu(up)
            down = jnp.einsum("bsef,efd->bsd", act, wd)
            return down * gatev
        return apply_op("moe_ffn", impl,
                        (x, self.w_up, self.w_down, self.gate.weight,
                         self.gate.bias), {})


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = CausalSelfAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        if cfg.use_moe:
            self.mlp = MoEFeedForward(cfg.hidden_size, cfg.intermediate_size,
                                      cfg.num_experts, cfg.moe_top_k)
        else:
            self.mlp = nn.Sequential(
                nn.Linear(cfg.hidden_size, cfg.intermediate_size),
                nn.GELU(),
                nn.Linear(cfg.intermediate_size, cfg.hidden_size))
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, use_ring=False):
        x = x + self.dropout(self.attn(self.ln1(x), use_ring=use_ring))
        x = x + self.dropout(self.mlp(self.ln2(x)))
        return x


class _GPTEmbeddingStage(nn.Layer):
    """Pipeline pre-section: token+position embedding (shares the GPT
    model's parameter Tensors; see parallel/pipeline.py)."""

    def __init__(self, gpt):
        super().__init__()
        self.wte = gpt.wte
        self.wpe = gpt.wpe
        self.drop = gpt.drop

    def forward(self, input_ids):
        b, s = input_ids.shape
        pos = creation.arange(0, s, dtype="int64")
        pos = manipulation.reshape(pos, [1, s])
        return self.drop(self.wte(input_ids) + self.wpe(pos))


class _GPTHeadStage(nn.Layer):
    """Pipeline post-section: final LN (+ tied LM head when lm=True)."""

    def __init__(self, gpt, lm):
        super().__init__()
        self.ln_f = gpt.ln_f
        self._lm = lm
        if lm:
            self.wte = gpt.wte  # tied head; dedup'd by named_parameters

    def forward(self, h):
        h = self.ln_f(h)
        if not self._lm:
            return h
        from ..ops.linalg import matmul
        return matmul(h, self.wte.weight, transpose_y=True)


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig = None, **kwargs):
        super().__init__()
        cfg = cfg or GPTConfig(**kwargs)
        self.config = cfg
        init = I.Normal(0.0, cfg.initializer_range)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=init)
        self.wpe = nn.Embedding(cfg.max_position_embeddings, cfg.hidden_size,
                                weight_attr=init)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = nn.LayerList([GPTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, use_ring=False):
        b, s = input_ids.shape
        pos = creation.arange(0, s, dtype="int64")
        pos = manipulation.reshape(pos, [1, s])
        h = self.drop(self.wte(input_ids) + self.wpe(pos))
        for blk in self.blocks:
            h = blk(h, use_ring=use_ring)
        return self.ln_f(h)

    def pipeline_sections(self):
        """(pre, blocks, post) for heterogeneous pipeline parallelism
        (reference PipelineOptimizer splits a Program by device_guard,
        `fluid/optimizer.py:3718`; here the model declares its stages)."""
        return (_GPTEmbeddingStage(self), self.blocks,
                _GPTHeadStage(self, lm=False))


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg: GPTConfig = None, **kwargs):
        super().__init__()
        self.gpt = GPTModel(cfg, **kwargs)

    def forward(self, input_ids, use_ring=False):
        h = self.gpt(input_ids, use_ring=use_ring)
        from ..ops.linalg import matmul
        return matmul(h, self.gpt.wte.weight, transpose_y=True)

    def pipeline_sections(self):
        return (_GPTEmbeddingStage(self.gpt), self.gpt.blocks,
                _GPTHeadStage(self.gpt, lm=True))

    def decode_weights(self):
        """The decode-math weight pytree shared by `generate()` and
        `serving.GenerationEngine`: raw jnp leaves (value-fresh after
        training steps — they ride jitted programs as ARGUMENTS, never
        baked constants). A projection replaced by
        `quantization.WeightOnlyLinear` (quantize_weights) contributes a
        `(q_int8, scale)` leaf instead of a float array — the integer
        tensor is what rides HBM; `_gen_w` dequantizes inside the traced
        matmul (int4 layers unpack once to int8 here, still 4x smaller
        than fp32)."""
        gpt = self.gpt
        if gpt.config.use_moe:
            raise NotImplementedError("generate() with MoE blocks")

        def w(lin):
            leaf = getattr(lin, "quant_decode_leaf", None)
            return leaf() if leaf is not None else lin.weight._value

        return {
            "wte": gpt.wte.weight._value, "wpe": gpt.wpe.weight._value,
            "lnf": (gpt.ln_f.weight._value, gpt.ln_f.bias._value),
            "blocks": [(
                blk.ln1.weight._value, blk.ln1.bias._value,
                w(blk.attn.q_proj), blk.attn.q_proj.bias._value,
                w(blk.attn.k_proj), blk.attn.k_proj.bias._value,
                w(blk.attn.v_proj), blk.attn.v_proj.bias._value,
                w(blk.attn.out_proj),
                blk.attn.out_proj.bias._value,
                blk.ln2.weight._value, blk.ln2.bias._value,
                w(blk.mlp[0]), blk.mlp[0].bias._value,
                w(blk.mlp[2]), blk.mlp[2].bias._value)
                for blk in gpt.blocks],
        }

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 top_k=None, temperature=1.0, seed=0):
        """Autoregressive decoding with a fixed-size KV cache (reference
        ecosystem: PaddleNLP GenerationMixin.generate/greedy_search).

        TPU design: ONE jax.jit program — prefill is a single batched
        [B,S,E] causal pass writing the whole prompt's K/V, decode is a
        `lax.scan` over `max_new_tokens` steps; K/V live in
        [L, B, H, T, D] buffers written in place with
        dynamic_update_slice, so shapes are static for every step and
        nothing retraces per token. Weights ride as jit ARGUMENTS
        (value-fresh after training steps) and the compiled program is
        memoized per static config. Eval-mode math (no dropout); the
        decode math is the shared `gpt_prefill`/`gpt_decode_step`
        internals (also serving.GenerationEngine's), anchored to the
        Layer forward by tests/test_generate.py's full-forward oracle."""
        import jax

        gpt = self.gpt
        cfg = gpt.config
        ids = jnp.asarray(
            input_ids._value if isinstance(input_ids, Tensor)
            else input_ids, jnp.int32)
        B, S = ids.shape
        T = S + int(max_new_tokens)
        if T > cfg.max_position_embeddings:
            raise ValueError(
                f"{T} positions exceed max_position_embeddings="
                f"{cfg.max_position_embeddings}")
        weights = self.decode_weights()
        L, E = cfg.num_layers, cfg.hidden_size
        H = cfg.num_heads
        D = E // H
        scale = 1.0 / D ** 0.5

        cfg_key = (B, S, int(max_new_tokens), bool(do_sample),
                   int(top_k or 0), float(temperature))
        cached = getattr(self, "_gen_jit_cache", None)
        if cached is None:
            cached = self._gen_jit_cache = {}
        run = cached.get(cfg_key)
        if run is None:
            attend = dense_cache_attend(scale)

            def sample(logits, key):
                if not do_sample:
                    return jnp.argmax(logits, -1).astype(jnp.int32)
                lg = logits / jnp.maximum(temperature, 1e-6)
                if top_k:
                    kth = jax.lax.top_k(lg, int(top_k))[0][..., -1:]
                    lg = jnp.where(lg < kth, -1e30, lg)
                return jax.random.categorical(key, lg).astype(jnp.int32)

            def run_fn(W, ids, key):
                kbufs = jnp.zeros((L, B, H, T, D), W["wte"].dtype)
                vbufs = jnp.zeros_like(kbufs)
                h, ks, vs = gpt_prefill(W, ids, num_heads=H, scale=scale)
                kbufs = kbufs.at[:, :, :, :S].set(ks)
                vbufs = vbufs.at[:, :, :, :S].set(vs)
                logits = gpt_logits(W, h[:, -1])

                def dec(carry, _):
                    lg, pos, kb, vb, key = carry
                    key, sub = jax.random.split(key)
                    tok = sample(lg, sub)
                    lg2, (kb, vb) = gpt_decode_step(
                        W, tok, pos, (kb, vb), dense_cache_write, attend,
                        num_heads=H, scale=scale)
                    return (lg2, pos + 1, kb, vb, key), tok
                _, toks = jax.lax.scan(
                    dec, (logits, jnp.asarray(S, jnp.int32), kbufs,
                          vbufs, key), None,
                    length=int(max_new_tokens))
                return jnp.concatenate([ids, toks.T], axis=1)

            run = cached[cfg_key] = jax.jit(run_fn)

        out = run(weights, ids, jax.random.PRNGKey(int(seed)))
        return Tensor(out)
