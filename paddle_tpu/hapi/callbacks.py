"""Callbacks (reference `python/paddle/hapi/callbacks.py`).

Loss values in `logs` may be LAZY (framework.deferred.DeferredScalar
device handles): the fit loop only materializes host floats on the
`log_freq` cadence so the hot loop never blocks on a device->host sync.
Callbacks that need a number coerce via `_as_float` / `float(v)` — which
IS a sync point, so only do it on paths that already print/persist.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "LRScheduler", "EarlyStopping", "VisualDL", "config_callbacks"]


def _as_float(v):
    """Host float from int/float/0-d array/DeferredScalar; None if `v`
    isn't scalar-like. Forces a device sync for lazy values."""
    if isinstance(v, bool):
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            c.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            c.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_begin")(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_end")(step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._epoch_t0 = time.time()

    @staticmethod
    def _items(logs):
        out = []
        for k, v in (logs or {}).items():
            if k in ("step", "batch_size"):
                continue
            f = _as_float(v)  # sync point for lazy losses; we're printing
            out.append(f"{k}: {f:.4f}" if f is not None else f"{k}: {v}")
        return out

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and step % self.log_freq == 0:
            print(f"Epoch {self.epoch + 1}/{self.epochs} "
                  f"step {step}/{self.steps} - " + " - ".join(
                      self._items(logs)))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose >= 1:
            dt = time.time() - self._epoch_t0
            print(f"Epoch {epoch + 1}/{self.epochs} done ({dt:.1f}s) - "
                  + " - ".join(self._items(logs)))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir and self.model:
            path = os.path.join(self.save_dir, "final")
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(path)


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_lr", None)
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s is not None and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s is not None and self.by_epoch:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """Scalar logger over utils.LogWriter (reference
    `paddle.callbacks.VisualDL`; VisualDL itself isn't in this image —
    the JSONL scalar stream is the dashboard-agnostic equivalent)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._writer = None
        self._step = 0
        self._pending = []  # (step, key, lazy value) — flushed per epoch

    def on_train_begin(self, logs=None):
        from ..utils.log_writer import LogWriter
        self._writer = LogWriter(self.log_dir)

    _FLUSH_EVERY = 1024  # bounds pinned device scalars between flushes

    def on_train_batch_end(self, step, logs=None):
        if self._writer:
            self._step += 1
            # keep lazy losses lazy: buffer the handle and materialize in
            # bulk so scalar logging never blocks the hot loop per step
            for k, v in (logs or {}).items():
                self._pending.append((self._step, k, v))
            if len(self._pending) >= self._FLUSH_EVERY:
                self._flush()

    def _flush(self):
        pending, self._pending = self._pending, []
        if not pending:
            return
        from ..framework.deferred import materialize_many
        # all lazy handles ride ONE device->host transfer (shared helper
        # with Model.evaluate) — not one sync per entry; non-scalar
        # entries come back as None and are skipped
        for (step, k, _), f in zip(pending, materialize_many(
                v for _, _, v in pending)):
            if f is not None:
                self._writer.add_scalar(f"train/{k}", f, step)

    def on_epoch_end(self, epoch, logs=None):
        if self._writer:
            self._flush()
            self._writer.dump_stats(step=epoch)

    def on_train_end(self, logs=None):
        if self._writer:
            self._flush()
            self._writer.close()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"batch_size": batch_size, "epochs": epochs,
                   "steps": steps, "verbose": verbose, "metrics":
                   metrics or ["loss"]})
    return cl
