"""High-level Model API (reference `python/paddle/hapi/model.py:810`:
Model.fit:1299 / evaluate / predict / save:1043, dual Static/Dynamic
adapters :224/:609).

TPU-native: ONE adapter — the functional train step. prepare() captures
the network functionally; fit() drives a jax.jit-compiled
carry -> carry step — forward, backward and the optimizer update fused
into a single XLA program per input signature (what the reference needs
CompiledProgram + ParallelExecutor for). When fleet is initialized the
same step is pjit'ed over the device mesh (see distributed/fleet).

Training hot-loop contract (the zero-copy / async-dispatch design):

* The whole model state — (params, buffers, opt_state) — travels as ONE
  donated carry pytree: `jax.jit(step, donate_argnums=(0,))`. XLA updates
  parameters in place; no second copy of the model state is allocated per
  step (mirrors parallel/spmd.py and parallel/pipeline.py donation).
  `FLAGS_train_step_donate=0` turns donation off for A/B checks.
* While a fit() epoch is running, `Tensor._value` on the network is STALE
  (the donated buffers are consumed). The carry is written back by
  `_sync_carry()` on epoch boundaries, save(), load(), parameters(),
  summary() — eval/predict read the live carry directly without a flush.
  Standalone train_batch calls (custom loops, outside fit) write back
  every call, preserving the public contract that direct Layer reads —
  net(x), state_dict() — stay fresh.
* `train_batch` returns a device-resident DeferredScalar loss; fit() only
  forces host floats every `log_freq` steps, so the Python loop runs ahead
  of the accelerator (async dispatch) instead of blocking every batch.
  CAVEAT: prepared Metrics update on host (`_update_metrics` pulls the
  step outputs with np.asarray), so a model with metrics still syncs once
  per batch — the deferred-sync win currently applies to metric-less
  training; moving metric accumulation into the jitted step is the
  follow-up that lifts this.
* Input batches are staged onto the device one step ahead by
  io.DeviceFeeder (double buffer) when the DataLoader has
  `use_buffer_reader=True` (the default). Under fleet the feeder gets the
  mesh's batch placement (parallel.spmd.batch_placement), so each batch
  lands directly in its dp/sp-sharded layout and the sharded step's
  synchronous per-step device_put disappears (STAT_sharded_batch_puts
  stays flat).
* The fleet path keeps `_sharded_state` device-resident across fit steps
  exactly like the single-device donated carry: `write_back` to the
  network's Tensors runs on epoch boundaries / save / load / parameters
  only (STAT_sharded_carry_syncs), with the same poisoned-carry
  validation. `FLAGS_train_step_donate=0` restores per-step write-back.
* `FLAGS_train_tail_bucketing` (default on): with `drop_last=False` the
  last partial batch is padded up to the loader's batch size (rows
  replicated from the last real sample) and a row mask is folded into the
  loss mean, so the tail reuses the full-batch executable — exactly one
  train-step compile per epoch instead of one per tail shape. The mask
  zero-weights padded rows and divides by the real-row count; per-row
  losses on the real rows are untouched (requires a row-independent
  forward — the serving engine's contract — and a loss that reduces
  rows by mean/sum; otherwise the model falls back to the unpadded step
  once and warns). eval_batch/predict_batch share the same padding so
  their per-exact-shape jit caches stop growing one entry per tail shape.
* Sequence packing (io.packing.PackingCollator as the loader's
  collate_fn, marked by `emits_token_mask`): batches arrive as
  fixed-shape packs whose last leaf is a [rows, max_tokens] token
  validity mask. fit/evaluate pop it and fold it into the loss as a
  TOKEN mask — per-token losses normalize by real tokens only — while
  the network masks attention per segment
  (F.scaled_dot_product_attention(segment_ids=...) → splash kernel).
  The row-mask tail machinery is bypassed: a short tail is just a pack
  with more masked tokens, so one-compile-per-epoch carries over and a
  batch is never double-masked.

Monitor counters (framework/monitor.py): STAT_train_steps,
STAT_train_step_compiles (one per input-shape key), STAT_train_step_ns
(dispatch wall time), STAT_train_host_syncs (DeferredScalar
materializations), STAT_sharded_carry_syncs (fleet write-backs),
STAT_tail_pad_batches / STAT_tail_pad_compiles_avoided (tail bucketing).
"""
from __future__ import annotations

import os
import pickle
import warnings
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as frandom
from ..framework.deferred import DeferredScalar, materialize_many
from ..framework.flags import flag
from ..framework.functional import functionalize, get_buffers, get_params
from ..framework.monitor import STAT_ADD, STAT_SUB, stat_get, stat_time
from ..framework.tensor import Tensor
from ..io import DataLoader, Dataset
from ..io.device_loader import DeviceFeeder
from ..metric import Metric
from ..profiler import RecordEvent, device_telemetry, flight_recorder
from . import callbacks as cbks_mod

__all__ = ["Model"]


def _flatten_batch(data):
    if isinstance(data, dict):
        return list(data.values())
    if isinstance(data, (list, tuple)):
        return list(data)
    return [data]


class _TailMaskError(TypeError):
    """The prepared loss cannot expose per-row values, so a padded tail's
    row mask cannot be folded into it (raised at trace time)."""


def _batch_rows(leaves):
    for x in leaves:
        v = x._value if isinstance(x, Tensor) else x
        if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1:
            return int(v.shape[0])
    return None


def _pad_leaf(x, rows, target):
    """Grow a batch-major leaf to `target` rows by replicating its last
    real row (a real sample: stays in-distribution and finite, unlike
    zeros which can be invalid labels)."""
    v = x._value if isinstance(x, Tensor) else x
    if not (hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1
            and v.shape[0] == rows):
        return x
    v = jnp.asarray(v)
    v = jnp.concatenate([v, jnp.repeat(v[-1:], target - rows, axis=0)],
                        axis=0)
    return Tensor(v) if isinstance(x, Tensor) else v


def _real_rows(mask):
    """(padded_rows, real-row index array) for a loss mask. fit's own
    row masks are ones-prefixes, but loss_mask is a public train_batch/
    eval_batch parameter and may have holes. A token-level mask
    [rows, T] (packing) counts a row as real when ANY of its tokens is
    real — metrics then see whole packed rows, pad positions included
    (per-token metric masking is the packing contract's caveat)."""
    m = np.asarray(mask)
    if m.ndim > 1:
        m = (m.reshape(m.shape[0], -1) > 0).any(axis=1)
    return int(m.shape[0]), np.flatnonzero(m)


def _select_rows(leaves, padded_rows, idx):
    """Keep only the real rows of every batch-major leaf (host-side view
    for metrics / fallback reruns). A contiguous prefix uses a cheap
    slice; arbitrary masks gather by index."""
    n = len(idx)
    prefix = bool(np.array_equal(idx, np.arange(n)))
    out = []
    for x in leaves:
        v = x._value if isinstance(x, Tensor) else x
        if hasattr(v, "shape") and getattr(v, "ndim", 0) >= 1 and \
                v.shape[0] == padded_rows:
            sel = v[:n] if prefix else v[idx]
            out.append(Tensor(sel) if isinstance(x, Tensor) else sel)
        else:
            out.append(x)
    return out


def _steps_of(loader):
    """len(loader) or None — a generator has no __len__ and a DataLoader
    over an IterableDataset raises TypeError from its own; both mean the
    progress display falls back to countless mode."""
    if not hasattr(loader, "__len__"):
        return None
    try:
        return len(loader)
    except TypeError:
        return None


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._amp_level = None
        self._apply_fn = None
        self._opt_state = None
        self._train_carry = None  # donated {params,buffers,opt_state} pytree
        self._in_fit = False  # fit() defers carry write-back to epoch ends
        self._sharded_state = None  # fleet device-resident donated carry
        self._sharded_dirty = False  # sharded state ahead of the Tensors
        self._sharded_mask_live = False  # trace-time: mask rides labels[-1]
        self._tail_maskable = True  # cleared once the loss refuses a mask
        self._mask_cache = {}  # (mask bytes, sharded) -> placed device mask
        self._train_step_cache = {}
        self._eval_step_cache = {}
        self._pred_step_cache = {}
        self.stop_training = False
        self._dist_ctx = None  # set by fleet.distributed_model

    # -- preparation --------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        if amp_configs is not None:
            self._amp_level = (amp_configs if isinstance(amp_configs, str)
                               else amp_configs.get("level", "O1"))
        self._apply_fn, _, _ = functionalize(self.network)
        if optimizer is not None and getattr(
                optimizer, "_parameter_list", None) is None:
            optimizer._parameter_list = self.network.parameters()
        # fleet-distributed: route training through the SPMD sharded step
        # (reference `hapi/model.py:165` prepare_distributed_context)
        try:
            from ..distributed.fleet import fleet as _fleet
            from ..parallel.mesh import get_mesh
            mesh = get_mesh()
            if _fleet._inited and mesh is not None and \
                    mesh.devices.size > 1:
                self._dist_ctx = _fleet
        except Exception:
            self._dist_ctx = None
        return self

    # -- internals ----------------------------------------------------------
    def _loss_value(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        if self._loss is None:
            # network returns the loss directly
            v = outs[0]
            return v
        if callable(self._loss):
            return self._loss(*outs, *labels)
        raise TypeError("loss must be callable")

    def _masked_loss(self, outputs, labels, mask):
        """User loss folded with a validity mask.

        A 1-D mask [rows] is the tail row mask: padded rows get zero
        weight and the mean divides by the real-row count, so the scalar
        equals the loss of the unpadded batch (for losses that reduce
        rows by mean/sum). A 2-D mask [rows, T] is a TOKEN mask (the
        packing collator's last leaf): the loss must expose per-token
        values [rows, T(, ...)], padded tokens get zero weight and the
        mean divides by the REAL-TOKEN count — per-token losses
        normalize by real tokens only, which is the packing contract.

        Losses with a `reduction` attribute are traced with
        reduction='none' to expose per-element values; a loss that only
        yields a scalar raises _TailMaskError at trace time and the
        caller falls back.

        CAVEAT (row masks only): a loss whose mean has a data-dependent
        denominator (e.g. cross_entropy with ignore_index labels
        present) is reduced here as a mean of per-row means, which
        weights rows uniformly instead of by valid-element count. Token
        masks don't have the problem — the denominator IS the
        valid-token count.
        """
        m = mask._value if isinstance(mask, Tensor) else mask
        red = getattr(self._loss, "reduction", None)
        if red in ("mean", "sum"):
            self._loss.reduction = "none"
            try:
                lv = self._loss_value(outputs, labels)
            finally:
                self._loss.reduction = red
        else:
            lv = self._loss_value(outputs, labels)
        lv_raw = (lv._value if isinstance(lv, Tensor) else lv)
        lv_raw = lv_raw.astype("float32")
        rows = int(m.shape[0])
        if m.ndim == 2:
            T = int(m.shape[1])
            if lv_raw.ndim < 2 or tuple(lv_raw.shape[:2]) != (rows, T):
                raise _TailMaskError(
                    f"loss produced shape "
                    f"{tuple(getattr(lv_raw, 'shape', ()))} — not "
                    f"per-token over the ({rows}, {T}) pack, so the "
                    "token mask cannot be folded in; packed training "
                    "needs a per-token-maskable loss (e.g. "
                    "CrossEntropyLoss over [rows, T, C] logits)")
            per_tok = lv_raw.reshape((rows, T, -1))
            per_tok = (per_tok.sum(axis=2) if red == "sum"
                       else per_tok.mean(axis=2))
            # where, not multiply: a non-finite pad-token value must not
            # poison the sum through NaN * 0
            per_tok = jnp.where(m > 0, per_tok, jnp.zeros_like(per_tok))
            if red == "sum":
                return jnp.sum(per_tok)
            return jnp.sum(per_tok) / jnp.maximum(
                jnp.sum(m.astype("float32")), 1.0)
        if lv_raw.ndim < 1 or lv_raw.shape[0] != rows:
            raise _TailMaskError(
                f"loss produced shape {tuple(getattr(lv_raw, 'shape', ()))}"
                f" — not per-row over the {rows}-row batch, so the tail "
                "row mask cannot be folded in; set "
                "FLAGS_train_tail_bucketing=0 or use a loss with a "
                "mean/sum `reduction`")
        per_row = lv_raw.reshape((rows, -1))
        per_row = (per_row.sum(axis=1) if red == "sum"
                   else per_row.mean(axis=1))
        # where, not multiply: a non-finite padded-row value must not
        # poison the sum through NaN * 0
        per_row = jnp.where(m > 0, per_row, jnp.zeros_like(per_row))
        if red == "sum":
            return jnp.sum(per_row)
        return jnp.sum(per_row) / jnp.sum(m.astype("float32"))

    def _make_train_step(self):
        apply_fn = self._apply_fn
        opt = self._optimizer
        amp_level = self._amp_level

        def loss_fn(pv, bv, rng, inputs, labels, mask):
            def fwd():
                wrapped_in = [Tensor(x) for x in inputs]
                wrapped_lb = [Tensor(x) for x in labels]
                out, new_bufs = apply_fn(pv, bv, rng, True,
                                         *[w._value for w in wrapped_in])
                wout = jax.tree_util.tree_map(
                    lambda x: Tensor(x), out)
                if mask is None:
                    lv = self._loss_value(wout, wrapped_lb)
                else:
                    lv = self._masked_loss(wout, wrapped_lb, mask)
                return lv, (out, new_bufs)
            if amp_level:
                from .. import amp as amp_mod
                from ..framework.autograd import trace_mode
                with trace_mode(), amp_mod.auto_cast(level=amp_level):
                    lv, aux = fwd()
            else:
                from ..framework.autograd import trace_mode
                with trace_mode():
                    lv, aux = fwd()
            lv_raw = lv._value if isinstance(lv, Tensor) else lv
            return jnp.mean(lv_raw.astype("float32")), aux

        def step(carry, rng, step_no, lr, inputs, labels, mask=None):
            pv, bv, opt_state = (carry["params"], carry["buffers"],
                                 carry["opt_state"])
            (lv, (out, new_bufs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(pv, bv, rng, inputs, labels, mask)
            new_pv, new_state = opt.apply_gradients_pytree(
                grads, pv, opt_state, lr, step_no)
            return {"params": new_pv, "buffers": new_bufs,
                    "opt_state": new_state}, lv, out
        return step

    # -- carry management ----------------------------------------------------
    def _ensure_carry(self):
        """Device-resident {params, buffers, opt_state} pytree that the
        donated train step consumes and reproduces each step."""
        if self._train_carry is None:
            pv = {n: t._value
                  for n, t in get_params(self.network).items()}
            bv = {n: t._value
                  for n, t in get_buffers(self.network).items()}
            if self._opt_state is None:
                self._opt_state = self._optimizer.init_state_pytree(pv)
            self._train_carry = {"params": pv, "buffers": bv,
                                 "opt_state": self._opt_state}
        return self._train_carry

    def _sync_carry(self, validate=False):
        """Write the training carry back into the network's Tensors.

        Called on epoch boundaries, save(), load() and parameters() —
        NOT per step. After the first donated step of an epoch the
        Tensors' old buffers are consumed; anything that reads
        `Tensor._value` directly mid-epoch must flush through here first.

        `validate=True` (epoch boundaries and fit's error path) blocks
        until the carry is ready and DROPS it if the device computation
        failed: with async dispatch a step's XLA error surfaces at a
        later host sync, after the poisoned output carry was already
        installed — writing it back would leave the network's Tensors
        re-raising the XLA error on every read. NOTE: with donation
        active the Tensors' pre-epoch buffers were consumed by the first
        step, so after a drop the model state is NOT recoverable from the
        live network (reads raise "Array has been deleted") — recovery is
        via ModelCheckpoint epoch saves, which flush to host files. With
        FLAGS_train_step_donate=0 the Tensors keep valid pre-carry values.
        """
        self._sync_sharded_carry(validate=validate)
        carry = self._train_carry
        if carry is None:
            return
        if validate:
            try:
                jax.block_until_ready(jax.tree_util.tree_leaves(carry))
            except Exception as e:
                # device-side failure only (XLA runtime errors are
                # Exception subclasses): drop the poisoned carry.
                # KeyboardInterrupt/SystemExit propagate with the carry
                # kept installed — it is healthy, and a later
                # _sync_carry() still writes it back.
                self._train_carry = None
                self._opt_state = None  # rode the same poisoned step
                # the raised error says WHAT failed; the flight record
                # keeps the step/feeder timeline + counters around WHEN
                flight_recorder.dump("poisoned_carry", {
                    "error": repr(e),
                    "donate": bool(flag("FLAGS_train_step_donate")),
                    "train_steps": stat_get("STAT_train_steps")})
                return
        for n, t in get_params(self.network).items():
            t._value = carry["params"][n]
        for n, t in get_buffers(self.network).items():
            t._value = carry["buffers"][n]
        self._opt_state = carry["opt_state"]
        self._train_carry = None

    def _sync_sharded_carry(self, validate=False):
        """Fleet analogue of the single-device carry flush: write the
        device-resident `_sharded_state` params/buffers back into the
        network's Tensors. Unlike the single-device carry the state stays
        live (it carries the sharded optimizer moments across epochs);
        only the dirty bit clears. Same poisoned-carry rule: with
        `validate` a state whose async step failed is DROPPED, not
        written back (recovery is via checkpoint saves — the donated
        pre-epoch buffers were already consumed)."""
        if not getattr(self, "_sharded_dirty", False):
            return
        state = self._sharded_state
        if validate:
            try:
                jax.block_until_ready(jax.tree_util.tree_leaves(state))
            except Exception as e:
                # poisoned: never write failed arrays into the Tensors.
                # With donation off the Tensors are still healthy, so a
                # rebuilt step can restart from them; with donation on
                # the pre-epoch buffers are consumed — the next sharded
                # step raises until a checkpoint is loaded.
                self._sharded_state = None
                self._sharded_dirty = False
                if not getattr(self, "_sharded_donate", True) and \
                        hasattr(self, "_sharded_step"):
                    del self._sharded_step
                flight_recorder.dump("poisoned_sharded_carry", {
                    "error": repr(e),
                    "donate": getattr(self, "_sharded_donate", True),
                    "train_steps": stat_get("STAT_train_steps")})
                return
        from ..parallel.spmd import write_back
        write_back(self.network, state)
        STAT_ADD("STAT_sharded_carry_syncs")
        self._sharded_dirty = False

    def _current_values(self):
        """(params, buffers) value dicts for eval/predict: the live carry
        when training is in flight (no flush — eval doesn't donate), else
        the network's Tensors."""
        carry = self._train_carry
        if carry is not None:
            return carry["params"], carry["buffers"]
        state = getattr(self, "_sharded_state", None)
        if state is not None and getattr(self, "_sharded_dirty", False):
            # sharded training in flight: Tensors are stale until the
            # epoch-boundary write_back — read the live carry directly
            return state["params"], state["buffers"]
        return ({n: t._value for n, t in get_params(self.network).items()},
                {n: t._value for n, t in get_buffers(self.network).items()})

    def _placed_mask(self, loss_mask):
        """Device-resident loss mask, cached per exact ROW-mask pattern.

        fit passes the same handful of row masks every epoch (all-ones
        per full batch, one tail pattern); caching their placement keeps
        the hot loop free of per-step host->device mask uploads — and on
        the fleet path the dp-sharded placement lets the step's
        pre-placed fast path skip the mask too. Keyed by the exact byte
        pattern: train_batch's loss_mask parameter is public, and two
        masks with the same population count need not select the same
        rows. Token-level masks [rows, T] (packing) differ on every
        batch — they are placed but NOT cached (a byte-keyed cache
        would grow one entry per batch forever); they ride to the
        device like any other batch leaf, and one that is ALREADY a
        device array (the DeviceFeeder staged it with the rest of the
        pack) passes straight through instead of a device→host→device
        round trip in the hot loop."""
        mv = loss_mask._value if isinstance(loss_mask, Tensor) else loss_mask
        if isinstance(mv, jax.Array) and getattr(mv, "ndim", 0) > 1:
            return mv if mv.dtype == jnp.float32 \
                else mv.astype(jnp.float32)
        m = np.ascontiguousarray(np.asarray(loss_mask, "float32"))
        sharded = self._dist_ctx is not None
        key = None
        if m.ndim == 1:
            key = (m.tobytes(), sharded)
            hit = self._mask_cache.get(key)
            if hit is not None:
                return hit
        arr = jnp.asarray(m, "float32")
        if sharded:
            from ..parallel.mesh import get_mesh
            from ..parallel.spmd import batch_placement
            mesh = get_mesh()
            if mesh is not None:
                # batch_placement leaves a row count that does not
                # divide dp unsharded instead of hard-failing device_put
                sh = batch_placement(mesh)(m)
                if sh is not None:
                    arr = jax.device_put(arr, sh)
        if key is not None:
            self._mask_cache[key] = arr
        return arr

    @staticmethod
    def _is_token_mask(loss_mask):
        m = loss_mask._value if isinstance(loss_mask, Tensor) else loss_mask
        return m is not None and getattr(m, "ndim", 1) > 1

    def _mask_fallback(self, inputs, labels, loss_mask):
        """A loss that cannot fold the tail row mask: warn once, pin the
        model to unpadded tails, and rerun this batch on its real rows.

        Row masks only — a TOKEN mask (packing) has no unpadded shape to
        fall back to (the pack IS the batch), so its _TailMaskError
        propagates: packed training requires a per-token-maskable loss."""
        if getattr(self, "_tail_maskable", True):
            self._tail_maskable = False
            warnings.warn(
                "FLAGS_train_tail_bucketing: the prepared loss does not "
                "expose per-row values; falling back to unpadded tail "
                "batches (one extra XLA compile per tail shape)",
                stacklevel=3)
        rows, idx = _real_rows(loss_mask)
        return (_select_rows(inputs, rows, idx),
                _select_rows(labels, rows, idx))

    def train_batch(self, inputs, labels=None, update=True, loss_mask=None):
        if self._dist_ctx is not None:
            return self._train_batch_sharded(inputs, labels,
                                             loss_mask=loss_mask)
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        labels = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(labels or [])]
        carry = self._ensure_carry()
        donate = bool(flag("FLAGS_train_step_donate"))
        mask = None if loss_mask is None else self._placed_mask(loss_mask)
        key = (donate,
               tuple((tuple(a.shape), str(a.dtype)) for a in inputs),
               tuple((tuple(a.shape), str(a.dtype)) for a in labels),
               None if mask is None else tuple(mask.shape))
        fn = self._train_step_cache.get(key)
        if fn is None:
            fn = jax.jit(self._make_train_step(),
                         donate_argnums=(0,) if donate else ())
            self._train_step_cache[key] = fn
            STAT_ADD("STAT_train_step_compiles")
        rng = frandom.get_rng_key()
        step_no = getattr(self, "_global_step", 0) + 1
        self._global_step = step_no
        try:
            with stat_time("STAT_train_step_ns"):
                new_carry, lv, out = fn(
                    carry, rng, jnp.asarray(step_no, "int32"),
                    jnp.asarray(self._optimizer.get_lr(), "float32"),
                    tuple(inputs), tuple(labels), mask)
        except _TailMaskError:
            # trace-time failure: the carry was never dispatched into —
            # rerun the real rows through the plain (unpadded) step. The
            # evicted entry never produced an executable, so it does not
            # count against the compile budget either.
            if self._train_step_cache.pop(key, None) is not None:
                STAT_SUB("STAT_train_step_compiles")
            self._global_step = step_no - 1
            if self._is_token_mask(loss_mask):
                raise  # packing: no unpadded shape to fall back to
            ins, lbs = self._mask_fallback(inputs, labels, loss_mask)
            return self.train_batch(ins, lbs, update=update)
        except BaseException:
            # a step that died mid-call may have consumed the donated
            # carry (XLA error after dispatch). Keep the carry when its
            # buffers are intact (trace-time error, Ctrl-C before
            # dispatch, donation inactive) — that preserves the last
            # completed step — but drop it once consumed so the
            # epoch-boundary _sync_carry never writes deleted buffers
            # back into the network's Tensors.
            if any(getattr(leaf, "is_deleted", lambda: False)()
                   # lint: allow(use-after-donate): is_deleted() probes buffer liveness metadata without touching the (possibly deleted) data — detecting a consumed carry is this handler's whole purpose
                   for leaf in jax.tree_util.tree_leaves(carry)):
                self._train_carry = None
                self._opt_state = None  # its arrays rode the same donation
            raise
        self._train_carry = new_carry
        STAT_ADD("STAT_train_steps")
        if device_telemetry.active() and \
                key not in getattr(self, "_flops_noted_keys", ()):
            # estimated per-step FLOPs for the MFU gauge — HLO cost
            # analysis on the lowered module, no second backend compile;
            # new_carry shares the (possibly donated) carry's avals.
            # Keyed on the compile-cache key and gated on the sampler
            # being live, so telemetry enabled mid-training still gets
            # FLOPs on the next step while inactive processes never pay
            # the retrace.
            if not hasattr(self, "_flops_noted_keys"):
                self._flops_noted_keys = set()
            self._flops_noted_keys.add(key)
            device_telemetry.note_train_step_lowering(
                fn, (new_carry, rng, jnp.asarray(step_no, "int32"),
                     jnp.asarray(self._optimizer.get_lr(), "float32"),
                     tuple(inputs), tuple(labels), mask))
        if not self._in_fit:
            # public custom-loop contract: a standalone train_batch call
            # writes updated params back to the network's Tensors (cheap
            # reference stores), so direct Layer reads — net(x),
            # state_dict() — stay valid. Only fit() keeps the carry live
            # across steps.
            self._sync_carry()
        outs = jax.tree_util.tree_leaves(out)
        if loss_mask is not None and self._metrics and \
                not self._is_token_mask(loss_mask):
            # metrics must never see the masked-out rows. Token masks
            # (packing) skip this: metrics see whole packed rows by
            # contract (pad positions included — README caveat), and a
            # per-batch _real_rows would force a device->host copy of a
            # feeder-staged mask in the hot loop
            rows, idx = _real_rows(loss_mask)
            if len(idx) < rows:
                outs = _select_rows(outs, rows, idx)
                labels = _select_rows(labels, rows, idx)
        metrics = self._update_metrics(outs, labels)
        loss = DeferredScalar(lv)
        return (loss, metrics) if self._metrics else ([loss], metrics)

    def _train_batch_sharded(self, inputs, labels, loss_mask=None):
        """fleet path: one pjit'ed step over the mesh (dp/tp/zero per
        strategy). The state is a device-resident donated carry like the
        single-device path: inside fit it stays live across steps and is
        written back to the network's Tensors on epoch boundaries only
        (`_sync_sharded_carry`); standalone calls — and
        FLAGS_train_step_donate=0 — keep the per-call write-back
        contract. A padded tail's row mask rides along as an extra
        dp-sharded "label" so the pjit signature (and the single
        compiled executable) is shared with full batches."""
        donate = bool(flag("FLAGS_train_step_donate"))
        if not hasattr(self, "_sharded_step"):
            def loss_fn(outs, lbs):
                out = outs[0] if isinstance(outs, (list, tuple)) else outs
                if self._sharded_mask_live:
                    mask = lbs[-1]
                    lv = self._masked_loss(out, list(lbs[:-1]), mask)
                    return Tensor(lv)
                return self._loss_value(out, lbs)
            self._sharded_donate = donate
            self._sharded_step, self._sharded_state = \
                self._dist_ctx.build_sharded_train_step(
                    self.network, self._optimizer, loss_fn, donate=donate)
        if self._sharded_state is None:
            raise RuntimeError(
                "sharded training state was dropped after a failed step "
                "and the donated pre-epoch buffers are consumed; restore "
                "from a checkpoint (Model.load) before training on")
        ins = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
               for t in _flatten_batch(inputs)]
        lbs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
               for t in _flatten_batch(labels or [])]
        if loss_mask is not None:
            lbs = lbs + [self._placed_mask(loss_mask)]
        # read at trace time by loss_fn; consistent because pjit retraces
        # exactly when the label structure (mask present/absent) changes
        self._sharded_mask_live = loss_mask is not None
        state = self._sharded_state
        try:
            with stat_time("STAT_train_step_ns"):
                new_state, lv = self._sharded_step(
                    state, tuple(ins), tuple(lbs))
        except _TailMaskError:
            if self._is_token_mask(loss_mask):
                raise  # packing: no unpadded shape to fall back to
            ins, lbs = self._mask_fallback(ins, lbs[:-1], loss_mask)
            return self._train_batch_sharded(ins, lbs)
        except BaseException:
            # same donated-carry hygiene as the single-device path: a
            # step that consumed the donated state mid-failure must not
            # leave deleted buffers where the epoch-end write_back (or
            # the next step) will read them
            if any(getattr(leaf, "is_deleted", lambda: False)()
                   for leaf in jax.tree_util.tree_leaves(state)):
                self._sharded_state = None
                self._sharded_dirty = False
            raise
        self._sharded_state = new_state
        self._sharded_dirty = True
        STAT_ADD("STAT_train_steps")
        if not (self._in_fit and getattr(self, "_sharded_donate", donate)):
            # standalone contract / donation off: Tensors stay fresh
            self._sync_sharded_carry()
        loss = DeferredScalar(lv)
        return (loss, []) if self._metrics else ([loss], [])

    def eval_batch(self, inputs, labels=None, loss_mask=None):
        pv, bv = self._current_values()
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        labels = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(labels or [])]
        mask = None if loss_mask is None else self._placed_mask(loss_mask)
        key = (tuple((tuple(a.shape), str(a.dtype))
                     for a in inputs + labels),
               None if mask is None else tuple(mask.shape))
        fn = self._eval_step_cache.get(key)
        if fn is None:
            apply_fn = self._apply_fn

            def estep(pv_, bv_, rng, ins, lbs, mask_=None):
                from ..framework.autograd import trace_mode
                out, _ = apply_fn(pv_, bv_, rng, False, *ins)
                with trace_mode():
                    wout = jax.tree_util.tree_map(lambda x: Tensor(x), out)
                    if self._loss is not None and lbs:
                        wlbs = [Tensor(x) for x in lbs]
                        lv = (self._loss_value(wout, wlbs) if mask_ is None
                              else Tensor(self._masked_loss(wout, wlbs,
                                                            mask_)))
                    else:
                        lv = None
                lv_raw = (jnp.mean(lv._value.astype("float32"))
                          if isinstance(lv, Tensor) else
                          (lv if lv is not None else jnp.zeros(())))
                return lv_raw, out
            fn = jax.jit(estep)
            self._eval_step_cache[key] = fn
        rng = frandom.get_rng_key()
        try:
            lv, out = fn(pv, bv, rng, tuple(inputs), tuple(labels), mask)
        except _TailMaskError:
            self._eval_step_cache.pop(key, None)
            if self._is_token_mask(loss_mask):
                raise  # packing: no unpadded shape to fall back to
            ins, lbs = self._mask_fallback(inputs, labels, loss_mask)
            return self.eval_batch(ins, lbs)
        outs = jax.tree_util.tree_leaves(out)
        if loss_mask is not None and not self._is_token_mask(loss_mask):
            # token masks skip row filtering — same contract and hot-loop
            # reasoning as train_batch above
            rows, idx = _real_rows(loss_mask)
            if len(idx) < rows:
                outs = _select_rows(outs, rows, idx)
                labels = _select_rows(labels, rows, idx)
        metrics = self._update_metrics(outs, labels)
        return DeferredScalar(lv), metrics

    def predict_batch(self, inputs, nreal=None):
        """`nreal` (tail bucketing): the batch was padded; only the first
        `nreal` output rows are returned — and the padded shape means the
        per-exact-shape jit cache gets no tail-shape entry."""
        pv, bv = self._current_values()
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        key = tuple((tuple(a.shape), str(a.dtype)) for a in inputs)
        fn = self._pred_step_cache.get(key)
        if fn is None:
            apply_fn = self._apply_fn
            fn = jax.jit(lambda pv_, bv_, rng, ins: apply_fn(
                pv_, bv_, rng, False, *ins)[0])
            self._pred_step_cache[key] = fn
        out = fn(pv, bv, frandom.get_rng_key(), tuple(inputs))
        rows = _batch_rows(inputs)
        out = jax.tree_util.tree_map(lambda x: np.asarray(x), out)
        if nreal is not None and rows is not None and nreal < rows:
            out = jax.tree_util.tree_map(
                lambda x: (x[:nreal] if (hasattr(x, "shape")
                                         and getattr(x, "ndim", 0) >= 1
                                         and x.shape[0] == rows) else x),
                out)
        return out

    def _update_metrics(self, outputs, labels):
        res = []
        for m in self._metrics:
            inp = m.compute(Tensor(outputs[0]),
                            *[Tensor(l) for l in labels])
            r = m.update(inp if not isinstance(inp, tuple) else inp[0])
            res.append(r)
        return res

    # -- loops --------------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data

    def _buffered(self, loader):
        """Wrap a DataLoader with the async DeviceFeeder double buffer
        (host->device transfer of batch N+1 overlaps batch N's compute)
        when the loader opted into buffering (`use_buffer_reader`).

        Under fleet the feeder gets the strategy's batch placement, so
        the background thread lays every batch directly into its
        dp/sp-sharded layout and the sharded step consumes it without a
        synchronous re-placement."""
        if isinstance(loader, DataLoader) and \
                getattr(loader, "use_buffer_reader", False):
            placement = None
            if self._dist_ctx is not None:
                try:
                    placement = self._dist_ctx.batch_placement()
                except Exception:
                    placement = None
            return DeviceFeeder(loader, device=placement)
        return loader

    def _token_masked(self, loader):
        """True when the loader's collator is a packing collator
        (io.packing.PackingCollator or anything with emits_token_mask):
        every batch's LAST leaf is a [rows, max_tokens] token validity
        mask that fit/evaluate pop off the labels and fold into the loss
        as a token-level mask. Packs are always full-shape — a short
        tail is just a pack with more masked tokens — so the row-mask
        tail machinery (_tail_target/_pad_tail) is bypassed entirely:
        one compiled step per epoch, and never BOTH masks on one batch.

        The model must be constructed with explicit `inputs=` specs so
        _split_batch knows how many leading pack leaves (tokens,
        segment_ids, position_ids, ...) feed the network."""
        cf = getattr(loader, "collate_fn", None)
        return bool(getattr(cf, "emits_token_mask", False))

    def _pop_token_mask(self, lbs):
        """Split the collator-emitted token mask off the label leaves.
        The mask stays whatever the feeder made it (host numpy or an
        already-placed device array) — never forced through the host
        here."""
        if not lbs:
            raise ValueError(
                "packing collator batches must carry at least the token "
                "mask after the input leaves — construct the Model with "
                "inputs= specs matching the pack layout")
        tm = lbs[-1]
        return lbs[:-1], (tm._value if isinstance(tm, Tensor) else tm)

    def _tail_target(self, loader, need_mask=True):
        """The loader's batch size when its epochs can actually produce a
        partial tail batch (unknown-length loaders count as "can"), else
        None. Gating on this keeps datasets that only ever emit full
        batches on the exact maskless step they always had — the masked
        reduction is numerically identical for row-uniform losses but
        weights rows (not valid elements) for losses with data-dependent
        denominators like cross_entropy ignore_index, so it must not be
        paid where it buys nothing. `need_mask=False` (predict: no loss,
        rows just sliced off the output) pads even when the prepared
        loss refused the mask."""
        if not flag("FLAGS_train_tail_bucketing"):
            return None
        if need_mask and not getattr(self, "_tail_maskable", True):
            return None
        bs = getattr(loader, "batch_size", None)
        if not bs:
            return None
        sampler = getattr(loader, "batch_sampler", None)
        if getattr(sampler, "drop_last", False):
            return None  # the sampler already drops the tail
        ds = getattr(loader, "dataset", None)
        if ds is not None and sampler is not None:
            try:
                if len(ds) % bs == 0:
                    return None  # every batch is full
            except TypeError:
                pass  # unsized dataset: a tail is possible
        return bs

    def _pad_tail(self, ins, lbs, target):
        """Tail bucketing: grow a partial batch to `target` rows and
        return (ins, lbs, row_mask, nreal). Full batches pass through
        with an all-ones mask (same jit signature -> same executable as
        the padded tail: exactly one train-step compile per epoch)."""
        rows = _batch_rows(ins + lbs)
        if rows is None:
            return ins, lbs, None, None
        if rows >= target:
            return ins, lbs, np.ones((rows,), "float32"), rows
        mask = np.zeros((target,), "float32")
        mask[:rows] = 1.0
        ins = [_pad_leaf(x, rows, target) for x in ins]
        lbs = [_pad_leaf(x, rows, target) for x in lbs]
        STAT_ADD("STAT_tail_pad_batches")
        return ins, lbs, mask, rows

    def _split_batch(self, batch):
        data = _flatten_batch(batch)
        n_in = len(self._inputs) if self._inputs else 1
        if len(data) == 1:
            return data, []
        return data[:n_in], data[n_in:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        assert self._optimizer is not None, "call prepare() first"
        loader = self._as_loader(train_data, batch_size, shuffle, num_workers,
                                 drop_last)
        eval_loader = self._as_loader(eval_data, batch_size, False,
                                      num_workers, False)
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs,
            steps=_steps_of(loader),
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose,
            metrics=["loss"] + [n for m in self._metrics
                                for n in (m.name() if isinstance(m.name(),
                                                                 list)
                                          else [m.name()])])
        cbks.on_begin("train")
        self.stop_training = False
        step_count = 0
        logs = {}  # stays bound for on_end even with epochs=0
        feed = self._buffered(loader)
        self._in_fit = True  # keep the carry live; write back at epoch ends
        flight_recorder.touch()  # periodic counter snapshots while training
        device_telemetry.touch()  # HBM/compile/MFU gauges while training
        try:
            for epoch in range(epochs):
                if hasattr(loader, "batch_sampler") and hasattr(
                        loader.batch_sampler, "set_epoch"):
                    loader.batch_sampler.set_epoch(epoch)
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                # tail bucketing: pad the drop_last=False partial batch
                # to the loader's batch size and fold a row mask into the
                # loss, so every batch of the epoch shares ONE compiled
                # step (the mask rides the signature even on full
                # batches; epochs that cannot produce a tail skip the
                # mask entirely and keep the plain step). A packing
                # collator replaces all of this with its own token mask:
                # packs are already fixed-shape, so the tail machinery
                # must stay OFF (no row padding, no double-masking).
                token_masked = self._token_masked(loader)
                pad_to = None if token_masked else self._tail_target(loader)
                for step, batch in enumerate(feed):
                    cbks.on_batch_begin("train", step, logs)
                    ins, lbs = self._split_batch(batch)
                    mask, nreal = None, None
                    if token_masked:
                        lbs, mask = self._pop_token_mask(lbs)
                    elif pad_to and self._tail_maskable:
                        # _tail_maskable re-checked per batch: a
                        # mid-epoch fallback stops the masked attempts
                        ins, lbs, mask, nreal = self._pad_tail(
                            ins, lbs, pad_to)
                    padded = not token_masked and mask is not None and \
                        nreal is not None and nreal < len(mask)
                    c0 = (stat_get("STAT_train_step_compiles") if padded
                          else 0)
                    # the fit loop's own track in the chrome trace: step
                    # scopes on the main thread next to the feeder/lane
                    # threads (dispatch wall time; device time is in the
                    # jax.profiler trace)
                    with RecordEvent("fit::train_step"):
                        loss, metrics = self.train_batch(ins, lbs,
                                                         loss_mask=mask)
                    if padded and self._dist_ctx is None and \
                            stat_get("STAT_train_step_compiles") == c0:
                        # the padded tail rode an executable some full
                        # batch already compiled — the win this is for.
                        # (single-device only: pjit compiles are not
                        # observable through this counter, so the fleet
                        # path makes no claim here)
                        STAT_ADD("STAT_tail_pad_compiles_avoided")
                    lv = loss[0] if isinstance(loss, (list, tuple)) else loss
                    # deferred host sync: the loss stays a device handle
                    # except on the log cadence (one sync per log_freq)
                    if log_freq and step % log_freq == 0 and \
                            isinstance(lv, DeferredScalar):
                        lv = float(lv)
                    logs = {"loss": lv, "step": step, "batch_size":
                            nreal if nreal is not None else
                            (ins[0].shape[0] if hasattr(ins[0], "shape")
                             else batch_size)}
                    for m, r in zip(self._metrics, metrics):
                        names = m.name() if isinstance(m.name(), list) else \
                            [m.name()]
                        vals = r if isinstance(r, list) else [r]
                        for n, v in zip(names, vals):
                            logs[n] = v
                    cbks.on_batch_end("train", step, logs)
                    step_count += 1
                    if num_iters is not None and step_count >= num_iters:
                        self.stop_training = True
                        break
                # epoch boundary: params/opt state back into Tensors, loss
                # to a host float (callbacks may checkpoint / early-stop).
                # validate: an async step failure from the un-synced tail
                # of the epoch must not be written back as poisoned arrays
                self._sync_carry(validate=True)
                if isinstance(logs.get("loss"), DeferredScalar):
                    logs["loss"] = float(logs["loss"])
                # epoch-level metric accumulation
                for m in self._metrics:
                    names = m.name() if isinstance(m.name(), list) else \
                        [m.name()]
                    vals = m.accumulate()
                    vals = vals if isinstance(vals, list) else [vals]
                    for n, v in zip(names, vals):
                        logs[n] = v
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_loader, batch_size=batch_size,
                                  verbose=0, num_workers=num_workers,
                                  callbacks=None)
                if self.stop_training:
                    break
        except BaseException:
            # an async device failure surfaces at a deferred float() sync
            # or in a callback, AFTER train_batch installed the (possibly
            # poisoned) output carry — validate before write-back so the
            # network keeps its last synced values instead of arrays that
            # re-raise the XLA error on every read
            self._in_fit = False
            self._sync_carry(validate=True)
            try:
                # on_end still fires: VisualDL flushes its buffered
                # scalars; ModelCheckpoint's "final" save succeeds when
                # the carry survived (or donation is off) and fails
                # loudly-but-contained when donated state was consumed
                cbks.on_end("train", logs)
            except Exception:
                pass  # never mask the original error
            raise
        self._in_fit = False
        self._sync_carry()
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._as_loader(eval_data, batch_size, False, num_workers,
                                 False)
        for m in self._metrics:
            m.reset()
        losses = []
        weights = []
        token_masked = self._token_masked(loader)
        pad_to = None if token_masked else self._tail_target(loader)
        for batch in self._buffered(loader):
            ins, lbs = self._split_batch(batch)
            mask = None
            if token_masked:
                lbs, mask = self._pop_token_mask(lbs)
                # each pack's loss is already real-token-normalized;
                # weight packs by their real-token count so the pass
                # mean is the TRUE per-token mean over the dataset (a
                # near-empty tail pack must not count like a full one).
                # A device-resident mask's count stays a deferred
                # handle — it rides the same single stacked transfer
                # as the losses below instead of a per-batch sync
                mv = mask._value if isinstance(mask, Tensor) else mask
                weights.append(DeferredScalar(jnp.sum(mv))
                               if isinstance(mv, jax.Array)
                               else float(np.asarray(mv).sum()))
            elif pad_to and self._tail_maskable:
                ins, lbs, mask, _ = self._pad_tail(ins, lbs, pad_to)
            lv, _ = self.eval_batch(ins, lbs, loss_mask=mask)
            losses.append(lv)
        # one device->host sync for the whole pass: every per-batch handle
        # rides a single stacked transfer (framework.deferred)
        vals = materialize_many(losses + weights)
        vals, weights = vals[:len(losses)], vals[len(losses):]
        if token_masked and vals and sum(weights) > 0:
            logs = {"loss": float(np.average(vals, weights=weights))}
        else:
            logs = {"loss": float(np.mean(vals)) if vals else 0.0}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            for n, v in zip(names, vals):
                logs[n] = v
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, num_workers,
                                 False)
        outputs = []
        # packing collators emit fixed-shape packs whose row count is
        # unrelated to the loader's sequences-per-pack batch_size — row
        # padding would corrupt them (and is never needed)
        pad_to = None if self._token_masked(loader) else \
            self._tail_target(loader, need_mask=False)
        for batch in self._buffered(loader):
            ins, _ = self._split_batch(batch)
            nreal = None
            if pad_to:
                rows = _batch_rows(ins)
                if rows is not None and rows < pad_to:
                    ins = [_pad_leaf(x, rows, pad_to) for x in ins]
                    nreal = rows
                    STAT_ADD("STAT_tail_pad_batches")
            outputs.append(self.predict_batch(ins, nreal=nreal))
        if stack_outputs and outputs:
            if isinstance(outputs[0], (list, tuple)):
                outputs = [np.concatenate([o[i] for o in outputs])
                           for i in range(len(outputs[0]))]
            else:
                outputs = np.concatenate(outputs)
        return outputs

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io_state import save as psave
        self._sync_carry()
        if training:
            psave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                opt_state = {"global_step": getattr(self, "_global_step", 0)}
                if self._opt_state is not None:
                    opt_state["state"] = jax.tree_util.tree_map(
                        lambda x: np.asarray(x), self._opt_state)
                psave(opt_state, path + ".pdopt")
        else:
            from .. import jit as pjit
            specs = self._inputs
            pjit.save(self.network, path, input_spec=specs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io_state import load as pload
        self._train_carry = None  # loaded values supersede any live carry
        # the sharded step closed over the pre-load param placements;
        # rebuild it (and its state) from the freshly loaded Tensors
        self._sharded_state = None
        self._sharded_dirty = False
        if hasattr(self, "_sharded_step"):
            del self._sharded_step
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and os.path.exists(opt_path):
            opt_state = pload(opt_path)
            self._global_step = opt_state.get("global_step", 0)
            # no "state" key (checkpoint saved before any step) must still
            # drop the previous run's moments, not keep them
            self._opt_state = (jax.tree_util.tree_map(
                lambda x: jnp.asarray(x), opt_state["state"])
                if "state" in opt_state else None)
        else:
            # actually reset: otherwise _ensure_carry would resume with the
            # previous run's optimizer moments against the loaded weights
            self._opt_state = None
            self._global_step = 0
        return self

    def parameters(self, *args, **kwargs):
        self._sync_carry()  # expose fresh values, not donated buffers
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        self._sync_carry()  # summary forwards through Tensor._value
        return summary(self.network, input_size, dtype)
