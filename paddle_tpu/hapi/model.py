"""High-level Model API (reference `python/paddle/hapi/model.py:810`:
Model.fit:1299 / evaluate / predict / save:1043, dual Static/Dynamic
adapters :224/:609).

TPU-native: ONE adapter — the functional train step. prepare() captures
the network functionally; fit() drives a jax.jit-compiled
carry -> carry step — forward, backward and the optimizer update fused
into a single XLA program per input signature (what the reference needs
CompiledProgram + ParallelExecutor for). When fleet is initialized the
same step is pjit'ed over the device mesh (see distributed/fleet).

Training hot-loop contract (the zero-copy / async-dispatch design):

* The whole model state — (params, buffers, opt_state) — travels as ONE
  donated carry pytree: `jax.jit(step, donate_argnums=(0,))`. XLA updates
  parameters in place; no second copy of the model state is allocated per
  step (mirrors parallel/spmd.py and parallel/pipeline.py donation).
  `FLAGS_train_step_donate=0` turns donation off for A/B checks.
* While a fit() epoch is running, `Tensor._value` on the network is STALE
  (the donated buffers are consumed). The carry is written back by
  `_sync_carry()` on epoch boundaries, save(), load(), parameters(),
  summary() — eval/predict read the live carry directly without a flush.
  Standalone train_batch calls (custom loops, outside fit) write back
  every call, preserving the public contract that direct Layer reads —
  net(x), state_dict() — stay fresh.
* `train_batch` returns a device-resident DeferredScalar loss; fit() only
  forces host floats every `log_freq` steps, so the Python loop runs ahead
  of the accelerator (async dispatch) instead of blocking every batch.
  CAVEAT: prepared Metrics update on host (`_update_metrics` pulls the
  step outputs with np.asarray), so a model with metrics still syncs once
  per batch — the deferred-sync win currently applies to metric-less
  training; moving metric accumulation into the jitted step is the
  follow-up that lifts this.
* Input batches are staged onto the device one step ahead by
  io.DeviceFeeder (double buffer) when the DataLoader has
  `use_buffer_reader=True` (the default).

Monitor counters (framework/monitor.py): STAT_train_steps,
STAT_train_step_compiles (one per input-shape key), STAT_train_step_ns
(dispatch wall time), STAT_train_host_syncs (DeferredScalar
materializations).
"""
from __future__ import annotations

import os
import pickle
import warnings
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as frandom
from ..framework.deferred import DeferredScalar, materialize_many
from ..framework.flags import flag
from ..framework.functional import functionalize, get_buffers, get_params
from ..framework.monitor import STAT_ADD, stat_time
from ..framework.tensor import Tensor
from ..io import DataLoader, Dataset
from ..io.device_loader import DeviceFeeder
from ..metric import Metric
from . import callbacks as cbks_mod

__all__ = ["Model"]


def _flatten_batch(data):
    if isinstance(data, dict):
        return list(data.values())
    if isinstance(data, (list, tuple)):
        return list(data)
    return [data]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._amp_level = None
        self._apply_fn = None
        self._opt_state = None
        self._train_carry = None  # donated {params,buffers,opt_state} pytree
        self._in_fit = False  # fit() defers carry write-back to epoch ends
        self._train_step_cache = {}
        self._eval_step_cache = {}
        self._pred_step_cache = {}
        self.stop_training = False
        self._dist_ctx = None  # set by fleet.distributed_model

    # -- preparation --------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        if amp_configs is not None:
            self._amp_level = (amp_configs if isinstance(amp_configs, str)
                               else amp_configs.get("level", "O1"))
        self._apply_fn, _, _ = functionalize(self.network)
        if optimizer is not None and getattr(
                optimizer, "_parameter_list", None) is None:
            optimizer._parameter_list = self.network.parameters()
        # fleet-distributed: route training through the SPMD sharded step
        # (reference `hapi/model.py:165` prepare_distributed_context)
        try:
            from ..distributed.fleet import fleet as _fleet
            from ..parallel.mesh import get_mesh
            mesh = get_mesh()
            if _fleet._inited and mesh is not None and \
                    mesh.devices.size > 1:
                self._dist_ctx = _fleet
        except Exception:
            self._dist_ctx = None
        return self

    # -- internals ----------------------------------------------------------
    def _loss_value(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        if self._loss is None:
            # network returns the loss directly
            v = outs[0]
            return v
        if callable(self._loss):
            return self._loss(*outs, *labels)
        raise TypeError("loss must be callable")

    def _make_train_step(self):
        apply_fn = self._apply_fn
        opt = self._optimizer
        amp_level = self._amp_level

        def loss_fn(pv, bv, rng, inputs, labels):
            def fwd():
                wrapped_in = [Tensor(x) for x in inputs]
                wrapped_lb = [Tensor(x) for x in labels]
                out, new_bufs = apply_fn(pv, bv, rng, True,
                                         *[w._value for w in wrapped_in])
                wout = jax.tree_util.tree_map(
                    lambda x: Tensor(x), out)
                lv = self._loss_value(wout, wrapped_lb)
                return lv, (out, new_bufs)
            if amp_level:
                from .. import amp as amp_mod
                from ..framework.autograd import trace_mode
                with trace_mode(), amp_mod.auto_cast(level=amp_level):
                    lv, aux = fwd()
            else:
                from ..framework.autograd import trace_mode
                with trace_mode():
                    lv, aux = fwd()
            lv_raw = lv._value if isinstance(lv, Tensor) else lv
            return jnp.mean(lv_raw.astype("float32")), aux

        def step(carry, rng, step_no, lr, inputs, labels):
            pv, bv, opt_state = (carry["params"], carry["buffers"],
                                 carry["opt_state"])
            (lv, (out, new_bufs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(pv, bv, rng, inputs, labels)
            new_pv, new_state = opt.apply_gradients_pytree(
                grads, pv, opt_state, lr, step_no)
            return {"params": new_pv, "buffers": new_bufs,
                    "opt_state": new_state}, lv, out
        return step

    # -- carry management ----------------------------------------------------
    def _ensure_carry(self):
        """Device-resident {params, buffers, opt_state} pytree that the
        donated train step consumes and reproduces each step."""
        if self._train_carry is None:
            pv = {n: t._value
                  for n, t in get_params(self.network).items()}
            bv = {n: t._value
                  for n, t in get_buffers(self.network).items()}
            if self._opt_state is None:
                self._opt_state = self._optimizer.init_state_pytree(pv)
            self._train_carry = {"params": pv, "buffers": bv,
                                 "opt_state": self._opt_state}
        return self._train_carry

    def _sync_carry(self, validate=False):
        """Write the training carry back into the network's Tensors.

        Called on epoch boundaries, save(), load() and parameters() —
        NOT per step. After the first donated step of an epoch the
        Tensors' old buffers are consumed; anything that reads
        `Tensor._value` directly mid-epoch must flush through here first.

        `validate=True` (epoch boundaries and fit's error path) blocks
        until the carry is ready and DROPS it if the device computation
        failed: with async dispatch a step's XLA error surfaces at a
        later host sync, after the poisoned output carry was already
        installed — writing it back would leave the network's Tensors
        re-raising the XLA error on every read. NOTE: with donation
        active the Tensors' pre-epoch buffers were consumed by the first
        step, so after a drop the model state is NOT recoverable from the
        live network (reads raise "Array has been deleted") — recovery is
        via ModelCheckpoint epoch saves, which flush to host files. With
        FLAGS_train_step_donate=0 the Tensors keep valid pre-carry values.
        """
        carry = self._train_carry
        if carry is None:
            return
        if validate:
            try:
                jax.block_until_ready(jax.tree_util.tree_leaves(carry))
            except Exception:
                # device-side failure only (XLA runtime errors are
                # Exception subclasses): drop the poisoned carry.
                # KeyboardInterrupt/SystemExit propagate with the carry
                # kept installed — it is healthy, and a later
                # _sync_carry() still writes it back.
                self._train_carry = None
                self._opt_state = None  # rode the same poisoned step
                return
        for n, t in get_params(self.network).items():
            t._value = carry["params"][n]
        for n, t in get_buffers(self.network).items():
            t._value = carry["buffers"][n]
        self._opt_state = carry["opt_state"]
        self._train_carry = None

    def _current_values(self):
        """(params, buffers) value dicts for eval/predict: the live carry
        when training is in flight (no flush — eval doesn't donate), else
        the network's Tensors."""
        carry = self._train_carry
        if carry is not None:
            return carry["params"], carry["buffers"]
        return ({n: t._value for n, t in get_params(self.network).items()},
                {n: t._value for n, t in get_buffers(self.network).items()})

    def train_batch(self, inputs, labels=None, update=True):
        if self._dist_ctx is not None:
            return self._train_batch_sharded(inputs, labels)
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        labels = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(labels or [])]
        carry = self._ensure_carry()
        donate = bool(flag("FLAGS_train_step_donate"))
        key = (donate,
               tuple((tuple(a.shape), str(a.dtype)) for a in inputs),
               tuple((tuple(a.shape), str(a.dtype)) for a in labels))
        fn = self._train_step_cache.get(key)
        if fn is None:
            fn = jax.jit(self._make_train_step(),
                         donate_argnums=(0,) if donate else ())
            self._train_step_cache[key] = fn
            STAT_ADD("STAT_train_step_compiles")
        rng = frandom.get_rng_key()
        step_no = getattr(self, "_global_step", 0) + 1
        self._global_step = step_no
        try:
            with stat_time("STAT_train_step_ns"):
                new_carry, lv, out = fn(
                    carry, rng, jnp.asarray(step_no, "int32"),
                    jnp.asarray(self._optimizer.get_lr(), "float32"),
                    tuple(inputs), tuple(labels))
        except BaseException:
            # a step that died mid-call may have consumed the donated
            # carry (XLA error after dispatch). Keep the carry when its
            # buffers are intact (trace-time error, Ctrl-C before
            # dispatch, donation inactive) — that preserves the last
            # completed step — but drop it once consumed so the
            # epoch-boundary _sync_carry never writes deleted buffers
            # back into the network's Tensors.
            if any(getattr(leaf, "is_deleted", lambda: False)()
                   for leaf in jax.tree_util.tree_leaves(carry)):
                self._train_carry = None
                self._opt_state = None  # its arrays rode the same donation
            raise
        self._train_carry = new_carry
        STAT_ADD("STAT_train_steps")
        if not self._in_fit:
            # public custom-loop contract: a standalone train_batch call
            # writes updated params back to the network's Tensors (cheap
            # reference stores), so direct Layer reads — net(x),
            # state_dict() — stay valid. Only fit() keeps the carry live
            # across steps.
            self._sync_carry()
        outs = jax.tree_util.tree_leaves(out)
        metrics = self._update_metrics(outs, labels)
        loss = DeferredScalar(lv)
        return (loss, metrics) if self._metrics else ([loss], metrics)

    def _train_batch_sharded(self, inputs, labels):
        """fleet path: one pjit'ed step over the mesh (dp/tp/zero per
        strategy); params written back so eval/save see fresh values."""
        import jax
        from ..parallel.spmd import write_back
        if not hasattr(self, "_sharded_step"):
            def loss_fn(outs, lbs):
                out = outs[0] if isinstance(outs, (list, tuple)) else outs
                return self._loss_value(out, lbs)
            self._sharded_step, self._sharded_state = \
                self._dist_ctx.build_sharded_train_step(
                    self.network, self._optimizer, loss_fn)
        ins = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
               for t in _flatten_batch(inputs)]
        lbs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
               for t in _flatten_batch(labels or [])]
        self._sharded_state, lv = self._sharded_step(
            self._sharded_state, tuple(ins), tuple(lbs))
        write_back(self.network, self._sharded_state)
        loss = DeferredScalar(lv)
        return (loss, []) if self._metrics else ([loss], [])

    def eval_batch(self, inputs, labels=None):
        pv, bv = self._current_values()
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        labels = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(labels or [])]
        key = tuple((tuple(a.shape), str(a.dtype)) for a in inputs + labels)
        fn = self._eval_step_cache.get(key)
        if fn is None:
            apply_fn = self._apply_fn

            def estep(pv_, bv_, rng, ins, lbs):
                from ..framework.autograd import trace_mode
                out, _ = apply_fn(pv_, bv_, rng, False, *ins)
                with trace_mode():
                    wout = jax.tree_util.tree_map(lambda x: Tensor(x), out)
                    lv = self._loss_value(wout, [Tensor(x) for x in lbs]) \
                        if (self._loss is not None and lbs) else None
                lv_raw = (jnp.mean(lv._value.astype("float32"))
                          if isinstance(lv, Tensor) else
                          (lv if lv is not None else jnp.zeros(())))
                return lv_raw, out
            fn = jax.jit(estep)
            self._eval_step_cache[key] = fn
        rng = frandom.get_rng_key()
        lv, out = fn(pv, bv, rng, tuple(inputs), tuple(labels))
        outs = jax.tree_util.tree_leaves(out)
        metrics = self._update_metrics(outs, labels)
        return DeferredScalar(lv), metrics

    def predict_batch(self, inputs):
        pv, bv = self._current_values()
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        key = tuple((tuple(a.shape), str(a.dtype)) for a in inputs)
        fn = self._pred_step_cache.get(key)
        if fn is None:
            apply_fn = self._apply_fn
            fn = jax.jit(lambda pv_, bv_, rng, ins: apply_fn(
                pv_, bv_, rng, False, *ins)[0])
            self._pred_step_cache[key] = fn
        out = fn(pv, bv, frandom.get_rng_key(), tuple(inputs))
        return jax.tree_util.tree_map(lambda x: np.asarray(x), out)

    def _update_metrics(self, outputs, labels):
        res = []
        for m in self._metrics:
            inp = m.compute(Tensor(outputs[0]),
                            *[Tensor(l) for l in labels])
            r = m.update(inp if not isinstance(inp, tuple) else inp[0])
            res.append(r)
        return res

    # -- loops --------------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data

    def _buffered(self, loader):
        """Wrap a DataLoader with the async DeviceFeeder double buffer
        (host->device transfer of batch N+1 overlaps batch N's compute)
        when the loader opted into buffering (`use_buffer_reader`)."""
        if isinstance(loader, DataLoader) and \
                getattr(loader, "use_buffer_reader", False):
            return DeviceFeeder(loader)
        return loader

    def _split_batch(self, batch):
        data = _flatten_batch(batch)
        n_in = len(self._inputs) if self._inputs else 1
        if len(data) == 1:
            return data, []
        return data[:n_in], data[n_in:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        assert self._optimizer is not None, "call prepare() first"
        loader = self._as_loader(train_data, batch_size, shuffle, num_workers,
                                 drop_last)
        eval_loader = self._as_loader(eval_data, batch_size, False,
                                      num_workers, False)
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs,
            steps=len(loader) if hasattr(loader, "__len__") else None,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose,
            metrics=["loss"] + [n for m in self._metrics
                                for n in (m.name() if isinstance(m.name(),
                                                                 list)
                                          else [m.name()])])
        cbks.on_begin("train")
        self.stop_training = False
        step_count = 0
        logs = {}  # stays bound for on_end even with epochs=0
        feed = self._buffered(loader)
        self._in_fit = True  # keep the carry live; write back at epoch ends
        try:
            for epoch in range(epochs):
                if hasattr(loader, "batch_sampler") and hasattr(
                        loader.batch_sampler, "set_epoch"):
                    loader.batch_sampler.set_epoch(epoch)
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                for step, batch in enumerate(feed):
                    cbks.on_batch_begin("train", step, logs)
                    ins, lbs = self._split_batch(batch)
                    loss, metrics = self.train_batch(ins, lbs)
                    lv = loss[0] if isinstance(loss, (list, tuple)) else loss
                    # deferred host sync: the loss stays a device handle
                    # except on the log cadence (one sync per log_freq)
                    if log_freq and step % log_freq == 0 and \
                            isinstance(lv, DeferredScalar):
                        lv = float(lv)
                    logs = {"loss": lv, "step": step, "batch_size":
                            ins[0].shape[0] if hasattr(ins[0], "shape") else
                            batch_size}
                    for m, r in zip(self._metrics, metrics):
                        names = m.name() if isinstance(m.name(), list) else \
                            [m.name()]
                        vals = r if isinstance(r, list) else [r]
                        for n, v in zip(names, vals):
                            logs[n] = v
                    cbks.on_batch_end("train", step, logs)
                    step_count += 1
                    if num_iters is not None and step_count >= num_iters:
                        self.stop_training = True
                        break
                # epoch boundary: params/opt state back into Tensors, loss
                # to a host float (callbacks may checkpoint / early-stop).
                # validate: an async step failure from the un-synced tail
                # of the epoch must not be written back as poisoned arrays
                self._sync_carry(validate=True)
                if isinstance(logs.get("loss"), DeferredScalar):
                    logs["loss"] = float(logs["loss"])
                # epoch-level metric accumulation
                for m in self._metrics:
                    names = m.name() if isinstance(m.name(), list) else \
                        [m.name()]
                    vals = m.accumulate()
                    vals = vals if isinstance(vals, list) else [vals]
                    for n, v in zip(names, vals):
                        logs[n] = v
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                    self.evaluate(eval_loader, batch_size=batch_size,
                                  verbose=0, num_workers=num_workers,
                                  callbacks=None)
                if self.stop_training:
                    break
        except BaseException:
            # an async device failure surfaces at a deferred float() sync
            # or in a callback, AFTER train_batch installed the (possibly
            # poisoned) output carry — validate before write-back so the
            # network keeps its last synced values instead of arrays that
            # re-raise the XLA error on every read
            self._in_fit = False
            self._sync_carry(validate=True)
            try:
                # on_end still fires: VisualDL flushes its buffered
                # scalars; ModelCheckpoint's "final" save succeeds when
                # the carry survived (or donation is off) and fails
                # loudly-but-contained when donated state was consumed
                cbks.on_end("train", logs)
            except Exception:
                pass  # never mask the original error
            raise
        self._in_fit = False
        self._sync_carry()
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._as_loader(eval_data, batch_size, False, num_workers,
                                 False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in self._buffered(loader):
            ins, lbs = self._split_batch(batch)
            lv, _ = self.eval_batch(ins, lbs)
            losses.append(lv)
        # one device->host sync for the whole pass: every per-batch handle
        # rides a single stacked transfer (framework.deferred)
        vals = materialize_many(losses)
        logs = {"loss": float(np.mean(vals)) if vals else 0.0}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            for n, v in zip(names, vals):
                logs[n] = v
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, num_workers,
                                 False)
        outputs = []
        for batch in self._buffered(loader):
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            if isinstance(outputs[0], (list, tuple)):
                outputs = [np.concatenate([o[i] for o in outputs])
                           for i in range(len(outputs[0]))]
            else:
                outputs = np.concatenate(outputs)
        return outputs

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io_state import save as psave
        self._sync_carry()
        if training:
            psave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                opt_state = {"global_step": getattr(self, "_global_step", 0)}
                if self._opt_state is not None:
                    opt_state["state"] = jax.tree_util.tree_map(
                        lambda x: np.asarray(x), self._opt_state)
                psave(opt_state, path + ".pdopt")
        else:
            from .. import jit as pjit
            specs = self._inputs
            pjit.save(self.network, path, input_spec=specs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io_state import load as pload
        self._train_carry = None  # loaded values supersede any live carry
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and os.path.exists(opt_path):
            opt_state = pload(opt_path)
            self._global_step = opt_state.get("global_step", 0)
            # no "state" key (checkpoint saved before any step) must still
            # drop the previous run's moments, not keep them
            self._opt_state = (jax.tree_util.tree_map(
                lambda x: jnp.asarray(x), opt_state["state"])
                if "state" in opt_state else None)
        else:
            # actually reset: otherwise _ensure_carry would resume with the
            # previous run's optimizer moments against the loaded weights
            self._opt_state = None
            self._global_step = 0
        return self

    def parameters(self, *args, **kwargs):
        self._sync_carry()  # expose fresh values, not donated buffers
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        self._sync_carry()  # summary forwards through Tensor._value
        return summary(self.network, input_size, dtype)
