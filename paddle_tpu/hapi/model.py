"""High-level Model API (reference `python/paddle/hapi/model.py:810`:
Model.fit:1299 / evaluate / predict / save:1043, dual Static/Dynamic
adapters :224/:609).

TPU-native: ONE adapter — the functional train step. prepare() captures
the network functionally; fit() drives a jax.jit-compiled
(params, opt_state, batch) -> (loss, outputs, new_params, new_opt_state)
step — forward, backward and the optimizer update fused into a single XLA
program per input signature (what the reference needs CompiledProgram +
ParallelExecutor for). When fleet is initialized the same step is pjit'ed
over the device mesh (see distributed/fleet).
"""
from __future__ import annotations

import os
import pickle
import warnings
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as frandom
from ..framework.functional import functionalize, get_buffers, get_params
from ..framework.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from . import callbacks as cbks_mod

__all__ = ["Model"]


def _flatten_batch(data):
    if isinstance(data, dict):
        return list(data.values())
    if isinstance(data, (list, tuple)):
        return list(data)
    return [data]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._amp_level = None
        self._apply_fn = None
        self._opt_state = None
        self._train_step_cache = {}
        self._eval_step_cache = {}
        self._pred_step_cache = {}
        self.stop_training = False
        self._dist_ctx = None  # set by fleet.distributed_model

    # -- preparation --------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        if amp_configs is not None:
            self._amp_level = (amp_configs if isinstance(amp_configs, str)
                               else amp_configs.get("level", "O1"))
        self._apply_fn, _, _ = functionalize(self.network)
        if optimizer is not None and getattr(
                optimizer, "_parameter_list", None) is None:
            optimizer._parameter_list = self.network.parameters()
        # fleet-distributed: route training through the SPMD sharded step
        # (reference `hapi/model.py:165` prepare_distributed_context)
        try:
            from ..distributed.fleet import fleet as _fleet
            from ..parallel.mesh import get_mesh
            mesh = get_mesh()
            if _fleet._inited and mesh is not None and \
                    mesh.devices.size > 1:
                self._dist_ctx = _fleet
        except Exception:
            self._dist_ctx = None
        return self

    # -- internals ----------------------------------------------------------
    def _loss_value(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        if self._loss is None:
            # network returns the loss directly
            v = outs[0]
            return v
        if callable(self._loss):
            return self._loss(*outs, *labels)
        raise TypeError("loss must be callable")

    def _make_train_step(self):
        apply_fn = self._apply_fn
        opt = self._optimizer
        amp_level = self._amp_level

        def loss_fn(pv, bv, rng, inputs, labels):
            def fwd():
                wrapped_in = [Tensor(x) for x in inputs]
                wrapped_lb = [Tensor(x) for x in labels]
                out, new_bufs = apply_fn(pv, bv, rng, True,
                                         *[w._value for w in wrapped_in])
                wout = jax.tree_util.tree_map(
                    lambda x: Tensor(x), out)
                lv = self._loss_value(wout, wrapped_lb)
                return lv, (out, new_bufs)
            if amp_level:
                from .. import amp as amp_mod
                from ..framework.autograd import trace_mode
                with trace_mode(), amp_mod.auto_cast(level=amp_level):
                    lv, aux = fwd()
            else:
                from ..framework.autograd import trace_mode
                with trace_mode():
                    lv, aux = fwd()
            lv_raw = lv._value if isinstance(lv, Tensor) else lv
            return jnp.mean(lv_raw.astype("float32")), aux

        def step(pv, bv, opt_state, rng, step_no, lr, inputs, labels):
            (lv, (out, new_bufs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(pv, bv, rng, inputs, labels)
            new_pv, new_state = opt.apply_gradients_pytree(
                grads, pv, opt_state, lr, step_no)
            return lv, out, new_bufs, new_pv, new_state
        return step

    def train_batch(self, inputs, labels=None, update=True):
        if self._dist_ctx is not None:
            return self._train_batch_sharded(inputs, labels)
        params = get_params(self.network)
        buffers = get_buffers(self.network)
        pv = {n: t._value for n, t in params.items()}
        bv = {n: t._value for n, t in buffers.items()}
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        labels = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(labels or [])]
        if self._opt_state is None:
            self._opt_state = {n: self._optimizer._init_state(v)
                               for n, v in pv.items()}
        key = (tuple((tuple(a.shape), str(a.dtype)) for a in inputs),
               tuple((tuple(a.shape), str(a.dtype)) for a in labels))
        fn = self._train_step_cache.get(key)
        if fn is None:
            fn = jax.jit(self._make_train_step())
            self._train_step_cache[key] = fn
        rng = frandom.get_rng_key()
        step_no = getattr(self, "_global_step", 0) + 1
        self._global_step = step_no
        lv, out, new_bufs, new_pv, new_state = fn(
            pv, bv, self._opt_state, rng,
            jnp.asarray(step_no, "int32"),
            jnp.asarray(self._optimizer.get_lr(), "float32"),
            tuple(inputs), tuple(labels))
        for n, t in params.items():
            t._value = new_pv[n]
        for n, t in buffers.items():
            t._value = new_bufs[n]
        self._opt_state = new_state
        outs = jax.tree_util.tree_leaves(out)
        metrics = self._update_metrics(outs, labels)
        return (float(lv), metrics) if self._metrics else ([float(lv)],
                                                           metrics)

    def _train_batch_sharded(self, inputs, labels):
        """fleet path: one pjit'ed step over the mesh (dp/tp/zero per
        strategy); params written back so eval/save see fresh values."""
        import jax
        from ..parallel.spmd import write_back
        if not hasattr(self, "_sharded_step"):
            def loss_fn(outs, lbs):
                out = outs[0] if isinstance(outs, (list, tuple)) else outs
                return self._loss_value(out, lbs)
            self._sharded_step, self._sharded_state = \
                self._dist_ctx.build_sharded_train_step(
                    self.network, self._optimizer, loss_fn)
        ins = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
               for t in _flatten_batch(inputs)]
        lbs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
               for t in _flatten_batch(labels or [])]
        self._sharded_state, lv = self._sharded_step(
            self._sharded_state, tuple(ins), tuple(lbs))
        write_back(self.network, self._sharded_state)
        outs = []  # sharded step doesn't return outputs; metrics use eval
        return float(lv), []

    def eval_batch(self, inputs, labels=None):
        params = get_params(self.network)
        buffers = get_buffers(self.network)
        pv = {n: t._value for n, t in params.items()}
        bv = {n: t._value for n, t in buffers.items()}
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        labels = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(labels or [])]
        key = tuple((tuple(a.shape), str(a.dtype)) for a in inputs + labels)
        fn = self._eval_step_cache.get(key)
        if fn is None:
            apply_fn = self._apply_fn

            def estep(pv_, bv_, rng, ins, lbs):
                from ..framework.autograd import trace_mode
                out, _ = apply_fn(pv_, bv_, rng, False, *ins)
                with trace_mode():
                    wout = jax.tree_util.tree_map(lambda x: Tensor(x), out)
                    lv = self._loss_value(wout, [Tensor(x) for x in lbs]) \
                        if (self._loss is not None and lbs) else None
                lv_raw = (jnp.mean(lv._value.astype("float32"))
                          if isinstance(lv, Tensor) else
                          (lv if lv is not None else jnp.zeros(())))
                return lv_raw, out
            fn = jax.jit(estep)
            self._eval_step_cache[key] = fn
        rng = frandom.get_rng_key()
        lv, out = fn(pv, bv, rng, tuple(inputs), tuple(labels))
        outs = jax.tree_util.tree_leaves(out)
        metrics = self._update_metrics(outs, labels)
        return float(lv), metrics

    def predict_batch(self, inputs):
        params = get_params(self.network)
        buffers = get_buffers(self.network)
        pv = {n: t._value for n, t in params.items()}
        bv = {n: t._value for n, t in buffers.items()}
        inputs = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                  for t in _flatten_batch(inputs)]
        key = tuple((tuple(a.shape), str(a.dtype)) for a in inputs)
        fn = self._pred_step_cache.get(key)
        if fn is None:
            apply_fn = self._apply_fn
            fn = jax.jit(lambda pv_, bv_, rng, ins: apply_fn(
                pv_, bv_, rng, False, *ins)[0])
            self._pred_step_cache[key] = fn
        out = fn(pv, bv, frandom.get_rng_key(), tuple(inputs))
        return jax.tree_util.tree_map(lambda x: np.asarray(x), out)

    def _update_metrics(self, outputs, labels):
        res = []
        for m in self._metrics:
            inp = m.compute(Tensor(outputs[0]),
                            *[Tensor(l) for l in labels])
            r = m.update(inp if not isinstance(inp, tuple) else inp[0])
            res.append(r)
        return res

    # -- loops --------------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data

    def _split_batch(self, batch):
        data = _flatten_batch(batch)
        n_in = len(self._inputs) if self._inputs else 1
        if len(data) == 1:
            return data, []
        return data[:n_in], data[n_in:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        assert self._optimizer is not None, "call prepare() first"
        loader = self._as_loader(train_data, batch_size, shuffle, num_workers,
                                 drop_last)
        eval_loader = self._as_loader(eval_data, batch_size, False,
                                      num_workers, False)
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs,
            steps=len(loader) if hasattr(loader, "__len__") else None,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose,
            metrics=["loss"] + [n for m in self._metrics
                                for n in (m.name() if isinstance(m.name(),
                                                                 list)
                                          else [m.name()])])
        cbks.on_begin("train")
        self.stop_training = False
        step_count = 0
        for epoch in range(epochs):
            if hasattr(loader, "batch_sampler") and hasattr(
                    loader.batch_sampler, "set_epoch"):
                loader.batch_sampler.set_epoch(epoch)
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_batch_begin("train", step, logs)
                ins, lbs = self._split_batch(batch)
                loss, metrics = self.train_batch(ins, lbs)
                logs = {"loss": loss if np.isscalar(loss) else loss[0],
                        "step": step, "batch_size":
                        ins[0].shape[0] if hasattr(ins[0], "shape") else
                        batch_size}
                for m, r in zip(self._metrics, metrics):
                    names = m.name() if isinstance(m.name(), list) else \
                        [m.name()]
                    vals = r if isinstance(r, list) else [r]
                    for n, v in zip(names, vals):
                        logs[n] = v
                cbks.on_batch_end("train", step, logs)
                step_count += 1
                if num_iters is not None and step_count >= num_iters:
                    self.stop_training = True
                    break
            # epoch-level metric accumulation
            for m in self._metrics:
                names = m.name() if isinstance(m.name(), list) else \
                    [m.name()]
                vals = m.accumulate()
                vals = vals if isinstance(vals, list) else [vals]
                for n, v in zip(names, vals):
                    logs[n] = v
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=0, num_workers=num_workers,
                              callbacks=None)
            if isinstance(self._optimizer._lr, object) and hasattr(
                    self._optimizer._lr, "step") and not np.isscalar(
                    self._optimizer._lr):
                pass
            if self.stop_training:
                break
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._as_loader(eval_data, batch_size, False, num_workers,
                                 False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            ins, lbs = self._split_batch(batch)
            lv, _ = self.eval_batch(ins, lbs)
            losses.append(lv)
        logs = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            for n, v in zip(names, vals):
                logs[n] = v
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, num_workers,
                                 False)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            if isinstance(outputs[0], (list, tuple)):
                outputs = [np.concatenate([o[i] for o in outputs])
                           for i in range(len(outputs[0]))]
            else:
                outputs = np.concatenate(outputs)
        return outputs

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io_state import save as psave
        if training:
            psave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                opt_state = {"global_step": getattr(self, "_global_step", 0)}
                if self._opt_state is not None:
                    opt_state["state"] = jax.tree_util.tree_map(
                        lambda x: np.asarray(x), self._opt_state)
                psave(opt_state, path + ".pdopt")
        else:
            from .. import jit as pjit
            specs = self._inputs
            pjit.save(self.network, path, input_spec=specs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io_state import load as pload
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and os.path.exists(opt_path):
            opt_state = pload(opt_path)
            self._global_step = opt_state.get("global_step", 0)
            if "state" in opt_state:
                self._opt_state = jax.tree_util.tree_map(
                    lambda x: jnp.asarray(x), opt_state["state"])
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        return summary(self.network, input_size, dtype)
