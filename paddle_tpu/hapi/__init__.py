from . import callbacks, model_summary
from .model import Model
from .model_summary import summary
