"""Model summary (reference `python/paddle/hapi/model_summary.py`)."""
from __future__ import annotations

import numpy as np

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    lines = ["-" * (width + 30),
             f"{'Param':<{width}}{'Shape':<20}{'Count':>8}",
             "-" * (width + 30)]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<20}{n:>8}")
    lines += ["-" * (width + 30),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}",
              "-" * (width + 30)]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
