"""Model summary (reference `python/paddle/hapi/model_summary.py`)."""
from __future__ import annotations

import numpy as np

__all__ = ["summary", "flops"]


def flops(net, input_size, custom_ops=None, print_detail=False):
    """FLOPs estimate per layer type (reference `hapi/dynamic_flops.py`)."""
    total = [0]
    hooks = []

    def conv_hook(layer, inputs, output):
        x = inputs[0]
        k = int(np.prod(layer._kernel_size))
        cin = layer._in_channels // layer._groups
        out_elems = int(np.prod(output.shape))
        total[0] += 2 * out_elems * cin * k

    def linear_hook(layer, inputs, output):
        total[0] += 2 * int(np.prod(output.shape)) * layer._in_features

    for layer in net.sublayers(include_self=True):
        tn = type(layer).__name__
        if tn in ("Conv2D", "Conv1D", "Conv3D"):
            hooks.append(layer.register_forward_post_hook(conv_hook))
        elif tn == "Linear":
            hooks.append(layer.register_forward_post_hook(linear_hook))
        elif custom_ops and tn in custom_ops:
            fn = custom_ops[tn]
            hooks.append(layer.register_forward_post_hook(
                lambda l, i, o, fn=fn: total.__setitem__(
                    0, total[0] + fn(l, i, o))))
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    x = Tensor(jnp.zeros(tuple(input_size), "float32"))
    was_training = net.training
    net.eval()
    net(x)
    if was_training:
        net.train()
    for h in hooks:
        h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]:,}")
    return total[0]


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    lines = ["-" * (width + 30),
             f"{'Param':<{width}}{'Shape':<20}{'Count':>8}",
             "-" * (width + 30)]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<20}{n:>8}")
    lines += ["-" * (width + 30),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}",
              "-" * (width + 30)]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
