"""Engine-name-keyed weakref registry shared by the step-log and
decision-audit surfaces (`/steps`, flight dumps).

Entries hold weakrefs so a registry can never keep a dead engine's log
alive, and dead refs are pruned on every read instead of leaking one
map entry per engine name forever. `unregister` only removes the entry
if it still points at the caller's object (or is already dead) — a
newer engine reusing the name must not be evicted by the old one's
shutdown.
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict


class EngineRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._refs: Dict[str, weakref.ref] = {}

    def register(self, name: str, obj) -> None:
        with self._lock:
            self._refs[name] = weakref.ref(obj)

    def unregister(self, name: str, obj) -> None:
        with self._lock:
            ref = self._refs.get(name)
            if ref is not None and ref() in (obj, None):
                del self._refs[name]

    def get(self, name: str):
        """The live object registered under `name`, pruning a dead ref."""
        with self._lock:
            ref = self._refs.get(name)
            if ref is not None and ref() is None:
                del self._refs[name]
                ref = None
        return ref() if ref is not None else None

    def live(self) -> Dict[str, object]:
        """{name: obj} of every live entry, pruning dead refs."""
        with self._lock:
            items = list(self._refs.items())
        out = {}
        for name, ref in items:
            obj = ref()
            if obj is None:
                with self._lock:
                    if self._refs.get(name) is ref:
                        del self._refs[name]
                continue
            out[name] = obj
        return out
