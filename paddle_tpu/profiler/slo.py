"""SLO objectives + multi-window burn-rate evaluation (ISSUE 11).

The histograms answer "what latency have we EVER served"; an SLO needs
"are we meeting the objective RIGHT NOW, and how fast are we spending
the error budget". Three configurable objectives, all off by default:

- **TTFT p99** (`FLAGS_slo_ttft_p99_ms`): at most 1% of delivered
  requests per window may see first-token latency above the target.
- **TPOT p99** (`FLAGS_slo_tpot_p99_ms`): same budget for the steady
  decode cadence.
- **error rate** (`FLAGS_slo_error_rate`): at most this fraction of
  finished requests may fail (timeout / poison / engine death).

Each objective is evaluated over the rolling windows of
`FLAGS_slo_windows_s` (shortest first). The **burn rate** is the
classic SRE multi-window form: `bad_fraction / budget_fraction` — 1.0
means the budget is being consumed exactly as fast as the window
allows, >1.0 means the objective will be violated if the window's rate
holds, and the short window reacts in seconds while the long window
filters blips. Burn rates export three ways:

- `/slo` JSON (`payload()`), per engine per objective per window;
- Prometheus gauges `STAT_slo_<obj>_burn_bp_w<w>` (basis points,
  refreshed at `/metrics` scrape time like device telemetry);
- `GenerationEngine.health()`: with `FLAGS_slo_max_burn_rate` > 0 an
  engine whose FAST-window burn reaches the threshold reports
  not-ready, so `/readyz` sheds load BEFORE the budget is gone.

Observations are fed by the GenSpan resolve path (ttft/tpot) and the
engine's outcome paths (`observe_request`); everything is a bounded
deque append under one lock — recording never syncs the device and the
trackers are inert (no-ops) until some objective flag is set.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..framework import monitor
from ..framework.flags import flag

__all__ = ["enabled", "objectives", "windows", "observe_ttft",
           "observe_tpot", "observe_request", "evaluate", "payload",
           "shed_verdict", "clear_gauges", "forget", "reset"]

_MAX_SAMPLES = 65536      # per-series bound (oldest pruned)
_SHED_TTL_S = 0.5         # shed_verdict caches its (O(samples)) verdict

_lock = threading.Lock()
# engine -> {"ttft": deque[(t, ms)], "tpot": deque[(t, ms)],
#            "requests": deque[(t, ok)]}
_trackers: Dict[str, Dict[str, deque]] = {}
_gauge_names: set = set()  # STAT_slo_* names the last evaluate() wrote
# (engine, thresh, objectives) -> (wall, verdict): health()/readyz are
# router hot paths — a full evaluate() per poll rescans every sample
_shed_cache: Dict[tuple, Tuple[float, Optional[str]]] = {}


def objectives() -> Dict[str, float]:
    """{objective: target} of the ACTIVE objectives (flag > 0)."""
    out = {}
    ttft = float(flag("FLAGS_slo_ttft_p99_ms"))
    if ttft > 0:
        out["ttft"] = ttft
    tpot = float(flag("FLAGS_slo_tpot_p99_ms"))
    if tpot > 0:
        out["tpot"] = tpot
    err = float(flag("FLAGS_slo_error_rate"))
    if err > 0:
        out["error_rate"] = err
    return out


def enabled() -> bool:
    return bool(objectives())


def windows() -> List[float]:
    """Rolling-window lengths in seconds, shortest first (the first is
    the fast-burn window readiness shedding keys on)."""
    raw = str(flag("FLAGS_slo_windows_s"))
    out = sorted({float(w) for w in raw.split(",") if w.strip()
                  and float(w) > 0})
    return out or [60.0, 300.0]


def _series(engine: str, kind: str) -> deque:
    with _lock:
        tr = _trackers.setdefault(engine, {})
        s = tr.get(kind)
        if s is None:
            s = tr[kind] = deque(maxlen=_MAX_SAMPLES)
        return s


def _prune(s: deque, horizon: float) -> None:
    # oldest-first deque; drop everything older than the longest window
    while s and s[0][0] < horizon:
        s.popleft()


def observe_ttft(engine: str, ms: float) -> None:
    if enabled():
        _series(engine, "ttft").append((time.monotonic(), float(ms)))


def observe_tpot(engine: str, ms: float) -> None:
    if enabled():
        _series(engine, "tpot").append((time.monotonic(), float(ms)))


def observe_request(engine: str, ok: bool) -> None:
    """One finished request outcome (delivered vs timeout/poison/death)
    — the error-rate objective's sample stream."""
    if enabled():
        _series(engine, "requests").append((time.monotonic(), bool(ok)))


def _burn_cells(samples: List[Tuple[float, float]], now: float,
                wins: List[float], bad, budget: float) -> List[dict]:
    """All of one objective's (window, burn) cells in ONE pass over the
    samples: each sample is bucketed into the smallest window (`wins` is
    ascending) that contains it, and running prefix sums give every
    wider window's totals — O(samples + windows), not their product."""
    k = len(wins)
    totals = [0] * k
    viols = [0] * k
    for t, v in samples:
        age = now - t
        i = next((j for j in range(k) if age <= wins[j]), None)
        if i is None:
            continue
        totals[i] += 1
        if bad(v):
            viols[i] += 1
    cells = []
    total = viol = 0
    for j in range(k):
        total += totals[j]
        viol += viols[j]
        frac = viol / total if total else 0.0
        burn = frac / budget if total else 0.0
        cells.append({"seconds": wins[j], "count": total,
                      "violations": viol,
                      "bad_fraction": round(frac, 6),
                      "burn_rate": round(burn, 4),
                      "violated": bool(total) and burn >= 1.0})
    return cells


def evaluate(engine: Optional[str] = None,
             set_gauges: bool = True) -> dict:
    """Evaluate every active objective over every window for `engine`
    (or all tracked engines) and refresh the burn-rate gauges.

    Gauges are PER OBJECTIVE (max across engines — one process usually
    hosts one engine; the per-engine split lives in `/slo`), in basis
    points so a Prometheus alert on `> 10000` fires at burn 1.0."""
    objs = objectives()
    now = time.monotonic()
    wins = windows()
    horizon = now - max(wins)
    with _lock:
        names = ([engine] if engine is not None
                 else sorted(_trackers.keys()))
        snap = {}
        for name in names:
            tr = _trackers.get(name, {})
            series = {}
            for kind in ("ttft", "tpot", "requests"):
                s = tr.get(kind)
                if s is not None:
                    _prune(s, horizon)
                series[kind] = list(s) if s is not None else []
            snap[name] = series
    out = {}
    peak: Dict[str, float] = {}
    for name, series in snap.items():
        per_obj = {}
        for obj, target in objs.items():
            if obj == "error_rate":
                samples, bad, budget = (series["requests"],
                                        (lambda ok: not ok), target)
            else:
                samples, bad, budget = (series[obj],
                                        (lambda ms, t=target: ms > t),
                                        0.01)
            cells = _burn_cells(samples, now, wins, bad, budget)
            per_obj[obj] = {"target": target, "windows": cells,
                            "violated": any(c["violated"]
                                            for c in cells)}
            for c in cells:
                key = (obj, c["seconds"])
                peak[key] = max(peak.get(key, 0.0), c["burn_rate"])
        out[name] = per_obj
    if set_gauges:
        written = set()
        for (obj, w), burn in sorted(peak.items()):
            name = f"STAT_slo_{obj}_burn_bp_w{int(w)}"
            monitor.stat_set(name, int(round(burn * 10000)))
            written.add(name)
        # an objective that was just disabled (or a window that was
        # removed) must not keep exporting its last burn forever
        with _lock:
            stale = _gauge_names - written
            _gauge_names.clear()
            _gauge_names.update(written)
        for name in stale:
            monitor.stat_set(name, 0)
    return out


def clear_gauges() -> None:
    """Zero every burn-rate gauge the last evaluate() wrote — called by
    the exporter when SLOs are disabled so a stale burn can't keep a
    Prometheus alert firing on an objective that no longer exists."""
    with _lock:
        stale = set(_gauge_names)
        _gauge_names.clear()
    for name in stale:
        monitor.stat_set(name, 0)


def payload() -> dict:
    """The `/slo` JSON surface."""
    return {"enabled": enabled(),
            "objectives": objectives(),
            "windows_s": windows(),
            "max_burn_rate": float(flag("FLAGS_slo_max_burn_rate")),
            "engines": evaluate()}


def shed_verdict(engine: str) -> Optional[str]:
    """Readiness folding: a human reason string when `engine` should
    shed load (fast-window burn of any objective >=
    FLAGS_slo_max_burn_rate), else None. Called from
    GenerationEngine.health() — cheap when SLOs are off."""
    thresh = float(flag("FLAGS_slo_max_burn_rate"))
    objs = objectives()
    if thresh <= 0 or not objs:
        return None
    # TTL-cached: evaluate() rescans every sample, and health() is a
    # router hot path; a flag change invalidates through the key
    key = (engine, thresh, tuple(sorted(objs.items())))
    now = time.monotonic()
    with _lock:
        hit = _shed_cache.get(key)
        if hit is not None and now - hit[0] < _SHED_TTL_S:
            return hit[1]
    verdict = None
    per_obj = evaluate(engine, set_gauges=False).get(engine)
    for obj, res in sorted((per_obj or {}).items()):
        fast = res["windows"][0]
        if fast["count"] and fast["burn_rate"] >= thresh:
            verdict = (f"slo {obj} fast-window burn "
                       f"{fast['burn_rate']:.2f} >= {thresh:g} "
                       f"({fast['violations']}/{fast['count']} over "
                       f"{fast['seconds']:g}s, target {res['target']:g})")
            break
    with _lock:
        if len(_shed_cache) > 64:
            _shed_cache.clear()
        _shed_cache[key] = (now, verdict)
    return verdict


def forget(engine: str) -> None:
    """Drop one engine's samples + cached verdicts (engine shutdown —
    without this a process that churns uniquely-named engines grows a
    tracker per name forever and /slo keeps listing dead replicas)."""
    with _lock:
        _trackers.pop(engine, None)
        for k in [k for k in _shed_cache if k[0] == engine]:
            del _shed_cache[k]


def reset() -> None:
    """Drop every tracked sample (tests/benches on a warm process)."""
    with _lock:
        _trackers.clear()
        _shed_cache.clear()
