"""Live metrics export surface (reference `platform/monitor.h`
StatRegistry::publish → here rendered straight to Prometheus text, plus
a tiny stdlib HTTP server so the process is observable from OUTSIDE —
curl, a Prometheus scraper, or a dashboard — instead of only via
in-process `all_stats()` calls).

Endpoints (`MetricsServer`, 127.0.0.1, daemon threads, zero deps):

- `/metrics` — Prometheus text: every monitor counter (`counter`, or
  `gauge` for up-down/level stats — queue depth, device telemetry) and
  every `StatHistogram` as a real `histogram` — the log-spaced buckets
  map one-to-one onto cumulative `_bucket{le=...}` lines (zero-delta
  runs coalesced), plus `_sum`/`_count`. A scrape refreshes the device
  telemetry gauges so HBM/MFU are never interval-stale.
- `/stats` — JSON: counters, histogram snapshots, every registered
  `InferenceEngine.stats()` (lanes, buckets, occupancy, phase
  breakdown), device-telemetry snapshot, trace-ring state, and the
  flight recorder's last-dump summaries (reason, timestamp, path) so
  operators see recent postmortems without filesystem access.
- `/steps` — JSON of every generation engine's scheduler step ring
  (per-iteration admitted/freed/expired counts, queue depth + oldest
  age, page occupancy, prefill-vs-decode wall) plus the decision-audit
  tail — the input of `tools/engine_report.py`.
- `/slo` — SLO objectives, per-engine multi-window burn rates and
  violated flags (`profiler/slo.py`).
- `/trace` — the current chrome trace (same payload
  `export_chrome_tracing` writes, scheduler + history counter tracks
  included), so a live timeline is one curl away.
- `/history` — the time-series metrics rings (`profiler/timeseries.py`:
  counters-as-rates, gauges-as-levels, per-replica pressure ticks),
  bounded by FLAGS_metrics_history_samples — the trend view `/stats`
  cannot give, and the input of `tools/router_report.py --history`.
- `/healthz` — liveness: 200 whenever the process can answer.
- `/readyz` — readiness: 200 iff ≥1 registered engine is warmed up,
  has a live lane, is not draining, and its queue is below the
  rejection threshold; 503 otherwise, always with per-engine/per-lane
  JSON detail. This is the surface the router tier load-balances and
  drains against.

Wire-up: `InferenceEngine(metrics_port=)` / `FLAGS_metrics_port`, or
`start_metrics_server(port)` directly (port 0 binds an ephemeral port —
read it back from `.port`).
"""
from __future__ import annotations

import json
import os
import re
import threading
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..framework import monitor
from ..framework.flags import flag
from . import (device_telemetry, flight_recorder, slo, step_log,
               timeseries, tracer)

__all__ = ["render_prometheus", "MetricsServer", "start_metrics_server",
           "register_engine", "unregister_engine", "live_engines",
           "stats_payload", "readiness_payload"]

_PREFIX = "paddle_tpu_"


def _is_gauge(name: str) -> bool:
    # monitor is the single registry of gauge names (ISSUE 11): level
    # gauges self-register through stat_set/stat_gauge_add, up/down
    # counters register explicitly via monitor.register_gauge(...,
    # updown=True) — the exporter and the mp relay's skip rule read the
    # same table, so a gauge added in one place can't be mis-typed in
    # the other
    return monitor.is_gauge_name(name)


def _metric_name(name: str) -> str:
    return _PREFIX + re.sub(r"[^a-zA-Z0-9_]", "_", name).lower()


def _fmt(v: float) -> str:
    return "+Inf" if v == float("inf") else f"{v:.6g}"


def render_prometheus() -> str:
    """Prometheus exposition text of every registered counter and
    histogram (reference StatRegistry publish, Prometheus-shaped)."""
    try:  # refresh HBM/MFU gauges at scrape time (no-op off-accelerator)
        device_telemetry.sample()
    except Exception:
        pass
    try:  # refresh SLO burn-rate gauges the same way (no-op when off)
        if slo.enabled():
            slo.evaluate()
        else:
            slo.clear_gauges()  # disabling an objective must also stop
            # its last burn value from rendering forever
    except Exception:
        pass
    lines = []
    for name, v in monitor.all_stats().items():
        m = _metric_name(name)
        typ = "gauge" if _is_gauge(name) else "counter"
        lines.append(f"# TYPE {m} {typ}")
        lines.append(f"{m} {v}")
    for name, h in sorted(monitor.registered_histograms().items()):
        m = _metric_name(name)
        buckets = h.buckets()          # one consistent cumulative pass
        count = buckets[-1][1]
        lines.append(f"# TYPE {m} histogram")
        # sparse `le` sets are valid Prometheus, but histogram_quantile
        # interpolates linearly across whatever gap it sees — so a run of
        # equal cumulative counts must keep its LAST bucket (the tight
        # lower bound of the next occupied bucket), or quantiles read up
        # to the full run width low. Emit every change point plus the
        # bucket immediately before it.
        prev = None
        last_idx = -1
        for i, (le, cum) in enumerate(buckets[:-1]):
            if cum != prev:
                if i - 1 > last_idx:
                    ple, pcum = buckets[i - 1]
                    lines.append(f'{m}_bucket{{le="{_fmt(ple)}"}} {pcum}')
                lines.append(f'{m}_bucket{{le="{_fmt(le)}"}} {cum}')
                prev = cum
                last_idx = i
        if count != prev and last_idx < len(buckets) - 2:
            ple, pcum = buckets[-2]
            lines.append(f'{m}_bucket{{le="{_fmt(ple)}"}} {pcum}')
        lines.append(f'{m}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{m}_sum {h.sum:.6g}")
        lines.append(f"{m}_count {count}")
    for name, v in sorted(tracer.ring_stats().items()):
        m = f"{_PREFIX}trace_{name}"
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {v}")
    return "\n".join(lines) + "\n"


# -- engine registry (the `/stats` "engines" section) ----------------------

_engines_lock = threading.Lock()
_engines = {}  # engine name -> weakref


def register_engine(engine) -> None:
    with _engines_lock:
        _engines[engine.name] = weakref.ref(engine)


def unregister_engine(engine) -> None:
    with _engines_lock:
        ref = _engines.get(engine.name)
        if ref is not None and ref() in (engine, None):
            del _engines[engine.name]


def live_engines() -> dict:
    """`{name: engine}` of the still-alive registered engines — the
    registry surface the time-series sampler takes `pressure()` ticks
    from (weakrefs resolved, dead entries skipped but not reaped: the
    reaping stays with `_engines_snapshot`, the only mutating reader)."""
    with _engines_lock:
        items = list(_engines.items())
    out = {}
    for name, ref in items:
        eng = ref()
        if eng is not None:
            out[name] = eng
    return out


def _engines_snapshot() -> dict:
    with _engines_lock:
        items = list(_engines.items())
    out = {}
    for name, ref in items:
        eng = ref()
        if eng is None:
            with _engines_lock:
                if _engines.get(name) is ref:
                    del _engines[name]
            continue
        try:
            out[name] = eng.stats()
        except Exception as e:  # a dying engine must not break the page
            out[name] = {"error": repr(e)}
    return out


def stats_payload() -> dict:
    return {"stats": monitor.all_stats(),
            "histograms": monitor.all_histograms(),
            "engines": _engines_snapshot(),
            "device_telemetry": device_telemetry.snapshot(),
            "trace": tracer.ring_stats(),
            "flight_recorder": {"enabled": flight_recorder.enabled(),
                                "dumps": flight_recorder.dump_records()}}


def readiness_payload() -> dict:
    """`(ready, detail)` shape for `/readyz`: the process is ready iff
    at least one registered engine can take traffic right now — warmed
    up, ≥1 live lane, not draining, queue below the rejection
    threshold. Per-engine/per-lane detail always included so a router
    can tell "warming up" from "draining" from "overloaded"."""
    with _engines_lock:
        items = list(_engines.items())
    engines = {}
    for name, ref in items:
        eng = ref()
        if eng is None:
            continue
        try:
            engines[name] = eng.health()
        except Exception as e:  # a dying engine reads as not-ready
            engines[name] = {"ready": False, "reason": repr(e)}
    ready = any(h.get("ready") for h in engines.values())
    out = {"ready": ready, "engines": engines}
    if not engines:
        out["reason"] = "no engines registered"
    return out


# -- HTTP surface ----------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle_tpu-metrics"

    def log_message(self, *args):  # no per-scrape stderr chatter
        pass

    def do_GET(self):
        monitor.stat_add("STAT_metrics_requests")
        path = self.path.split("?", 1)[0]
        status = 200
        try:
            if path in ("/", "/metrics"):
                body = render_prometheus().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/stats":
                body = json.dumps(stats_payload(), default=str).encode()
                ctype = "application/json"
            elif path == "/steps":
                body = json.dumps(step_log.steps_payload(),
                                  default=str).encode()
                ctype = "application/json"
            elif path == "/slo":
                body = json.dumps(slo.payload(), default=str).encode()
                ctype = "application/json"
            elif path == "/trace":
                tracer.sample_counters()
                trace = tracer.chrome_trace()
                # scheduler state as counter tracks under the request
                # timeline (step ring → "C" events), plus the history
                # rings' rate/level series (ISSUE 20)
                trace["traceEvents"].extend(
                    step_log.chrome_counter_events())
                trace["traceEvents"].extend(
                    timeseries.chrome_counter_events())
                body = json.dumps(trace, default=str).encode()
                ctype = "application/json"
            elif path == "/history":
                body = json.dumps(timeseries.history_payload(),
                                  default=str).encode()
                ctype = "application/json"
            elif path == "/healthz":
                body = json.dumps({"status": "ok",
                                   "pid": os.getpid()}).encode()
                ctype = "application/json"
            elif path == "/readyz":
                payload = readiness_payload()
                status = 200 if payload["ready"] else 503
                body = json.dumps(payload, default=str).encode()
                ctype = "application/json"
            else:
                self.send_error(404, "unknown endpoint (have /metrics "
                                     "/stats /steps /slo /trace "
                                     "/history /healthz /readyz)")
                return
        except Exception as e:  # noqa: BLE001 — a scrape never kills us
            self.send_error(500, repr(e))
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsServer:
    """Threaded stdlib HTTP server bound to 127.0.0.1; `port=0` binds an
    ephemeral port (read `.port` back). Serves until `close()`."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._closed = False
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"paddle_tpu-metrics-{self.port}")
        self._thread.start()
        flight_recorder.touch()   # metrics users want the samplers running
        device_telemetry.touch()
        timeseries.touch()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self):
        if self._closed:  # idempotent: engine shutdown + caller may race
            return
        self._closed = True
        with _servers_lock:
            for k, v in list(_servers.items()):
                if v is self:
                    del _servers[k]
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


_servers_lock = threading.Lock()
_servers = {}  # requested port -> MetricsServer


def start_metrics_server(port: Optional[int] = None) -> \
        Optional[MetricsServer]:
    """Start (or return the already-running) metrics server. `port=None`
    resolves `FLAGS_metrics_port`, where 0 means OFF (returns None);
    an explicit `port=0` binds an ephemeral port. Idempotent per
    requested port — every engine pointing at the same port shares one
    server."""
    from_flag = port is None
    port = int(flag("FLAGS_metrics_port")) if port is None else int(port)
    if from_flag and port == 0:
        return None
    with _servers_lock:
        srv = _servers.get(port)
        if srv is not None:
            return srv
        srv = MetricsServer(port)
        if port != 0:  # ephemeral requests are never shared
            _servers[port] = srv
        return srv
