"""Per-request latency attribution for the serving engine.

One end-to-end latency histogram (PR 2) tells an operator a request was
slow; it never says WHERE — queued behind a batching window, padding and
concat on the dispatcher, on-device compute, or host-sync/slice on the
completer. A `Span` is assigned at `InferenceEngine.submit()` and rides
the `_Request` through collector → lane dispatch → device completion →
slice/resolve; each stage stamps one monotonic phase timestamp:

    queued      submit() accepted the request into the intake queue
    claimed     the collector popped it into a batch
    padded      the dispatcher finished concat + pad-to-bucket
    dispatched  the device call was enqueued (async dispatch returned)
    device_done the completer's host sync finished (device compute done)
    sliced      per-request rows were sliced out of the batch outputs
    resolved    the future was resolved

On resolve the consecutive stamp deltas feed four process-global
`StatHistogram`s — `serving_queue_ms` (queued→claimed), `serving_pad_ms`
(claimed→dispatched), `serving_device_ms` (dispatched→device_done),
`serving_resolve_ms` (device_done→resolved) — whose sum telescopes
exactly to resolved−queued, so per-phase numbers always reconcile with
the end-to-end latency. The same stamps are exported three more ways:

- chrome-trace **flow events** (`ph:"s"` in the submit scope, `"t"` in
  the lane's dispatch scope, `"f"` in its complete scope) draw arrows
  linking one request's scopes across threads in the timeline;
- one compact `reqspan:` instant per resolved request carrying the full
  breakdown — `tools/latency_report.py` reconstructs per-request
  p50/p99 and top-N offenders offline from an exported trace;
- `engine.stats()["phases"]` / `/metrics` for live dashboards.

A request that is retried (poisoned batch isolation) re-stamps the
dispatch-side phases — latest wins, so the first attempt's device time
is attributed to the pad phase of the retry and the telescoping sum
still holds. Spans on timed-out or failed requests are abandoned (no
histogram samples — phase latencies describe DELIVERED work) but still
appear in flight-recorder dumps as the dying lane's in-flight spans.

Everything is gated by `FLAGS_serving_spans` (default on); the cost per
request is a handful of `perf_counter()` calls, dict stores and bounded
ring appends — `bench.py --mode serving` A/Bs the flag and gates the
overhead at <2% qps.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

from ..framework import monitor
from ..framework.flags import flag
from . import tracer

__all__ = ["Span", "enabled", "start", "phase_snapshot", "PHASES",
           "GenSpan", "start_gen", "GEN_PHASES"]

PHASES = ("queued", "claimed", "padded", "dispatched", "device_done",
          "sliced", "resolved")

# (histogram, from_stamp, to_stamp) — consecutive, so sums telescope
_PHASE_HISTS = (("serving_queue_ms", "queued", "claimed"),
                ("serving_pad_ms", "claimed", "dispatched"),
                ("serving_device_ms", "dispatched", "device_done"),
                ("serving_resolve_ms", "device_done", "resolved"))

_next_id = itertools.count(1)
_hists_lock = threading.Lock()
_hists = None


def enabled() -> bool:
    return bool(flag("FLAGS_serving_spans"))


def _phase_hists():
    global _hists
    if _hists is None:
        with _hists_lock:
            if _hists is None:
                # literal names: the check_stats lint reads these
                _hists = (monitor.histogram("serving_queue_ms"),
                          monitor.histogram("serving_pad_ms"),
                          monitor.histogram("serving_device_ms"),
                          monitor.histogram("serving_resolve_ms"))
    return _hists


def phase_snapshot() -> dict:
    """{phase_histogram_name: snapshot} — the engine.stats() breakdown.
    Process-global like every STAT counter: engines share the four
    histograms (the per-engine split lives in `<name>_request_ms`)."""
    return {spec[0]: h.snapshot()
            for spec, h in zip(_PHASE_HISTS, _phase_hists())}


class Span:
    """One request's phase clock. Single-writer per stage (the request
    moves collector → dispatcher → completer hand-to-hand), so plain
    dict stores under the GIL are enough."""

    __slots__ = ("rid", "engine", "lane", "bucket", "stamps")

    def __init__(self, engine: str):
        self.rid = next(_next_id)
        self.engine = engine
        self.lane: Optional[int] = None
        self.bucket: Optional[int] = None
        self.stamps = {}

    def stamp(self, phase: str, t: Optional[float] = None) -> None:
        # latest-wins: a poisoned-batch retry re-runs the dispatch-side
        # phases; overwriting keeps the stamps monotone so the phase
        # deltas stay non-negative and telescope to end-to-end
        self.stamps[phase] = time.perf_counter() if t is None else t

    def flow(self, ph: str) -> None:
        """Emit the chrome flow event for this request on the CALLING
        thread — inside the scope the arrow should attach to."""
        tracer.flow("serving_request", ph, self.rid)

    def phase_ms(self) -> Optional[dict]:
        """{hist_name: ms} for the four consecutive phases; None until
        every boundary stamp exists."""
        s = self.stamps
        out = {}
        for name, a, b in _PHASE_HISTS:
            if a not in s or b not in s:
                return None
            out[name] = (s[b] - s[a]) * 1000.0
        return out

    def finish(self) -> None:
        """Called once per DELIVERED request, after `resolved` is
        stamped: feed the phase histograms and drop one self-contained
        `reqspan:` instant into the trace ring for offline attribution."""
        phases = self.phase_ms()
        if phases is None:
            return
        for (name, _, _), h in zip(_PHASE_HISTS, _phase_hists()):
            h.observe(max(0.0, phases[name]))
        e2e = (self.stamps["resolved"] - self.stamps["queued"]) * 1000.0
        q, p, d, r = (phases[n] for n, _, _ in _PHASE_HISTS)
        tracer.instant(
            f"reqspan:{self.rid}:{self.engine}:lane{self.lane}:"
            f"b{self.bucket}:q={q:.3f},p={p:.3f},d={d:.3f},r={r:.3f},"
            f"e={e2e:.3f}", t=self.stamps["resolved"])

    def to_dict(self) -> dict:
        """Postmortem shape for flight-recorder dumps (the in-flight
        spans of a dying lane)."""
        now = time.perf_counter()
        return {"rid": self.rid, "engine": self.engine, "lane": self.lane,
                "bucket": self.bucket,
                "phases": dict(self.stamps),
                "age_ms": round((now - self.stamps["queued"]) * 1000.0, 3)
                if "queued" in self.stamps else None}


def start(engine: str) -> Optional[Span]:
    """Span for one accepted request (None when spans are off). Stamps
    `queued` and emits the flow start — call inside the submit scope."""
    if not enabled():
        return None
    span = Span(engine)
    span.stamp("queued")
    span.flow("s")
    return span


# -- generation spans (continuous-batching token latency) -------------------
#
# A generative request's latency story is not the serving pipeline's
# queue/pad/device/resolve: what operators tune against is **TTFT**
# (time to first token — queue + prefill) and **TPOT** (time per output
# token — the steady decode cadence). A GenSpan rides a
# GenerationEngine request through submit → slot admission → prefill →
# every decode step, and on resolve feeds two process-global histograms
# that telescope into the existing end-to-end accounting:
#
#     ttft_ms + (n_tokens - 1) * tpot_ms  ==  queued → last_token
#
# with the engine's own `<name>_request_ms` histogram carrying the full
# queued → resolved wall (the resolve tail is host bookkeeping). Each
# resolved request also drops one self-contained `reqspan:` instant
# (slot-flavored: `reqspan:<rid>:<engine>:slot<k>:n=<tok>:
# ttft=…,tpot=…,e=…,pfx=<hit>`, `pfx` = prompt tokens served from the
# prefix cache) so `tools/latency_report.py` reconstructs TTFT/TPOT
# p50/p99 and slowest-request offenders offline from an exported trace.

GEN_PHASES = ("queued", "admitted", "prefilled", "first_token",
              "last_token", "resolved")

_gen_hists = None


def _gen_phase_hists():
    global _gen_hists
    if _gen_hists is None:
        with _hists_lock:
            if _gen_hists is None:
                # literal names: the check_stats lint reads these
                _gen_hists = (monitor.histogram("ttft_ms"),
                              monitor.histogram("tpot_ms"))
    return _gen_hists


class GenSpan:
    """One generative request's token clock (single-writer: the engine's
    step thread owns every stamp after `queued`). `prefix_tokens` is the
    count of prompt tokens served from cached prefix pages (ISSUE 12) —
    it rides the reqspan instant (`pfx=`) so offline TTFT attribution
    can split hit from miss requests. `spec_tokens` (ISSUE 14) is the
    count of accepted speculative draft tokens — it rides the instant as
    `acc=`, so offline TPOT attribution can split speculation's
    multi-token steps from plain decode. `trace_id` (ISSUE 20) is the
    fleet-wide 16-hex trace id — it rides the instant as `tid=` and is
    re-emitted as cross-process-stable `fleet_request` flow events, so
    the merged fleet timeline links router decision → this replica's
    span → any post-restart replay span under ONE arrow chain even
    though each incarnation allocated a fresh local rid."""

    __slots__ = ("rid", "engine", "slot", "stamps", "prefix_tokens",
                 "spec_tokens", "incarnation", "trace_id")

    def __init__(self, engine: str, incarnation: int = 0,
                 trace_id: Optional[str] = None):
        self.rid = next(_next_id)
        self.engine = engine
        self.slot: Optional[int] = None
        self.stamps = {}
        self.prefix_tokens = 0
        self.spec_tokens = 0
        # which engine generation served this request (ISSUE 15 — a
        # supervised restart bumps it); rides the reqspan as `inc=` so
        # offline reports split pre- from post-restart requests
        self.incarnation = int(incarnation)
        # fleet trace id (ISSUE 20) — None when propagation is off
        self.trace_id = trace_id

    def stamp(self, phase: str, t: Optional[float] = None) -> None:
        self.stamps[phase] = time.perf_counter() if t is None else t

    def flow(self, ph: str) -> None:
        tracer.flow("gen_request", ph, self.rid)

    def fleet_flow(self, ph: str) -> None:
        """Emit the fleet-wide flow event for this request's trace id —
        the flow id is derived from the 16-hex id itself, so every
        process that handled the same request emits under the same id
        and the merged timeline draws one chain."""
        if self.trace_id is None:
            return
        from . import trace_context
        tracer.flow("fleet_request", ph, trace_context.flow_id(self.trace_id))

    def finish(self, n_tokens: int,
               prefix_tokens: Optional[int] = None,
               spec_tokens: Optional[int] = None) -> None:
        """Called once per DELIVERED request after `resolved` is
        stamped: feed ttft_ms/tpot_ms and drop the reqspan instant."""
        if prefix_tokens is not None:
            self.prefix_tokens = int(prefix_tokens)
        if spec_tokens is not None:
            self.spec_tokens = int(spec_tokens)
        s = self.stamps
        if "queued" not in s or "first_token" not in s:
            return
        ttft_h, tpot_h = _gen_phase_hists()
        ttft = (s["first_token"] - s["queued"]) * 1000.0
        last = s.get("last_token", s["first_token"])
        tpot = ((last - s["first_token"]) * 1000.0
                / max(1, n_tokens - 1)) if n_tokens > 1 else 0.0
        ttft_h.observe(max(0.0, ttft))
        if n_tokens > 1:
            tpot_h.observe(max(0.0, tpot))
        # rolling-window SLO samples ride the same resolve path (no-ops
        # until an FLAGS_slo_* objective is configured)
        from . import slo
        slo.observe_ttft(self.engine, max(0.0, ttft))
        if n_tokens > 1:
            slo.observe_tpot(self.engine, max(0.0, tpot))
        e2e = (s.get("resolved", last) - s["queued"]) * 1000.0
        # pfx/acc ride the VALUES segment (after e=) so the colon-
        # separated head keeps its field count — downstream parsers
        # split on ":", and each appended value is regex-optional so
        # older traces (and older parsers) keep working both ways
        tid = f",tid={self.trace_id}" if self.trace_id else ""
        tracer.instant(
            f"reqspan:{self.rid}:{self.engine}:slot{self.slot}:"
            f"n={n_tokens}:ttft={ttft:.3f},tpot={tpot:.3f},e={e2e:.3f},"
            f"pfx={self.prefix_tokens},acc={self.spec_tokens},"
            f"inc={self.incarnation}{tid}",
            t=s.get("resolved", last))
        self.fleet_flow("f")

    def to_dict(self) -> dict:
        now = time.perf_counter()
        return {"rid": self.rid, "engine": self.engine, "slot": self.slot,
                "phases": dict(self.stamps),
                "age_ms": round((now - self.stamps["queued"]) * 1000.0, 3)
                if "queued" in self.stamps else None}


def start_gen(engine: str, incarnation: int = 0,
              trace_id: Optional[str] = None,
              trace_root: bool = True) -> Optional[GenSpan]:
    """GenSpan for one accepted generative request (None when spans are
    off — same FLAGS_serving_spans gate as the serving pipeline).

    `trace_root=False` means an upstream hop (the Router) already
    opened the fleet flow chain for `trace_id`, so admission emits a
    flow STEP ("t"); a locally-minted id opens the chain here ("s")."""
    if not enabled():
        return None
    span = GenSpan(engine, incarnation, trace_id=trace_id)
    span.stamp("queued")
    span.flow("s")
    span.fleet_flow("s" if trace_root else "t")
    return span
