"""Fleet-wide trace-context propagation.

One request = one 16-hex trace id, minted ONCE at the outermost entry
point that sees the request (the Router when placement is involved, the
engine itself for direct submits) and threaded *explicitly* through
every hop — placement audit details (``trace=``), supervisor delegation,
engine admission, per-incarnation GenSpans, replay entries, and stream
delivery. No contextvars, no thread-locals: the id rides the request
objects so it survives thread handoffs, supervisor restarts, and (soon)
process boundaries unchanged.

The id doubles as a chrome flow id: :func:`flow_id` folds the 16 hex
chars into a positive int64 that is stable across processes, so N
replicas' ``/trace`` exports merged by ``tools/fleet_trace.py`` draw one
arrow chain per request (``fleet_request`` flow events) even though each
process allocated its own local rids.
"""

import os
import re

from ..framework.flags import flag

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{16}$")


def enabled() -> bool:
    """Trace propagation on? Read per-request so tests and bench can
    flip FLAGS_trace_propagation at runtime."""
    return bool(flag("FLAGS_trace_propagation"))


def new_trace_id() -> str:
    """A fresh 16-hex (64-bit) trace id."""
    return os.urandom(8).hex()


def is_trace_id(s) -> bool:
    """True iff *s* is a well-formed 16-hex trace id."""
    return isinstance(s, str) and bool(_TRACE_ID_RE.match(s))


def flow_id(trace_id: str) -> int:
    """Chrome flow-event id for a trace id: the hex value masked to a
    positive int64. Deterministic across processes — every replica that
    saw the same trace id emits flow events under the same id, which is
    what lets the merged timeline link them."""
    return int(trace_id, 16) & 0x7FFFFFFFFFFFFFFF
