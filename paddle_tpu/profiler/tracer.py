"""Thread-aware bounded trace store (reference `platform/profiler.h`:
per-thread `EventList` + `GetEventList()` thread_local, merged at export
— the same structure CUPTI's `device_tracer` merges device streams
into).

Each thread owns ONE bounded ring buffer; appends touch only
thread-local state (no lock on the hot path — the ring is created once
per thread and registered under a lock, after which the owning thread is
the only writer). Readers (chrome export, the flight recorder, the
`/trace` endpoint) take a best-effort snapshot: under the GIL a list
copy is always well-formed, at worst missing the very newest events.

Recording is active whenever the profiler is started *or* the flight
recorder flag is on (the default), so a crash dump always has recent
context; memory stays bounded at `FLAGS_trace_ring_size` events per
thread — the ring overwrites its oldest events instead of growing.

Counter samples are a separate (small, locked) ring of
`(t, {stat: value})` snapshots taken by `Profiler.step()`, the flight
recorder's periodic sampler, and chrome export — they render as "C"
phase counter tracks in chrome://tracing.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..framework.flags import flag

# event: (name, ph, t0, t1) — ph "X" = complete scope, "i" = instant,
# "s#<id>"/"t#<id>"/"f#<id>" = chrome flow start/step/finish carrying the
# flow id (per-request spans link a submit scope to its lane's
# dispatch/complete scopes across threads).
_Event = Tuple[str, str, float, float]

_MAX_RINGS = 512        # bound on remembered threads (oldest evicted)
_COUNTER_CAP = 4096     # bound on counter samples

_registry_lock = threading.Lock()
_rings: List["_Ring"] = []
_next_track = [1]       # chrome tid allocator (0 = counter track)

_counter_lock = threading.Lock()
_counter_samples: List[Tuple[float, Dict[str, int]]] = []

_profiler_enabled = False
_t_start = 0.0          # perf_counter at the last start_profiler()


class _Ring:
    """One thread's bounded event ring. Only the owning thread appends."""

    __slots__ = ("os_tid", "track", "thread_name", "cap", "buf", "idx",
                 "overwritten", "_thread_ref")

    def __init__(self, thread, cap: int):
        self.os_tid = thread.ident
        self.thread_name = thread.name
        self.cap = max(1, int(cap))
        self.buf: List[_Event] = []
        self.idx = 0            # oldest slot once the ring is full
        self.overwritten = 0
        # weakref: liveness probe for registry eviction without keeping
        # dead Thread objects reachable
        import weakref
        self._thread_ref = weakref.ref(thread)

    def alive(self) -> bool:
        t = self._thread_ref()
        return t is not None and t.is_alive()

    def append(self, ev: _Event) -> None:
        if len(self.buf) < self.cap:
            self.buf.append(ev)
        else:
            self.buf[self.idx] = ev
            self.idx = (self.idx + 1) % self.cap
            self.overwritten += 1

    def snapshot(self) -> List[_Event]:
        buf = list(self.buf)    # atomic-enough copy under the GIL
        idx = self.idx
        if len(buf) < self.cap or idx == 0:
            return buf
        return buf[idx:] + buf[:idx]


class _Local(threading.local):
    ring: Optional[_Ring] = None


_local = _Local()


def _my_ring() -> _Ring:
    r = _local.ring
    if r is None:
        t = threading.current_thread()
        r = _Ring(t, int(flag("FLAGS_trace_ring_size")))
        with _registry_lock:
            r.track = _next_track[0]
            _next_track[0] += 1
            _rings.append(r)
            if len(_rings) > _MAX_RINGS:
                # evict oldest DEAD rings only: a live thread keeps
                # appending through its thread-local reference, and
                # unregistering it would silently drop its events from
                # every export (the exact bug this store exists to fix).
                # Recently-dead rings stay while there is room — their
                # events are postmortem context. Only a pathological
                # >_MAX_RINGS *live* threads can still overflow, in
                # which case the registry grows with them.
                overflow = len(_rings) - _MAX_RINGS
                i = 0
                while overflow > 0 and i < len(_rings) - 1:
                    if not _rings[i].alive():
                        del _rings[i]
                        overflow -= 1
                    else:
                        i += 1
        _local.ring = r
    return r


def _active() -> bool:
    return _profiler_enabled or bool(flag("FLAGS_flight_recorder"))


# -- recording -------------------------------------------------------------

def record_complete(name: str, t0: float, t1: float) -> None:
    """One closed scope on the calling thread (perf_counter seconds)."""
    if _active():
        _my_ring().append((name, "X", t0, t1))


def instant(name: str, t: Optional[float] = None) -> None:
    """One instant marker on the calling thread (step boundaries,
    flight-recorder notes)."""
    if _active():
        t = time.perf_counter() if t is None else t
        _my_ring().append((name, "i", t, t))


def flow(name: str, ph: str, flow_id: int, t: Optional[float] = None) -> None:
    """One chrome flow event on the calling thread: ph "s" (start), "t"
    (step) or "f" (finish). Events with the same id render as arrows
    linking the enclosing slices across threads — emit INSIDE the scope
    the arrow should attach to."""
    if ph not in ("s", "t", "f"):
        raise ValueError(f"flow ph must be s/t/f, got {ph!r}")
    if _active():
        t = time.perf_counter() if t is None else t
        _my_ring().append((name, f"{ph}#{int(flow_id)}", t, t))


def sample_counters(names=None) -> None:
    """Append one `(t, {stat: value})` snapshot of the monitor counters
    to the bounded counter-sample ring."""
    if not _active():
        return
    from ..framework import monitor
    snap = monitor.all_stats()
    if names is not None:
        names = set(names)
        snap = {k: v for k, v in snap.items() if k in names}
    with _counter_lock:
        _counter_samples.append((time.perf_counter(), snap))
        if len(_counter_samples) > _COUNTER_CAP:
            del _counter_samples[: len(_counter_samples) - _COUNTER_CAP]


# -- profiler session ------------------------------------------------------

def enable() -> None:
    global _profiler_enabled, _t_start
    _t_start = time.perf_counter()
    _profiler_enabled = True


def disable() -> None:
    global _profiler_enabled
    _profiler_enabled = False


def profiler_enabled() -> bool:
    return _profiler_enabled


def session_start() -> float:
    return _t_start


def clear() -> None:
    """Drop every recorded event and counter sample (tests)."""
    with _registry_lock:
        for r in _rings:
            r.buf = []
            r.idx = 0
            r.overwritten = 0
    with _counter_lock:
        del _counter_samples[:]


# -- reading ---------------------------------------------------------------

def _ring_list() -> List[_Ring]:
    with _registry_lock:
        return list(_rings)


def events(since: Optional[float] = None, with_threads: bool = False):
    """Flat event list across every thread, oldest-first.

    with_threads=False → [(name, t0, t1)] of complete scopes only (the
    legacy `profiler._state.events` shape); with_threads=True →
    [(name, ph, t0, t1, track, os_tid, thread_name)].
    """
    out = []
    for r in _ring_list():
        for name, ph, t0, t1 in r.snapshot():
            if since is not None and t0 < since:
                continue
            if with_threads:
                out.append((name, ph, t0, t1, r.track, r.os_tid,
                            r.thread_name))
            elif ph == "X":
                out.append((name, t0, t1))
    out.sort(key=lambda e: e[-5] if with_threads else e[1])
    return out


def tail_events(n: int):
    """The ~n most recent events across all threads, oldest-first, in
    the `with_threads` tuple shape — bounded work (each ring contributes
    at most n events, one sort) so failure-path dumps stay cheap even
    with large rings and many threads."""
    out = []
    for r in _ring_list():
        for name, ph, t0, t1 in r.snapshot()[-n:] if n > 0 else []:
            out.append((name, ph, t0, t1, r.track, r.os_tid,
                        r.thread_name))
    out.sort(key=lambda e: e[3])  # by scope end time
    return out[-n:] if n > 0 else out


def counter_samples(since: Optional[float] = None):
    with _counter_lock:
        samples = list(_counter_samples)
    if since is not None:
        samples = [s for s in samples if s[0] >= since]
    return samples


def ring_stats() -> dict:
    rings = _ring_list()
    return {"threads": len(rings),
            "events": sum(len(r.buf) for r in rings),
            "overwritten": sum(r.overwritten for r in rings),
            "ring_capacity": int(flag("FLAGS_trace_ring_size"))}


def chrome_trace(since: Optional[float] = None) -> dict:
    """chrome://tracing JSON object: per-thread named tracks (metadata
    "M" events carry real thread names), "X" scopes with real tids, "i"
    markers, and "C" counter tracks from the sampled monitor stats."""
    pid = os.getpid()
    trace = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
              "args": {"name": f"paddle_tpu (pid {pid})"}}]
    for r in _ring_list():
        evs = [e for e in r.snapshot()
               if since is None or e[2] >= since]
        if not evs:
            continue
        trace.append({"ph": "M", "name": "thread_name", "pid": pid,
                      "tid": r.track, "args": {"name": r.thread_name}})
        trace.append({"ph": "M", "name": "thread_sort_index", "pid": pid,
                      "tid": r.track, "args": {"sort_index": r.track}})
        for name, ph, t0, t1 in evs:
            if ph == "X":
                trace.append({"name": name, "ph": "X", "pid": pid,
                              "tid": r.track, "ts": t0 * 1e6,
                              "dur": (t1 - t0) * 1e6})
            elif "#" in ph:
                p, fid = ph.split("#", 1)
                ev = {"name": name, "cat": "serving", "ph": p,
                      "id": int(fid), "pid": pid, "tid": r.track,
                      "ts": t0 * 1e6}
                if p == "f":
                    ev["bp"] = "e"  # bind to enclosing slice's end
                trace.append(ev)
            else:
                trace.append({"name": name, "ph": "i", "s": "t",
                              "pid": pid, "tid": r.track, "ts": t0 * 1e6})
    # counter tracks: one "C" series per stat that is ever nonzero in
    # the sampled window (all-zero tracks are noise, not signal)
    samples = counter_samples(since)
    live = sorted({n for _, snap in samples for n, v in snap.items() if v})
    for t, snap in samples:
        for n in live:
            if n in snap:
                trace.append({"name": n, "ph": "C", "pid": pid, "tid": 0,
                              "ts": t * 1e6, "args": {"value": snap[n]}})
    return {"traceEvents": trace,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "paddle_tpu.profiler"}}
