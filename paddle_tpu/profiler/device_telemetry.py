"""Device telemetry: live HBM, compile-time ledger, FLOPs/MFU gauges.

The PR 5 surface measured the HOST (thread scopes, request counters);
the device itself stayed invisible — an operator could not answer "how
full is HBM", "how much wall time has gone to XLA compiles on chip 3",
or "what MFU is the train step achieving" without attaching a profiler.
This module closes that with a lazy periodic sampler (same lifecycle as
the flight recorder's counter sampler: `touch()`d by long-running
subsystems — engines, `Model.fit`, the `MetricsServer` — so a process
that never serves or trains never pays for the thread):

- **live HBM** — `jax` per-device `memory_stats()` →
  `STAT_device<id>_hbm_bytes_in_use` / `_hbm_bytes_limit` gauges; a
  graceful no-op on backends that return nothing (CPU test hosts).
- **compile-seconds ledger** — the serving lanes' exact per-replica
  compile counters already detect WHEN a (device, bucket) pair
  compiles; `note_compile()` adds the measured dispatch wall of that
  call to a cumulative per-(device, bucket) ledger, exported as
  `STAT_compile_ms_<key>` counters plus the full ledger in
  `snapshot()` → `/stats`. Warmup-vs-live compile cost is the number a
  restarting fleet's AOT-cache work (ROADMAP) will be judged against.
- **FLOPs / MFU** — `hapi.Model` / the sharded pjit step call
  `note_train_step_lowering()` once per newly-compiled step; an XLA
  HLO cost analysis on the *lowered* module (no second backend
  compile) yields per-step FLOPs (`STAT_train_step_flops`). The
  sampler turns the `STAT_train_steps` delta per wall interval into
  achieved FLOP/s and divides by the device-kind peak (table below, or
  `FLAGS_device_peak_flops`) × participating devices →
  `STAT_train_mfu_bp` (basis points, i.e. 100·percent). Unknown device
  kinds simply don't export MFU.

All values live in the ordinary monitor registry, so they render as
Prometheus gauges in `/metrics` AND as "C" counter tracks in the chrome
trace via the existing `sample_counters()` path — no new export plumbing.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from ..framework import monitor
from ..framework.flags import flag

__all__ = ["touch", "active", "sample", "note_compile",
           "note_train_step_lowering", "snapshot", "peak_flops"]

# bf16 peak FLOP/s per chip by device kind substring (public TPU specs);
# checked in order, first hit wins
_PEAK_TABLE = (
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

_lock = threading.Lock()
_sampler = [None]             # lazy daemon thread, one per process
_compile_ledger = {}          # (device_key, bucket) -> cumulative seconds
_flops_per_step = [0.0]       # from the last cost-analyzed train step
_train_devices = [1]          # devices participating in that step
_mfu_prev = [None]            # (t, STAT_train_steps) at the last window
# shortest steps/sec measurement window: every sample() caller (the
# periodic thread AND each /metrics scrape) shares one anchor under
# _lock, and the anchor only advances once a window this long has
# elapsed — a scrape landing 40ms after a sampler tick must not measure
# 1 step over 40ms and report a 5x MFU spike
_MIN_MFU_WINDOW_S = 0.5


def active() -> bool:
    """True while telemetry is wanted AND enabled: some subsystem has
    touch()ed the sampler and the interval flag is currently positive.
    The cost-analysis hooks check this, so flipping the flag to 0 at
    runtime stops both the sampling and the per-compile step retrace —
    and flipping it back on revives them (the sampler thread re-reads
    the flag every tick)."""
    return (_sampler[0] is not None
            and float(flag("FLAGS_device_telemetry_interval_s")) > 0)


def touch() -> None:
    """Start the sampler thread (idempotent, lazy; same contract as
    flight_recorder.touch). The thread starts even while the interval
    flag is 0 — it idles cheaply and honors a later runtime
    set_flags(interval>0), instead of being permanently unenableable
    because the flag happened to be 0 at touch() time."""
    with _lock:
        if _sampler[0] is None:
            t = threading.Thread(target=_sampler_loop, daemon=True,
                                 name="paddle_tpu-device-telemetry")
            _sampler[0] = t
            t.start()


def _sampler_loop():
    while True:
        iv = float(flag("FLAGS_device_telemetry_interval_s"))
        time.sleep(max(iv, 0.5) if iv > 0 else 5.0)
        if iv > 0:
            try:
                sample()
            except Exception:
                pass


def peak_flops(device) -> float:
    """Peak FLOP/s for one device: the flag override when set, else the
    device-kind table; 0.0 = unknown (no MFU gauge)."""
    override = float(flag("FLAGS_device_peak_flops"))
    if override > 0:
        return override
    kind = str(getattr(device, "device_kind", "")).lower()
    for sub, peak in _PEAK_TABLE:
        if sub in kind:
            return peak
    return 0.0


def sample() -> dict:
    """Take one telemetry sample, set the gauges, and return it (also
    called at `/metrics` scrape time so dashboards never read a stale
    interval-old value)."""
    out = {"devices": {}, "mfu_bp": None, "flops_per_step":
           int(_flops_per_step[0])}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        devices = []
    peak_total = 0.0
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # backend without memory introspection
            stats = None
        if stats:
            in_use = int(stats.get("bytes_in_use", 0))
            monitor.stat_set(f"STAT_device{d.id}_hbm_bytes_in_use", in_use)
            dev = {"hbm_bytes_in_use": in_use}
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            if limit:
                monitor.stat_set(f"STAT_device{d.id}_hbm_bytes_limit",
                                 int(limit))
                dev["hbm_bytes_limit"] = int(limit)
            out["devices"][str(d.id)] = dev
        peak_total += peak_flops(d)
    # MFU: achieved train FLOP/s over the measurement window vs peak of
    # the devices the step actually runs on. One anchor shared by every
    # caller, advanced under the lock and only after a minimum window —
    # concurrent scrapes can neither double-attribute a step delta nor
    # measure over an arbitrarily tiny interval.
    steps = monitor.stat_get("STAT_train_steps")
    now = time.perf_counter()
    flops = _flops_per_step[0]
    if flops > 0:
        monitor.stat_set("STAT_train_step_flops", int(flops))
    window = None
    with _lock:
        prev = _mfu_prev[0]
        if prev is None:
            _mfu_prev[0] = (now, steps)
        elif now - prev[0] >= _MIN_MFU_WINDOW_S:
            _mfu_prev[0] = (now, steps)
            window = (now - prev[0], steps - prev[1])
    if flops > 0 and window is not None:
        n_dev = max(1, int(_train_devices[0]))
        per_dev = peak_total / max(len(devices), 1) if devices else 0.0
        peak = per_dev * n_dev
        if peak > 0:
            dt, dsteps = window
            # dsteps == 0 decays the gauge to 0: an idle trainer reads
            # as idle, not as its last busy window forever
            mfu = (flops * max(0, dsteps) / dt) / peak
            out["mfu_bp"] = int(round(mfu * 10000))
            monitor.stat_set("STAT_train_mfu_bp", out["mfu_bp"])
    return out


def note_compile(device_key, bucket, seconds: float) -> None:
    """Add one observed XLA compile's wall seconds to the cumulative
    (device, bucket) ledger. Called by serving lanes when their exact
    per-replica compile counters detect a trace — the measured dispatch
    wall of that call is compile-dominated."""
    key = (str(device_key), bucket)
    with _lock:
        _compile_ledger[key] = _compile_ledger.get(key, 0.0) + seconds
    monitor.stat_add(f"STAT_compile_ms_{device_key}",
                     int(round(seconds * 1000)))


def note_train_step_lowering(jitted, args, n_devices: int = 1) -> None:
    """Estimate per-step FLOPs for a freshly-compiled train step via HLO
    cost analysis on the lowered (NOT re-compiled) module. No-op unless
    the sampler is active — tracing the step a second time is cheap but
    not free, and a process that never asked for telemetry shouldn't
    pay it. Never raises (telemetry must not break training)."""
    if not active():
        return
    try:
        ca = jitted.lower(*args).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0)) if ca else 0.0
        if flops > 0:
            _flops_per_step[0] = flops
            _train_devices[0] = max(1, int(n_devices))
            monitor.stat_set("STAT_train_step_flops", int(flops))
    except Exception:
        pass


def snapshot() -> dict:
    """The `/stats` section: compile ledger per (device, bucket), FLOPs
    and device count of the last analyzed step, sampler state."""
    with _lock:
        ledger = {f"{dev}/b{bkt}": round(s, 6)
                  for (dev, bkt), s in sorted(_compile_ledger.items())}
    return {"compile_seconds": ledger,
            "flops_per_step": int(_flops_per_step[0]),
            "train_devices": int(_train_devices[0]),
            "sampler_active": active(),
            "interval_s": float(flag("FLAGS_device_telemetry_interval_s"))}
