"""Profiler (reference `paddle/fluid/platform/profiler.h:127` RecordEvent /
`:210` EnableProfiler, CUPTI `device_tracer.h`, Python `fluid/profiler.py`).

TPU-native: RecordEvent scopes wrap host-side dispatch and annotate traces
via jax.profiler.TraceAnnotation (visible in the XLA/TPU trace); the
device side is jax.profiler (XPlane → TensorBoard). Host events land in a
thread-aware bounded trace store (`tracer.py` — per-thread rings, real
tids and thread names), so one `export_chrome_tracing` file renders the
collector, dispatch lanes, DeviceFeeder and fit loop as separate named
tracks next to the device trace, plus "C" counter tracks sampled from
`framework.monitor`. The same store feeds the crash flight recorder
(`flight_recorder.py`) and the live `/trace` endpoint
(`exporter.MetricsServer`). The reference's summary table is reproduced
from host timings via `summary()` — `stop_profiler` returns rows and
never prints.
"""
from __future__ import annotations

import contextlib
import json
import sys
import time
from collections import defaultdict
from typing import Optional

from . import tracer

__all__ = ["RecordEvent", "Profiler", "profiler", "start_profiler",
           "stop_profiler", "export_chrome_tracing", "summary"]


class _StateView:
    """Back-compat shim for the old module-global `_state`: `.events` is
    a merged snapshot of every thread's ring (the old shape —
    `(name, t0, t1)` tuples), `.enabled` the profiler session bit.
    Appending directly is gone; record through RecordEvent/tracer."""

    @property
    def enabled(self) -> bool:
        return tracer.profiler_enabled()

    @property
    def events(self):
        return tracer.events(since=tracer.session_start())


_state = _StateView()


class RecordEvent:
    """RAII scope (reference platform/profiler.h RecordEvent). Usable as a
    context manager or decorator; also emits a jax TraceAnnotation so the
    name shows up in device traces. Events are recorded into the calling
    thread's own ring with its real tid/thread name."""

    def __init__(self, name: str):
        self.name = name
        # stacks, not scalars: one RecordEvent instance may be entered
        # re-entrantly (recursive decorated function, nested `with ev:`)
        self._t0s = []
        self._jax_ctxs = []

    def __enter__(self):
        self.begin()
        return self

    def begin(self):
        self._t0s.append(time.perf_counter())
        ctx = None
        try:
            import jax.profiler
            ctx = jax.profiler.TraceAnnotation(self.name)
            ctx.__enter__()
        except Exception:
            ctx = None
        self._jax_ctxs.append(ctx)

    def end(self):
        """Close the innermost open scope. Safe to call when none is open
        (idempotent tail call), and closes the jax TraceAnnotation even if
        host-side bookkeeping raises."""
        if not self._t0s:
            return
        ctx = self._jax_ctxs.pop()
        t0 = self._t0s.pop()
        try:
            tracer.record_complete(self.name, t0, time.perf_counter())
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)

    def __exit__(self, *exc):
        # runs on the exception path too — the scope must not leak an open
        # TraceAnnotation or a dangling _t0 when the body raises
        self.end()
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)
        return wrapper


def start_profiler(state="All", tracer_option="Default"):
    tracer.enable()


def _aggregate(events):
    """[(name, t0, t1)] → rows sorted by total ms:
    (name, [calls, total_ms, min_ms, max_ms])."""
    agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    for name, t0, t1 in events:
        dt = (t1 - t0) * 1000
        a = agg[name]
        a[0] += 1
        a[1] += dt
        a[2] = min(a[2], dt)
        a[3] = max(a[3], dt)
    return sorted(agg.items(), key=lambda kv: -kv[1][1])


def summary(rows=None, sorted_key="total", file=None) -> str:
    """Format the reference profiler's event table. `rows` defaults to
    the current session's aggregation; writes to `file` when given (pass
    `sys.stdout` for the old print behavior) and returns the string."""
    if rows is None:
        rows = _aggregate(tracer.events(since=tracer.session_start()))
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Min':>10}"
             f"{'Max':>10}{'Ave':>10}"]
    for name, (calls, total, mn, mx) in rows:
        lines.append(f"{name:<40}{calls:>8}{total:>12.3f}{mn:>10.3f}"
                     f"{mx:>10.3f}{total / max(calls, 1):>10.3f}")
    text = "\n".join(lines)
    if file is not None:
        print(text, file=file)
    return text


def stop_profiler(sorted_key="total", profile_path=None, file=None):
    """End the profiling session and return the aggregated rows. Quiet by
    default (library users and pytest runs stay clean); pass
    `file=sys.stdout` — or call `summary()` — for the table."""
    events = tracer.events(since=tracer.session_start())
    tracer.sample_counters()
    tracer.disable()
    rows = _aggregate(events)
    if file is not None:
        summary(rows, sorted_key, file)
    if profile_path:
        export_chrome_tracing(profile_path)
    return rows


def export_chrome_tracing(path: str):
    """chrome://tracing json of host events: named per-thread tracks plus
    counter tracks (reference profiler chrome trace export merged with
    device_tracer-style per-stream lanes)."""
    tracer.sample_counters()  # at least one sample → counter tracks render
    since = tracer.session_start() or None
    trace = tracer.chrome_trace(since=since)
    from . import step_log
    trace["traceEvents"].extend(step_log.chrome_counter_events(since))
    with open(path, "w") as f:
        json.dump(trace, f)


@contextlib.contextmanager
def profiler(state="All", tracer_option="Default", profile_path=None,
             sorted_key="total", file=None):
    """fluid.profiler.profiler context manager. Pass `file=sys.stdout`
    to print the summary table on exit (the old unconditional print is
    gone — see `summary()`)."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path, file=file)


class Profiler:
    """paddle.profiler.Profiler 2.x-style wrapper; on TPU also drives
    jax.profiler for a device trace directory consumable by TensorBoard.

    `step()` is a real step marker: it closes a `ProfilerStep#N` scope on
    the calling thread and snapshots the monitor counters, so the chrome
    trace shows step boundaries and live counter tracks."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir: Optional[str] = None):
        self.log_dir = log_dir
        self._jax_started = False
        self._step_n = 0
        self._step_t0 = None

    def start(self):
        start_profiler()
        self._step_n = 0
        self._step_t0 = time.perf_counter()
        if self.log_dir:
            try:
                import jax.profiler
                jax.profiler.start_trace(self.log_dir)
                self._jax_started = True
            except Exception:
                pass
        return self

    def stop(self):
        if self._jax_started:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            except Exception:
                pass
        self.step()  # close the open ProfilerStep scope
        self._step_t0 = None
        stop_profiler()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def step(self):
        """Mark a train-step boundary: one `ProfilerStep#N` scope since
        the previous call plus a counter snapshot."""
        t = time.perf_counter()
        if self._step_t0 is not None:
            tracer.record_complete(f"ProfilerStep#{self._step_n}",
                                   self._step_t0, t)
            self._step_n += 1
        self._step_t0 = t
        tracer.sample_counters()

    def summary(self, sorted_key="total", file=None, **kwargs):
        return summary(sorted_key=sorted_key,
                       file=file if file is not None else sys.stdout)
