"""Profiler (reference `paddle/fluid/platform/profiler.h:127` RecordEvent /
`:210` EnableProfiler, CUPTI `device_tracer.h`, Python `fluid/profiler.py`).

TPU-native: RecordEvent scopes wrap host-side dispatch and annotate traces
via jax.profiler.TraceAnnotation (visible in the XLA/TPU trace); the
device side is jax.profiler (XPlane → TensorBoard). The reference's
summary table is reproduced from host timings.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import defaultdict
from typing import Optional

__all__ = ["RecordEvent", "Profiler", "profiler", "start_profiler",
           "stop_profiler", "export_chrome_tracing"]


class _State(threading.local):
    def __init__(self):
        self.enabled = False
        self.events = []  # (name, t0, t1)
        self.stack = []


_state = _State()


class RecordEvent:
    """RAII scope (reference platform/profiler.h RecordEvent). Usable as a
    context manager or decorator; also emits a jax TraceAnnotation so the
    name shows up in device traces."""

    def __init__(self, name: str):
        self.name = name
        # stacks, not scalars: one RecordEvent instance may be entered
        # re-entrantly (recursive decorated function, nested `with ev:`)
        self._t0s = []
        self._jax_ctxs = []

    def __enter__(self):
        self.begin()
        return self

    def begin(self):
        self._t0s.append(time.perf_counter())
        ctx = None
        try:
            import jax.profiler
            ctx = jax.profiler.TraceAnnotation(self.name)
            ctx.__enter__()
        except Exception:
            ctx = None
        self._jax_ctxs.append(ctx)

    def end(self):
        """Close the innermost open scope. Safe to call when none is open
        (idempotent tail call), and closes the jax TraceAnnotation even if
        host-side bookkeeping raises."""
        if not self._t0s:
            return
        ctx = self._jax_ctxs.pop()
        t0 = self._t0s.pop()
        try:
            if _state.enabled:
                _state.events.append((self.name, t0, time.perf_counter()))
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)

    def __exit__(self, *exc):
        # runs on the exception path too — the scope must not leak an open
        # TraceAnnotation or a dangling _t0 when the body raises
        self.end()
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)
        return wrapper


def start_profiler(state="All", tracer_option="Default"):
    _state.enabled = True
    _state.events = []


def stop_profiler(sorted_key="total", profile_path=None):
    _state.enabled = False
    agg = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    for name, t0, t1 in _state.events:
        dt = (t1 - t0) * 1000
        a = agg[name]
        a[0] += 1
        a[1] += dt
        a[2] = min(a[2], dt)
        a[3] = max(a[3], dt)
    rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
    print(f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Min':>10}"
          f"{'Max':>10}{'Ave':>10}")
    for name, (calls, total, mn, mx) in rows:
        print(f"{name:<40}{calls:>8}{total:>12.3f}{mn:>10.3f}{mx:>10.3f}"
              f"{total / max(calls, 1):>10.3f}")
    if profile_path:
        export_chrome_tracing(profile_path)
    return rows


def export_chrome_tracing(path: str):
    """chrome://tracing json of host events (reference profiler chrome
    trace export)."""
    events = []
    for name, t0, t1 in _state.events:
        events.append({"name": name, "ph": "X", "pid": 0, "tid": 0,
                       "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6})
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)


@contextlib.contextmanager
def profiler(state="All", tracer_option="Default", profile_path=None,
             sorted_key="total"):
    """fluid.profiler.profiler context manager."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class Profiler:
    """paddle.profiler.Profiler 2.x-style wrapper; on TPU also drives
    jax.profiler for a device trace directory consumable by TensorBoard."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir: Optional[str] = None):
        self.log_dir = log_dir
        self._jax_started = False

    def start(self):
        start_profiler()
        if self.log_dir:
            try:
                import jax.profiler
                jax.profiler.start_trace(self.log_dir)
                self._jax_started = True
            except Exception:
                pass
        return self

    def stop(self):
        if self._jax_started:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            except Exception:
                pass
        stop_profiler()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def step(self):
        pass

    def summary(self, **kwargs):
        pass
