"""Time-series metrics ring: continuous-in-time history for the fleet
(ISSUE 20).

`/metrics` and `/stats` are point-in-time — a scrape says the queue is
9 deep, never whether it got there over one second or one hour. Once a
Router supervises N replicas, the operator question changes shape from
"what is the value" to "what is the trend, per replica", and answering
it by polling from outside means every consumer re-implements rate
math. This module answers it in-process with one bounded sampler (the
device-telemetry lazy-thread lifecycle: `touch()`d by engines and the
`MetricsServer`, idles at interval 0, honors runtime flag flips in both
directions) that every tick records:

- every registered monitor **counter as a rate** (delta / wall seconds,
  clamped at 0 so a restart's counter reset reads as idle, not as a
  negative spike), and
- every registered **gauge as a level** (the monitor gauge registry is
  the single source of kind truth — same `is_gauge_name` table the
  Prometheus exporter renders TYPE lines from), and
- per-registered-engine `pressure()` ticks (queue depth, free pages,
  oldest queued age — the step-thread-published snapshot the router
  balances on, so sampling it is lock-free on the engine side)

into per-name rings bounded by `FLAGS_metrics_history_samples` (oldest
drop first; `FLAGS_metrics_history_interval_s` sets the cadence). The
rings serve three ways: `/history` JSON (`history_payload()` — the
input of `tools/router_report.py --history` sparklines), chrome "C"
counter tracks merged into `/trace` (`chrome_counter_events()`), and
direct `series()` reads in tests.

Locking: one module lock guards the rings and the rate anchors — the
sampler thread is the usual writer, but `sample()` is also callable
from tests and scrape paths, and a `/history` read racing an engine
`_die()` must see a consistent ring, so everything mutating or copying
ring state takes the lock. Engine `pressure()` reads are GIL-atomic
snapshot reads by design and take no engine-side lock.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..framework import monitor
from ..framework.flags import flag

__all__ = ["touch", "active", "sample", "series", "history_payload",
           "chrome_counter_events", "clear"]

_lock = threading.Lock()
_sampler = [None]             # lazy daemon thread, one per process
_series: Dict[str, dict] = {}  # name -> {"kind", "points": deque}
_prev = {}                    # counter name -> (t, value) rate anchor


def active() -> bool:
    """True while history is wanted AND enabled: some subsystem has
    touch()ed the sampler and the interval flag is currently positive
    (same contract as device_telemetry.active)."""
    return (_sampler[0] is not None
            and float(flag("FLAGS_metrics_history_interval_s")) > 0)


def touch() -> None:
    """Start the sampler thread (idempotent, lazy). Starts even while
    the interval flag is 0 — it idles cheaply and honors a later
    runtime set_flags(interval>0) instead of being permanently
    unenableable because the flag happened to be 0 at touch() time."""
    with _lock:
        if _sampler[0] is None:
            t = threading.Thread(target=_sampler_loop, daemon=True,
                                 name="paddle_tpu-metrics-history")
            _sampler[0] = t
            t.start()


def _sampler_loop():
    while True:
        iv = float(flag("FLAGS_metrics_history_interval_s"))
        time.sleep(max(iv, 0.5) if iv > 0 else 5.0)
        if iv > 0:
            try:
                sample()
            except Exception:
                pass


def _cap() -> int:
    return max(1, int(flag("FLAGS_metrics_history_samples")))


def _record_locked(name: str, kind: str, t: float, value) -> None:
    s = _series.get(name)
    if s is None:
        s = _series[name] = {"kind": kind, "points": deque()}
    s["points"].append((t, value))
    cap = _cap()
    while len(s["points"]) > cap:
        s["points"].popleft()


def sample() -> int:
    """Take one history tick across every registered stat and engine;
    returns the number of series updated. Safe from any thread."""
    t = time.perf_counter()
    snap = monitor.all_stats()
    # engine pressure ticks OUTSIDE the module lock: pressure() is a
    # lock-free snapshot read, but a misbehaving engine property must
    # not be able to deadlock against a concurrent /history render
    pressures = {}
    from . import exporter
    for name, eng in exporter.live_engines().items():
        try:
            p = getattr(eng, "pressure", None)
            p = p() if callable(p) else None
        except Exception:
            p = None
        if isinstance(p, dict):
            pressures[name] = p
    n = 0
    with _lock:
        for name, v in snap.items():
            if monitor.is_gauge_name(name):
                _record_locked(name, "level", t, v)
            else:
                prev = _prev.get(name)
                _prev[name] = (t, v)
                if prev is None or t <= prev[0]:
                    continue
                rate = max(0.0, (v - prev[1]) / (t - prev[0]))
                _record_locked(name, "rate", t, round(rate, 6))
            n += 1
        for ename, p in pressures.items():
            for field in ("queue_depth", "live", "free_pages",
                          "oldest_age_ms"):
                if field in p:
                    _record_locked(f"pressure:{ename}:{field}",
                                   "level", t, p[field])
                    n += 1
    return n


def series(name: str) -> List[tuple]:
    """One series' points as a list copy (tests)."""
    with _lock:
        s = _series.get(name)
        return list(s["points"]) if s else []


def history_payload() -> dict:
    """The `/history` JSON: every series with its kind and bounded
    points — `{"series": {name: {"kind": "rate"|"level",
    "points": [[t, v], ...]}}}` (t = perf_counter seconds, the same
    clock every trace event uses, so histories and timelines align)."""
    with _lock:
        out = {name: {"kind": s["kind"],
                      "points": [[round(t, 3), v]
                                 for t, v in s["points"]]}
               for name, s in sorted(_series.items())}
    return {"enabled": active(),
            "interval_s": float(flag("FLAGS_metrics_history_interval_s")),
            "samples": _cap(),
            "series": out}


def chrome_counter_events(since: Optional[float] = None,
                          pid: Optional[int] = None) -> List[dict]:
    """History rings as chrome-trace "C" counter events, one track per
    series that is ever nonzero in the window (all-zero tracks are
    noise, not signal) — merged into `/trace` under the request
    timeline next to the step-ring scheduler tracks."""
    import os
    pid = os.getpid() if pid is None else pid
    with _lock:
        items = [(name, s["kind"], list(s["points"]))
                 for name, s in sorted(_series.items())]
    out = []
    for name, kind, pts in items:
        if not any(v for _, v in pts):
            continue
        for t, v in pts:
            if since is not None and t < since:
                continue
            out.append({"name": f"history:{name}", "ph": "C",
                        "pid": pid, "tid": 0, "ts": t * 1e6,
                        "args": {kind: v}})
    return out


def clear() -> None:
    """Drop every series and rate anchor (tests)."""
    with _lock:
        _series.clear()
        _prev.clear()
