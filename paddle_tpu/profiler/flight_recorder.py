"""Crash flight recorder: always-on bounded postmortem context.

Black-box-recorder pattern: the per-thread trace rings (`tracer.py`)
keep recording the last `FLAGS_trace_ring_size` events per thread even
with the profiler stopped (gated by `FLAGS_flight_recorder`, default
on), and a lazy background sampler snapshots the monitor counters every
`FLAGS_flight_recorder_interval_s`. When one of the hardened failure
paths fires —

- serving lane death (`serving/engine.py` `_Lane._die`)
- poisoned-batch retry (`_complete_unit` isolation rerun)
- poisoned donated carry (`hapi/model.py` `_sync_carry` /
  `_sync_sharded_carry` validate-drop)
- DataLoader worker crash (`io/dataloader.py` multiprocess iter)

— `dump(reason, extra)` writes one JSON artifact with the tail of the
merged event timeline (real tids + thread names), the counter-sample
history, and a final consistent counter/histogram snapshot, so the
exception the caller sees comes with the seconds of runtime context
that led up to it. `dump` never raises (it sits on failure paths) and
prunes itself to `FLAGS_flight_recorder_max_dumps` files per process.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
from typing import Optional

from ..framework.flags import flag
from . import tracer

__all__ = ["enabled", "dump", "touch", "dump_dir", "last_dumps",
           "dump_records"]

_lock = threading.Lock()
_dumps = []            # {"path","reason","wall_time"} records, oldest first
_seq = [0]
_sampler = [None]      # the lazy background counter-sampler thread


def enabled() -> bool:
    return bool(flag("FLAGS_flight_recorder"))


def dump_dir() -> str:
    d = str(flag("FLAGS_flight_recorder_dir")).strip()
    if not d:
        d = os.path.join(tempfile.gettempdir(), "paddle_tpu_flightrec")
    return d


def last_dumps():
    """Paths of the dumps written by this process, oldest first."""
    with _lock:
        return [r["path"] for r in _dumps]


def dump_records():
    """`{path, reason, wall_time}` summaries of this process's dumps,
    oldest first — the `/stats` postmortem index, so an operator sees
    recent failures without filesystem access."""
    with _lock:
        return [dict(r) for r in _dumps]


def _sampler_loop():
    while True:
        iv = float(flag("FLAGS_flight_recorder_interval_s"))
        time.sleep(max(iv, 0.25) if iv > 0 else 5.0)
        if enabled() and iv > 0:
            try:
                tracer.sample_counters()
            except Exception:
                pass


def touch() -> None:
    """Start the periodic counter sampler (idempotent, lazy). Called by
    the long-running subsystems the recorder covers — serving engines,
    `Model.fit`, the multiprocess DataLoader — so a process that never
    uses them never pays for the thread."""
    if not enabled() or float(flag("FLAGS_flight_recorder_interval_s")) <= 0:
        return
    with _lock:
        if _sampler[0] is None:
            t = threading.Thread(target=_sampler_loop, daemon=True,
                                 name="paddle_tpu-flightrec-sampler")
            _sampler[0] = t
            t.start()


def dump(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Write one postmortem artifact; returns its path (None when the
    recorder is off or the write failed — this sits on failure paths and
    must never raise over the exception it documents)."""
    if not enabled():
        return None
    try:
        from ..framework import monitor
        tracer.instant(f"flightrec::{reason}")
        # bounded tail, not a full-store merge: this runs inline on
        # failure paths (e.g. between a poisoned batch and its
        # per-request reruns), so co-rider requests must not wait on a
        # sort of every ring
        evs = tracer.tail_events(int(flag("FLAGS_flight_recorder_events")))
        record = {
            "reason": reason,
            "wall_time": time.time(),
            "perf_time": time.perf_counter(),
            "pid": os.getpid(),
            "thread": threading.current_thread().name,
            "extra": extra or {},
            "stats": monitor.all_stats(),
            "histograms": monitor.all_histograms(),
            "counter_samples": [
                {"t": t, "stats": snap}
                for t, snap in tracer.counter_samples()[-64:]],
            "ring": tracer.ring_stats(),
            "events": [
                {"name": name, "ph": ph, "ts_us": t0 * 1e6,
                 "dur_us": (t1 - t0) * 1e6, "tid": track,
                 "os_tid": os_tid, "thread": tname}
                for name, ph, t0, t1, track, os_tid, tname in evs],
        }
        d = dump_dir()
        os.makedirs(d, exist_ok=True)
        with _lock:
            _seq[0] += 1
            path = os.path.join(
                d, f"flightrec-{os.getpid()}-{_seq[0]:03d}-{reason}.json")
            with open(path, "w") as f:
                json.dump(record, f, default=str)
            _dumps.append({"path": path, "reason": reason,
                           "wall_time": record["wall_time"]})
            keep = max(1, int(flag("FLAGS_flight_recorder_max_dumps")))
            while len(_dumps) > keep:
                old = _dumps.pop(0)
                try:
                    os.remove(old["path"])
                except OSError:
                    pass
        monitor.stat_add("STAT_flight_recorder_dumps")
        sys.stderr.write(f"[paddle_tpu] flight recorder: {reason} -> "
                         f"{path}\n")
        return path
    except Exception:
        return None
