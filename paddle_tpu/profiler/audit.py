"""Structured decision audit log for the generation scheduler.

The step ring (`step_log.py`) says WHAT the scheduler's state was each
iteration; this log says WHY each request moved — every
admit/defer/evict/expire/poison decision appends one reason-coded event
to a bounded per-engine ring, so a postmortem answers "why did this
request wait/die" from the engine's own words instead of inference over
counters.

Reason codes are a CLOSED set (`REASONS` below): `AuditLog.audit`
rejects an unknown code, and the `audit-reasons` lint pass
(`python tools/lint.py`) keeps the emitted codes and the documented
reason table in COVERAGE.md's "Audit reason codes" section in lockstep
both ways — the same bidirectional contract stats-doc enforces for
metric names.

Storage: a `collections.deque(maxlen=...)` per engine (appends are
atomic under the GIL, so the submit thread's REJECT_QUEUE_FULL events
interleave safely with the step thread's decisions), plus an optional
JSONL sink (`FLAGS_gen_audit_log` = path; '' keeps the ring only). The
sink write sits on scheduler paths and therefore never raises. The tail
rides flight-recorder dumps (`gen_engine_death`, poison, exhaustion)
and the `/steps` payload.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..framework import monitor
from ..framework.errors import InvalidArgumentError
from ..framework.flags import flag
from ._engine_registry import EngineRegistry

__all__ = ["REASONS", "AuditLog", "tail_for"]

# The closed reason-code vocabulary. Every code the engine emits MUST be
# here AND in COVERAGE.md's "Audit reason codes" table (audit-reasons
# lint). Codes are past-tense facts about one request.
REASONS = frozenset({
    "ADMIT",               # request took a slot + worst-case pages
    "ADMIT_PREFIX_HIT",    # admit whose prompt prefix mapped cached
                           # pages read-only; only the tail prefilled
    "COW_SPLIT",           # shared page split private before the one
                           # divergent write (full-prompt match)
    "EVICT_PREFIX_LRU",    # refcount-0 cached chain pages reclaimed
                           # LRU, before an admission's alloc
    "EVICT_PREFIX_BUDGET",  # cached chains evicted eagerly at
                            # register() to hold the page-count budget
                            # (FLAGS_gen_prefix_cache_max_pages)
    "DEFER_PAGES",         # admission deferred: free pages < worst case
    "DEFER_SLOTS",         # admission deferred: every decode slot busy
    "REJECT_QUEUE_FULL",   # submit shed by EngineOverloaded backpressure
    "EXPIRE_QUEUED",       # deadline passed while waiting in the queue
    "EXPIRE_DECODE",       # deadline passed mid-decode; sequence evicted
    "EXPIRE_LATE",         # finished the same instant it expired —
                           # delivered as a timeout, not a completion
    "COMPLETE_EOS",        # finished on the eos token
    "COMPLETE_MAX_NEW",    # finished by exhausting max_new_tokens
    "POISON_PREFILL",      # non-finite prefill logits; request isolated
    "POISON_DECODE",       # non-finite decode logits; sequence isolated
    "CANCELLED",           # future cancelled before the request ran
    "EVICT_SHUTDOWN",      # live sequence evicted by shutdown/abort
    "EVICT_SHUTDOWN_QUEUED",  # queued (never admitted) request dropped
                              # by shutdown(drain=False)
    "ENGINE_DIED",         # stranded by engine death (step-loop error)
    "ENGINE_RESTART",      # supervisor rebuilt the engine after a death
                           # (ISSUE 15; detail: incarnation, backoff)
    "REPLAY_ADMIT",        # crash-manifest request re-enqueued on the
                           # rebuilt engine (continuation or scratch)
    "RETRY_EXHAUSTED",     # request failed typed: its replay budget
                           # (FLAGS_gen_retry_limit) ran out
    "REPLAY_IMPOSSIBLE",   # request failed typed: no exactly-once
                           # replay exists (sampled stream whose
                           # continuation exceeds the prefill buckets)
                           # — no retry-limit tuning can fix this
    "BREAKER_OPEN",        # crash-storm circuit breaker opened — the
                           # supervisor stays down (/readyz 503)
    "DEGRADED_SPEC_OFF",   # poison storm flipped speculation off for
                           # this engine (FLAGS_gen_poison_degrade_k)
    "DEGRADED_ADMIT_CLAMP",  # repeated allocator exhaustion clamped
                             # admission: uncoverable submits now fail
                             # fast (FLAGS_gen_exhaust_clamp_k)
    "ROUTE_AFFINITY",      # router placed the request on the replica
                           # whose sketch held its longest prompt
                           # prefix chain (ISSUE 17; detail: replica,
                           # matched_pages)
    "ROUTE_LEAST_PRESSURE",  # no replica held the prefix (or affinity
                             # off/tied): placed by best headroom /
                             # shortest queue / youngest head
    "ROUTE_DRAIN",         # replica left (or re-entered) the placement
                           # set: SLO burn / breaker-open / not-ready
                           # — live streams on it finish untouched
    "ROUTE_REROUTE",       # placement failed typed on the chosen
                           # replica (breaker/shutdown/overload); the
                           # router retried the next-best replica
    "KV_DEMOTE",           # prefix-cache eviction demoted a chain
                           # page's content to the host tier instead of
                           # discarding it (ISSUE 18; detail: pages)
    "KV_PROMOTE",          # admission re-uploaded a host-tier chain
                           # run to HBM, overlapped with the tail
                           # prefill (detail: pages, tokens)
    "KV_TIER_EVICT",       # host-tier entries finally dropped — LRU
                           # byte-budget pressure or a cascade drop of
                           # orphaned descendants (demote-of-demoted =
                           # final eviction; detail: entries)
    "KV_PROMOTE_ABANDON",  # promotion abandoned mid-upload (fault /
                           # request expiry): written target pages
                           # zeroed, admission fell back to cold
                           # prefill — no leak on either tier
})

_CAP = 2048   # per-engine ring bound (≈ a few minutes of decisions)


class AuditLog:
    """One engine's bounded decision ring + optional JSONL sink."""

    def __init__(self, engine: str, capacity: int = _CAP):
        self.engine = engine
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._sink_lock = threading.Lock()
        self._sink_path = None   # open JSONL handle, kept across events
        self._sink = None
        # events awaiting their JSONL write: audit() runs on scheduler
        # paths (often under the engine lock), so disk I/O is deferred
        # to flush_sink() on a caller that can afford it; bounded so a
        # flush that never comes can't grow without bound (the sink is
        # best-effort — the ring is the source of truth)
        self._pending: deque = deque(maxlen=16384)
        self._count_lock = threading.Lock()
        self.recorded = 0        # total events ever appended
        _register(self)

    def audit(self, reason: str, rid: Optional[int] = None, **detail):
        """Append one reason-coded decision event. `reason` must be a
        registered code — an unknown code is a programming bug surfaced
        immediately (tests), not a silently-invented vocabulary."""
        if reason not in REASONS:
            raise InvalidArgumentError(
                f"unknown audit reason code {reason!r}; registered: "
                f"{sorted(REASONS)} (add new codes to profiler/audit.py "
                f"REASONS and the COVERAGE.md reason table)")
        ev = {"t": time.time(), "engine": self.engine, "reason": reason,
              "rid": rid}
        if detail:
            ev.update(detail)
        self._ring.append(ev)
        with self._count_lock:
            # audit() runs on the step thread AND on submit threads
            # (REJECT_QUEUE_FULL) — an unlocked += loses increments
            self.recorded += 1
        monitor.stat_add("STAT_gen_audit_events")
        path = str(flag("FLAGS_gen_audit_log")).strip()
        if path or self._sink is not None:
            # no I/O here: audit sites often hold the engine lock, and
            # a disk flush under it would stall every submit() caller
            self._pending.append(ev)
        return ev

    def flush_sink(self) -> None:
        """Write every pending event to the JSONL sink (never raises).
        Called OUTSIDE any engine lock: once per iteration by the step
        loop, by a rejecting submit() (the rejecting client pays for
        its own event, not the step thread), and by close()."""
        if not self._pending:
            return
        try:  # the sink is best-effort — never raise
            path = str(flag("FLAGS_gen_audit_log")).strip()
            with self._sink_lock:
                if path != self._sink_path:
                    # flag changed at runtime: swap the handle
                    if self._sink is not None:
                        self._sink.close()
                    self._sink = open(path, "a") if path else None
                    self._sink_path = path or None
                if self._sink is None:
                    self._pending.clear()
                    return
                wrote = False
                while self._pending:
                    ev = self._pending.popleft()
                    self._sink.write(json.dumps(ev, default=str) + "\n")
                    wrote = True
                if wrote:
                    # one flush per batch — the handle stays open (an
                    # open/close or flush per decision would put disk
                    # latency on the scheduler path)
                    self._sink.flush()
        except Exception:
            pass

    def tail(self, n: int = 256) -> List[dict]:
        """Last `n` events, oldest-first (GIL-consistent copy)."""
        evs = list(self._ring)
        return [dict(e) for e in evs[-max(0, int(n)):]]

    def close(self) -> None:
        """Drop the registry entry and release the sink handle (engine
        shutdown; the in-memory ring stays readable)."""
        unregister(self)
        self.flush_sink()
        with self._sink_lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except Exception:
                    pass
                self._sink = None
                self._sink_path = None


# -- registry (flight dumps + /steps read audit tails by engine name) -------

_logs = EngineRegistry()


def _register(log: AuditLog) -> None:
    _logs.register(log.engine, log)


def unregister(log: AuditLog) -> None:
    _logs.unregister(log.engine, log)


def tail_for(engine: str, n: int = 256) -> List[dict]:
    log = _logs.get(engine)
    return log.tail(n) if log is not None else []
