"""Per-step scheduler timeline for the generation engine ("scheduler
X-ray", ISSUE 11).

PR 7's spans explain ONE request's latency; nothing explained the
*scheduler's* behavior between requests — which iteration admitted or
evicted whom, how deep the queue ran, how close the page pool was to
exhaustion. The step thread records one compact `StepRecord` per engine
iteration into a bounded per-engine ring (`FLAGS_gen_step_log_size`,
oldest overwritten — the same bounding discipline as the trace rings):

    it            iteration ordinal (monotone per engine)
    step          decode-step total AFTER the iteration (unchanged when
                  the iteration only admitted/expired)
    live          occupied decode slots after the iteration
    admitted / completed / expired / poisoned / aborted / freed
                  scheduler decisions taken THIS iteration (freed =
                  slots released; completed+expired+poisoned+aborted
                  partition the request outcomes, so the ring's sums
                  reconcile exactly with STAT_gen_completions /
                  STAT_gen_timeouts / STAT_gen_poisoned)
    queue_depth / oldest_age_ms
                  intake pressure after the iteration (FIFO → the head
                  is the oldest)
    pages_in_use / free_pages
                  page-pool occupancy after the iteration
    prefix_tokens / cow_splits
                  prompt tokens served from cached prefix pages and
                  copy-on-write page splits performed THIS iteration
                  (ISSUE 12 — the prefix-cache effectiveness signal,
                  per iteration)
    tokens / spec_drafted / spec_accepted / prefill_chunks
                  tokens delivered THIS iteration (prefill first
                  tokens + decode/verify), speculative draft tokens
                  proposed and accepted, and prefill chunks run
                  (ISSUE 14 — tokens > live on a decode iteration is
                  speculation paying off; prefill_chunks interleaved
                  with decode_ms > 0 is chunked prefill protecting
                  TPOT). Appended AFTER the ISSUE-12 fields so older
                  ring consumers — which read by name with defaults —
                  parse records from both eras unchanged
    prefill_ms / decode_ms
                  wall spent in prefill jit calls vs the decode step
                  this iteration — the "is one long prompt spiking
                  everyone's TPOT" signal
    tier_demotions / tier_promotions
                  prefix-cache pages demoted to / promoted back from
                  the host-RAM tier THIS iteration (ISSUE 18 — the
                  cross-tier traffic signal)
    attr_admit_ms / attr_promote_ms / attr_bookkeep_ms / attr_idle_ms /
    attr_wall_ms  per-iteration goodput attribution (ISSUE 20): with
                  prefill_ms and decode_ms these six buckets tile the
                  step thread's mark-to-mark wall EXACTLY (bookkeeping
                  is the remainder of the rounded siblings), feeding
                  the STAT_gen_step_attr_* histogram family

The ring is exported three ways: `/steps` JSON
(`steps_payload()` — per-engine records + audit-log tail, the input of
`tools/engine_report.py`), chrome-trace counter tracks
(`chrome_counter_events()` merged into `/trace` and
`export_chrome_tracing`, so the scheduler state renders as "C" series
under the request timeline), and two histograms — `engine_step_ms`
(decode-step wall) and `gen_queue_age_ms` (oldest queued request's age,
observed every iteration the queue is non-empty).

Recording is single-writer (the engine's step thread owns every
append); readers take GIL-consistent list copies like the tracer rings.
Everything is gated by `FLAGS_gen_step_log` (default on; `bench.py
--mode generation` A/Bs the flag and gates the overhead <2%).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..framework import monitor
from ..framework.flags import flag
from ._engine_registry import EngineRegistry

__all__ = ["StepRecord", "StepLog", "enabled", "register", "unregister",
           "steps_payload", "chrome_counter_events"]

_FIELDS = ("it", "step", "t", "live", "admitted", "completed", "expired",
           "poisoned", "aborted", "freed", "queue_depth", "oldest_age_ms",
           "pages_in_use", "free_pages", "prefix_tokens", "cow_splits",
           "prefill_ms", "decode_ms", "tokens", "spec_drafted",
           "spec_accepted", "prefill_chunks",
           # ISSUE 15: which engine GENERATION (supervised-restart
           # ordinal) recorded this iteration — appended after the
           # older fields so ring consumers reading by name with
           # defaults parse records from every era unchanged
           "incarnation",
           # ISSUE 18: prefix-cache pages demoted to / promoted from
           # the host tier THIS iteration (same era-compat appending)
           "tier_demotions", "tier_promotions",
           # ISSUE 19: the engine's tensor-parallel degree (mesh-slice
           # width; 1 = single-chip lane) — constant per incarnation,
           # recorded so mixed-fleet step rings are self-describing
           "tp",
           # ISSUE 20: per-iteration goodput attribution. Six buckets —
           # attr_admit_ms (scheduler work net of nested device calls),
           # prefill_ms (above), attr_promote_ms (tier re-upload),
           # decode_ms (above), attr_bookkeep_ms (host bookkeeping:
           # record/flush/slice — computed as the remainder of the
           # ROUNDED siblings, so the stored buckets sum EXACTLY to
           # attr_wall_ms), attr_idle_ms (cv waits) — tile the step
           # thread's mark-to-mark iteration wall. attr_wall_ms == 0
           # marks a record from before this era (or the abort-path
           # flush record, which never owned a full iteration)
           "attr_admit_ms", "attr_promote_ms", "attr_bookkeep_ms",
           "attr_idle_ms", "attr_wall_ms")


def enabled() -> bool:
    return bool(flag("FLAGS_gen_step_log"))


class StepRecord:
    """One engine iteration's scheduler state (compact: slots only)."""

    __slots__ = _FIELDS

    def __init__(self, **kw):
        for f in _FIELDS:
            setattr(self, f, kw.get(f, 0))

    def to_dict(self) -> dict:
        return {f: getattr(self, f) for f in _FIELDS}


_hists_lock = threading.Lock()
_hists = None
_attr_hists = None


def _step_hists():
    global _hists
    if _hists is None:
        with _hists_lock:
            if _hists is None:
                # literal names: the check_stats lint reads these
                _hists = (monitor.histogram("engine_step_ms"),
                          monitor.histogram("gen_queue_age_ms"))
    return _hists


def _step_attr_hists():
    global _attr_hists
    if _attr_hists is None:
        with _hists_lock:
            if _attr_hists is None:
                # literal names: the check_stats lint reads these
                _attr_hists = (
                    monitor.histogram("STAT_gen_step_attr_admit_ms"),
                    monitor.histogram("STAT_gen_step_attr_prefill_ms"),
                    monitor.histogram("STAT_gen_step_attr_promote_ms"),
                    monitor.histogram("STAT_gen_step_attr_decode_ms"),
                    monitor.histogram("STAT_gen_step_attr_bookkeep_ms"),
                    monitor.histogram("STAT_gen_step_attr_idle_ms"))
    return _attr_hists


class StepLog:
    """One engine's bounded step ring. The owning step thread is the
    only writer; `snapshot()`/`tail()` are GIL-consistent copies."""

    def __init__(self, engine: str, capacity: Optional[int] = None):
        self.engine = engine
        self.cap = max(1, int(flag("FLAGS_gen_step_log_size")
                              if capacity is None else capacity))
        self._buf: List[StepRecord] = []
        self._idx = 0           # oldest slot once full
        self.recorded = 0       # total records ever appended
        register(self)

    def record(self, rec: StepRecord) -> None:
        """Append one iteration record (step thread only) and feed the
        step/queue-age histograms. One list append + two histogram
        observes — nothing here syncs the device."""
        step_h, age_h = _step_hists()
        if rec.decode_ms > 0:
            step_h.observe(rec.decode_ms)
        if rec.queue_depth:
            age_h.observe(max(0.0, rec.oldest_age_ms))
        if rec.attr_wall_ms > 0:
            # goodput attribution (ISSUE 20): one observe per bucket
            # per iteration — "where did this replica's ms go" as a
            # fleet-scrapeable histogram family
            for h, v in zip(_step_attr_hists(),
                            (rec.attr_admit_ms, rec.prefill_ms,
                             rec.attr_promote_ms, rec.decode_ms,
                             rec.attr_bookkeep_ms, rec.attr_idle_ms)):
                h.observe(max(0.0, v))
        if len(self._buf) < self.cap:
            self._buf.append(rec)
        else:
            self._buf[self._idx] = rec
            self._idx = (self._idx + 1) % self.cap
        self.recorded += 1

    def snapshot(self) -> List[StepRecord]:
        buf = list(self._buf)   # one GIL-atomic copy — consistent
        if len(buf) < self.cap:
            return buf
        # _idx may be stale relative to the copy (the step thread can
        # record() between the copy and the read), which would rotate
        # the true oldest record to the newest position — rotate on the
        # records' own monotone iteration counter instead
        lo = min(range(len(buf)), key=lambda i: buf[i].it)
        return buf[lo:] + buf[:lo] if lo else buf

    def tail(self, n: int) -> List[dict]:
        """Last `n` records as dicts, oldest-first (flight dumps,
        `/steps`)."""
        return [r.to_dict() for r in self.snapshot()[-max(0, int(n)):]]


# -- registry (the `/steps` surface) ----------------------------------------

_logs = EngineRegistry()


def register(log: StepLog) -> None:
    _logs.register(log.engine, log)


def unregister(log: StepLog) -> None:
    _logs.unregister(log.engine, log)


def _live_logs() -> Dict[str, StepLog]:
    return _logs.live()


def steps_payload(last: int = 0, audit_tail: int = 256) -> dict:
    """The `/steps` JSON: per-engine iteration records (all retained, or
    the last `last`) + the engine's decision-audit tail + the two step
    histograms — everything `tools/engine_report.py` needs to render a
    human timeline."""
    from . import audit
    step_h, age_h = _step_hists()
    engines = {}
    for name, log in sorted(_live_logs().items()):
        recs = [r.to_dict() for r in log.snapshot()]
        if last > 0:
            recs = recs[-last:]
        engines[name] = {
            "records": recs,
            "recorded_total": log.recorded,
            "ring_capacity": log.cap,
            "audit": audit.tail_for(name, audit_tail),
        }
    return {"enabled": enabled(),
            "engines": engines,
            "histograms": {"engine_step_ms": step_h.snapshot(),
                           "gen_queue_age_ms": age_h.snapshot()}}


def chrome_counter_events(since: Optional[float] = None,
                          pid: Optional[int] = None) -> List[dict]:
    """Step-ring records as chrome-trace "C" counter events — one event
    per record carrying the scheduler's live/queue/pages series, so the
    timeline shows slot occupancy and pool pressure UNDER the request
    scopes. Merged into `/trace` and `export_chrome_tracing`."""
    import os
    pid = os.getpid() if pid is None else pid
    out = []
    for name, log in sorted(_live_logs().items()):
        for r in log.snapshot():
            if since is not None and r.t < since:
                continue
            out.append({"name": f"{name} scheduler", "ph": "C",
                        "pid": pid, "tid": 0, "ts": r.t * 1e6,
                        "args": {"live_slots": r.live,
                                 "queue_depth": r.queue_depth,
                                 "pages_in_use": r.pages_in_use,
                                 "free_pages": r.free_pages}})
    return out
