"""@to_static + jit.save/load (reference `fluid/dygraph/jit.py:160,507,787`,
`dygraph_to_static/program_translator.py`).

TPU-native: "static graph" == XLA computation. to_static(fn) traces the
Python forward with jax (no AST transpiler — the same traced-once contract),
caches one compiled forward per input signature, and a compiled
recompute-backward twin so `loss.backward()` works through it (whole-program
rematerialization: the standard TPU memory/compute trade). jit.save
serializes weights + a StableHLO export (`jax.export`) — the serving
artifact a predictor can load without Python model code.
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Optional

import jax
import jax.export
import jax.numpy as jnp
import numpy as np

from ..framework import random as frandom
from ..framework.autograd import TapeNode, is_grad_enabled
from ..framework.functional import functionalize, get_buffers, get_params
from ..framework.tensor import Tensor

__all__ = ["to_static", "declarative", "save", "load", "TranslatedLayer",
           "not_to_static", "ProgramTranslator", "enable_to_static",
           "dy2static", "serialize_compiled", "deserialize_compiled",
           "compiled_alias_spec", "pytree_spec", "key_material_digest"]

from .dy2static import ProgramTranslator, ast_transform, enable_to_static


def _split_tensors(args, kwargs):
    leaves, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    t_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    arrays = [leaves[i]._value for i in t_idx]
    statics = [None if isinstance(l, Tensor) else l for l in leaves]
    return treedef, t_idx, arrays, statics


class StaticFunction:
    """reference `program_translator.py:233`."""

    def __init__(self, function: Callable, input_spec=None):
        self._function = function
        self._input_spec = input_spec
        self._layer = None
        obj = getattr(function, "__self__", None)
        from ..nn.layer.layers import Layer
        if isinstance(obj, Layer):
            self._layer = obj
        elif isinstance(function, Layer):
            self._layer = function
            self._function = function.forward
        # dygraph_to_static AST pass: data-dependent python control flow
        # becomes lax.cond/while_loop (reference ast_transformer.py)
        self._function = ast_transform(self._function)
        self._apply_fn = None
        self._fwd_cache: Dict[Any, Callable] = {}
        self._bwd_cache: Dict[Any, Callable] = {}
        # descriptor support: to_static on an unbound method
        self._bound_cache = {}

    def __get__(self, instance, owner):
        if instance is None:
            return self
        key = id(instance)
        if key not in self._bound_cache:
            bound = StaticFunction(self._function.__get__(instance, owner),
                                   self._input_spec)
            self._bound_cache[key] = bound
        return self._bound_cache[key]

    def _get_apply(self):
        if self._apply_fn is None:
            if self._layer is not None:
                self._apply_fn, _, _ = functionalize(self._layer,
                                                     self._function)
            else:
                fn = self._function

                def apply_fn(pv, bv, rng, training, *args, **kwargs):
                    from ..framework.autograd import trace_mode
                    from ..framework.functional import tree_unwrap, tree_wrap
                    from ..framework.random import rng_scope
                    with trace_mode(), rng_scope(rng):
                        out = fn(*tree_wrap(args), **tree_wrap(kwargs))
                        return tree_unwrap(out), bv
                self._apply_fn = apply_fn
        return self._apply_fn

    @property
    def parameters(self):
        return (get_params(self._layer) if self._layer is not None
                else {})

    def __call__(self, *args, **kwargs):
        apply_fn = self._get_apply()
        layer = self._layer
        params = get_params(layer) if layer is not None else {}
        buffers = get_buffers(layer) if layer is not None else {}
        pv = {n: t._value for n, t in params.items()}
        bv = {n: t._value for n, t in buffers.items()}
        training = bool(layer.training) if layer is not None else True
        treedef, t_idx, arrays, statics = _split_tensors(args, kwargs)

        def recon(arrs):
            ls = list(statics)
            for i, a in zip(t_idx, arrs):
                ls[i] = a
            return jax.tree_util.tree_unflatten(treedef, ls)

        key = (str(treedef), tuple(statics[i] is None for i in range(len(statics))),
               tuple((a.shape, str(a.dtype)) for a in arrays), training,
               tuple(repr(s) for s in statics))
        rng = frandom.get_rng_key()

        need_grad = is_grad_enabled() and (
            any(not p.stop_gradient for p in params.values())
            or any(isinstance(l, Tensor) and not l.stop_gradient
                   for l in jax.tree_util.tree_leaves(
                       (args, kwargs))))

        def run(pv_, rng_, *arrs):
            a2, k2 = recon(arrs)
            return apply_fn(pv_, bv, rng_, training, *a2, **k2)

        if not need_grad:
            fwd = self._fwd_cache.get(key)
            if fwd is None:
                fwd = jax.jit(run)
                self._fwd_cache[key] = fwd
            out_raw, new_bufs = fwd(pv, rng, *arrays)
            self._write_buffers(buffers, new_bufs)
            return jax.tree_util.tree_map(
                lambda x: Tensor(x), out_raw)

        # train path: compiled forward + compiled recompute-backward
        fwd = self._fwd_cache.get(key)
        if fwd is None:
            fwd = jax.jit(run)
            self._fwd_cache[key] = fwd
        out_raw, new_bufs = fwd(pv, rng, *arrays)
        self._write_buffers(buffers, new_bufs)

        out_leaves, out_tree = jax.tree_util.tree_flatten(out_raw)

        bwd = self._bwd_cache.get(key)
        if bwd is None:
            def bwd_fn(pv_, rng_, arrs, cots):
                def fwd_only(pv2, *xs):
                    o, _ = run(pv2, rng_, *xs)
                    return jax.tree_util.tree_leaves(o)
                _, vjp = jax.vjp(fwd_only, pv_, *arrs)
                return vjp(list(cots))
            bwd = jax.jit(bwd_fn)
            self._bwd_cache[key] = bwd

        param_list = list(params.values())
        in_tensors = [l for l in jax.tree_util.tree_leaves((args, kwargs))
                      if isinstance(l, Tensor)]
        diff_inputs = param_list + in_tensors
        npar = len(param_list)
        pnames = list(params.keys())

        def vjp_like(cots):
            cots = cots if isinstance(cots, tuple) else (cots,)
            grads = bwd(pv, rng, tuple(arrays), tuple(cots))
            pgrad_dict = grads[0]
            flat = [pgrad_dict[n] for n in pnames] + list(grads[1:])
            return flat

        out_tensors = [Tensor(x, stop_gradient=False) for x in out_leaves]
        node = TapeNode("to_static", vjp_like, diff_inputs, out_tensors)
        for t in out_tensors:
            t._node = node
        return jax.tree_util.tree_unflatten(out_tree, out_tensors)

    @staticmethod
    def _write_buffers(buffers, new_bufs):
        for n, t in buffers.items():
            t._value = new_bufs[n]

    def concrete_program(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None):
    def decorate(fn):
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec)
            return fn
        return StaticFunction(fn, input_spec)
    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn


# ---------------------------------------------------------------------------
# save / load: weights + StableHLO export
# ---------------------------------------------------------------------------

def _spec_to_sds(spec, scope=None, idx=0):
    """InputSpec → ShapeDtypeStruct. With a SymbolicScope, None/-1 dims
    become symbolic: dim 0 is the shared batch symbol "b" (every input's
    leading dim covaries — the serving-engine contract), other dynamic
    dims get a per-input name ("in<idx>_d<axis>") so unrelated inputs are
    NOT constrained equal. The StableHLO export is then shape-polymorphic:
    one artifact serves any batch size and `serving.InferenceEngine`
    compiles once per bucket instead of failing on every batch ≠ 1.
    Without a scope they collapse to 1 (the pre-polymorphism behavior,
    kept as the export fallback)."""
    from ..static.input_spec import InputSpec
    if isinstance(spec, InputSpec):
        from ..framework.dtype import to_jax_dtype
        dims = []
        for i, s in enumerate(spec.shape):
            if s is None or s == -1:
                if scope is None:
                    dims.append(1)
                else:
                    name = "b" if i == 0 else f"in{idx}_d{i}"
                    dims.append(jax.export.symbolic_shape(
                        name, scope=scope)[0])
            else:
                dims.append(int(s))
        return jax.ShapeDtypeStruct(tuple(dims), to_jax_dtype(spec.dtype))
    if isinstance(spec, Tensor):
        return jax.ShapeDtypeStruct(spec._value.shape, spec._value.dtype)
    return spec


def _collect_quant(layer, bv):
    """Quant manifest for jit.save: every sublayer exposing
    `quant_weight_spec()` (quantization.WeightOnlyLinear) contributes its
    quantized-weight and scale buffer names. These tensors are exported
    as leading runtime ARGUMENTS of the StableHLO artifact instead of
    baked closure constants: a baked int8 constant is legal StableHLO,
    but XLA's compile-time constant folding would dequantize
    `convert(q) * scale` into a resident fp32 weight — as an argument
    the weight stays integer in HBM and the dequant fuses into the
    matmul at run time. Tied layers appear once (named_sublayers dedups
    by id, the same traversal named_buffers uses)."""
    args, entries = [], []
    for pfx, sub in layer.named_sublayers(include_self=True):
        spec = getattr(sub, "quant_weight_spec", None)
        if spec is None:
            continue
        for qattr, sattr, bits in spec():
            qname = f"{pfx}.{qattr}" if pfx else qattr
            sname = f"{pfx}.{sattr}" if pfx else sattr
            if qname not in bv or sname not in bv:
                continue  # tied layer already collected under its
                # first traversal name
            args += [qname, sname]
            entries.append({"name": qname, "scale": sname,
                            "bits": int(bits)})
    return {"version": 1, "args": args, "entries": entries} \
        if entries else None


def save(layer, path, input_spec=None, **configs):
    """reference `jit.py:507` — writes {path}.pdmodel (StableHLO export),
    {path}.pdiparams (weights), {path}.pdmeta (structure + quant
    manifest). Weight-only-quantized sublayers export their int8/packed
    int4 tensors + scales as leading runtime arguments (see
    _collect_quant); inference.Predictor reads the manifest and feeds
    them device-resident, so the serving artifact is genuinely
    integer-weighted end to end."""
    from ..framework.functional import functionalize
    from ..nn.layer.layers import Layer

    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    if isinstance(layer, Layer):
        apply_fn, pv, bv = functionalize(layer)
        fwd = layer.forward
        if isinstance(fwd, StaticFunction):
            apply_fn = fwd._get_apply()
        if input_spec is None:
            raise ValueError("jit.save requires input_spec")
        rng = jax.random.PRNGKey(0)

        quant = _collect_quant(layer, bv)
        if quant is None:
            def infer(*xs):
                out, _ = apply_fn(pv, bv, rng, False, *xs)
                return out
            q_sds = []
        else:
            from ..framework import monitor
            monitor.stat_add("STAT_quant_exports")
            qnames = quant["args"]
            bv_rest = {k: v for k, v in bv.items() if k not in set(qnames)}
            q_sds = [jax.ShapeDtypeStruct(bv[n].shape, bv[n].dtype)
                     for n in qnames]

            def infer(*all_args):
                qvals = all_args[:len(qnames)]
                xs = all_args[len(qnames):]
                bv2 = dict(bv_rest)
                bv2.update(zip(qnames, qvals))
                out, _ = apply_fn(pv, bv2, rng, False, *xs)
                return out

        from ..static.input_spec import InputSpec
        dynamic = any(isinstance(s, InputSpec)
                      and any(d is None or d == -1 for d in s.shape)
                      for s in input_spec)
        exported = None
        if dynamic:
            # shape-polymorphic export: None/-1 dims stay symbolic so the
            # serving engine can batch-bucket one artifact. Some programs
            # reject polymorphic shapes (data-dependent reshapes) — fall
            # back to the concrete dim-1 export rather than failing save.
            try:
                scope = jax.export.SymbolicScope()
                sds = [_spec_to_sds(s, scope=scope, idx=i)
                       for i, s in enumerate(input_spec)]
                exported = jax.export.export(jax.jit(infer))(*q_sds, *sds)
            except Exception as sym_err:  # noqa: BLE001
                import warnings
                warnings.warn(
                    f"jit.save: shape-polymorphic export failed "
                    f"({sym_err!r}); falling back to concrete dims — the "
                    f"artifact will only accept the saved shapes")
                exported = None
        if exported is None:
            sds = [_spec_to_sds(s) for s in input_spec]
            exported = jax.export.export(jax.jit(infer))(*q_sds, *sds)
        with open(path + ".pdmodel", "wb") as f:
            f.write(exported.serialize())
        state = {n: np.asarray(v.numpy()) for n, v in
                 layer.state_dict().items()}
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump(state, f, protocol=4)
        meta = {"input_specs": [
            (tuple(d if isinstance(d, int) else str(d) for d in s.shape),
             str(s.dtype)) for s in sds]}
        if quant is not None:
            meta["quant"] = quant
        with open(path + ".pdmeta", "wb") as f:
            pickle.dump(meta, f, protocol=4)
        return
    raise TypeError("jit.save expects an nn.Layer")


class TranslatedLayer:
    """reference `jit.py:787` TranslatedLayer — runs a saved program.
    Quantized artifacts (a "quant" manifest in .pdmeta) expect their
    int8/int4 weight + scale tensors as leading call arguments; the
    layer keeps them device-resident in integer form and prepends them
    on every call (the dequant happens inside the compiled program)."""

    def __init__(self, exported, state, quant=None):
        self._exported = exported
        self._state = state
        self._quant = quant
        if quant:
            missing = [n for n in quant["args"] if n not in state]
            if missing:
                raise ValueError(
                    f"quantized artifact is missing weight tensors "
                    f"{missing} in its params file")
            self._qargs = [jnp.asarray(state[n]) for n in quant["args"]]
            # this base materialization IS device memory: account it
            # once here; Predictor replicas then count only buffers
            # their device_put actually created (same-device puts alias
            # the base buffer — see Predictor._load_quant_args)
            import weakref
            from ..inference import _note_quant_bytes
            total = sum(int(a.nbytes) for a in self._qargs)
            _note_quant_bytes(total)
            weakref.finalize(self, _note_quant_bytes, -total)
        else:
            self._qargs = []
        self.training = False

    def __call__(self, *args):
        arrays = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        out = self._exported.call(*self._qargs, *arrays)
        return jax.tree_util.tree_map(lambda x: Tensor(x), out)

    forward = __call__

    def eval(self):
        self.training = False
        return self

    def state_dict(self):
        return {k: Tensor(jnp.asarray(v)) for k, v in self._state.items()}


def load_meta(path) -> dict:
    """The .pdmeta sidecar ({} when absent — pre-manifest artifacts)."""
    if not os.path.exists(path + ".pdmeta"):
        return {}
    with open(path + ".pdmeta", "rb") as f:
        return pickle.load(f)


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    state = {}
    if os.path.exists(path + ".pdiparams"):
        with open(path + ".pdiparams", "rb") as f:
            state = pickle.load(f)
    return TranslatedLayer(exported, state,
                           quant=load_meta(path).get("quant"))


# -- AOT executable serialization (ISSUE 16) --------------------------------
#
# The serving program store (`serving/program_store.py`) persists the
# engine's compiled programs across PROCESSES; these are the shared
# primitives it and `tools/pack_inspect.py` build on. They ride
# `jax.experimental.serialize_executable` — a different artifact path
# than the persistent compilation cache, but the PR 1 lesson applies to
# both: a deserialized donated program is only trustworthy if its
# input/output aliasing survived the round trip, so the alias spec is
# introspectable here and checked on every load.

def serialize_compiled(compiled) -> bytes:
    """One opaque blob for a `jax.stages.Compiled`: the XLA executable
    payload plus the input/output pytree defs its caller signature
    needs (all three pickle cleanly on this stack)."""
    from jax.experimental import serialize_executable as _se
    payload, in_tree, out_tree = _se.serialize(compiled)
    return pickle.dumps((payload, in_tree, out_tree),
                        protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_compiled(blob: bytes):
    """Inverse of `serialize_compiled` → a callable
    `jax.stages.Compiled` loaded onto the current backend."""
    from jax.experimental import serialize_executable as _se
    payload, in_tree, out_tree = pickle.loads(blob)
    return _se.deserialize_and_load(payload, in_tree, out_tree)


def compiled_alias_spec(compiled) -> str:
    """The executable's input/output donation-aliasing spec as a
    canonical string ("" when the program aliases nothing). Extracted
    from the optimized HLO module header — the one place XLA states
    what the RUNTIME will actually alias, which is exactly what the
    PR 1 incident showed can silently differ from what jit was asked
    to donate."""
    import re
    mods = compiled.runtime_executable().hlo_modules()
    specs = []
    for m in mods:
        head = m.to_string()[:4000]
        got = re.search(r"input_output_alias=\{(.*?)\}, entry", head)
        if got:
            spec = " ".join(got.group(1).split())
            if spec:
                specs.append(spec)
    return "; ".join(specs)


def pytree_spec(tree) -> list:
    """Structural fingerprint of a pytree of arrays: sorted
    [path, shape, dtype] triples. For a quantized decode-weight tree
    the (int8 value, fp32 scale) leaf pairs land here with their own
    dtypes/shapes, so this doubles as the quant-manifest digest input
    the program-store key needs — same weights file, different
    quantization, different key."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in flat:
        arr = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
        out.append([jax.tree_util.keystr(path),
                    list(getattr(arr, "shape", [])),
                    str(getattr(arr, "dtype", type(arr).__name__))])
    return sorted(out)


def key_material_digest(material) -> str:
    """Stable content key over JSON-able key material (the program
    store's directory name): canonical JSON → blake2b-128 hex. Any
    non-JSON leaf falls back to str() — good enough because every
    field the store keys on is scalars/lists/dicts by construction."""
    import hashlib
    import json
    blob = json.dumps(material, sort_keys=True, separators=(",", ":"),
                      default=str).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()
