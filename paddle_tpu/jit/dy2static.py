"""Dygraph→static AST transpiler (reference
`fluid/dygraph/dygraph_to_static/` — `ast_transformer.py`,
`convert_operators.py`, `program_translator.py:756 ProgramTranslator`).

The reference rewrites Python source so data-dependent control flow becomes
graph ops (`while_op`, `conditional_block_op`).  The TPU-native equivalent
rewrites the same constructs into *runtime-dispatched converter calls* that
lower to `lax.cond` / `lax.while_loop` when the predicate is a traced
value, and run plain Python otherwise:

  * ``if``/ternary on a traced pred      → `lax.cond`
  * ``while`` with a traced condition    → `lax.while_loop`
  * ``for i in range(traced_n)``         → while-loop lowering
  * ``and`` / ``or`` / ``not`` on tensors → `logical_and/or/not`

Static control flow (python bools, static ranges) is untouched — XLA
prefers unrolled/static structure, so only genuinely data-dependent
branches pay for `lax` control-flow ops.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.tensor import Tensor

__all__ = ["ast_transform", "ProgramTranslator", "enable_to_static",
           "convert_ifelse", "convert_while", "convert_for_range",
           "convert_bool_op", "convert_not"]

_ENABLED = True


def enable_to_static(flag=True):
    global _ENABLED
    _ENABLED = bool(flag)


class ProgramTranslator:
    """reference `program_translator.py:756` — global on/off switch."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def enable(self, flag):
        enable_to_static(flag)

    @property
    def enable_to_static(self):
        return _ENABLED


# ---------------------------------------------------------------------------
# runtime converters
# ---------------------------------------------------------------------------

def _raw(x):
    return x._value if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(_raw(x), jax.core.Tracer)


def _unwrap_tree(t):
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, t,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap_tree(t):
    def one(x):
        if isinstance(x, (jax.Array, jax.core.Tracer)):
            return Tensor(x)
        return x
    return jax.tree_util.tree_map(one, t)


class _Undefined:
    """Placeholder for names not yet bound before a branch/loop assigns
    them (reference `dygraph_to_static/utils.py` UndefinedVar)."""

    def __repr__(self):
        return "<dy2static undefined>"


UNDEF = _Undefined()


def convert_ifelse(pred, true_fn, false_fn, init=(), single=None):
    """`if` with runtime dispatch (reference convert_ifelse).  Branch fns
    receive `init` (the pre-branch values of every name either branch
    assigns) so rebinding inside them never shadows the closure.

    ``single`` marks init slots whose name is assigned in only ONE branch.
    When such a name is also unbound before the `if`, the two branches
    would return mismatched structures under `lax.cond` — those slots are
    kept branch-local and stay undefined after the if, matching Python's
    untaken-branch behavior."""
    p = _raw(pred)
    if isinstance(p, jax.core.Tracer):
        init = tuple(init)
        single = tuple(single) if single is not None \
            else (False,) * len(init)
        dropped = {j for j in range(len(init))
                   if single[j] and isinstance(init[j], _Undefined)}
        # UNDEF placeholders can't ride the cond operand — route them
        # around it statically (the branch that uses one must assign it)
        leaves, treedef = jax.tree_util.tree_flatten(
            _unwrap_tree(init),
            is_leaf=lambda x: isinstance(x, _Undefined))
        idx = [i for i, l in enumerate(leaves)
               if not isinstance(l, _Undefined)]

        def runner(fn):
            def run(op_leaves):
                ls = list(leaves)
                for i, v in zip(idx, op_leaves):
                    ls[i] = v
                rebuilt = jax.tree_util.tree_unflatten(treedef, ls)
                out = fn(_wrap_tree(rebuilt))
                if dropped:
                    out = tuple(0 if j in dropped else v
                                for j, v in enumerate(tuple(out)))
                return _unwrap_tree(out)
            return run
        out = lax.cond(jnp.asarray(p).astype(bool).reshape(()),
                       runner(true_fn), runner(false_fn),
                       [leaves[i] for i in idx])
        res = _wrap_tree(out)
        if dropped:
            res = tuple(UNDEF if j in dropped else v
                        for j, v in enumerate(tuple(res)))
        return res
    return true_fn(init) if p else false_fn(init)


def convert_while(cond_fn, body_fn, init):
    """`while` with runtime dispatch (reference convert_while_loop)."""
    c = cond_fn(init)
    if _is_traced(c):
        undef = [l for l in jax.tree_util.tree_leaves(
            init, is_leaf=lambda x: isinstance(x, _Undefined))
            if isinstance(l, _Undefined)]
        if undef:
            raise ValueError(
                "dy2static: a variable assigned only inside a traced "
                "`while`/`for` cannot be loop-carried — initialize it "
                "before the loop (lax.while_loop needs a fixed carry)")

        def cond_w(carry):
            r = _raw(cond_fn(_wrap_tree(carry)))
            return jnp.asarray(r).astype(bool).reshape(())

        def body_w(carry):
            return _unwrap_tree(body_fn(_wrap_tree(carry)))
        return _wrap_tree(lax.while_loop(cond_w, body_w,
                                         _unwrap_tree(init)))
    vars_ = init
    while True:
        cv = _raw(cond_fn(vars_))
        if not bool(cv):
            return vars_
        vars_ = body_fn(vars_)


def convert_bool_op(op, *operand_fns):
    """`and`/`or` preserving python short-circuit for concrete values and
    lowering to elementwise logical ops for traced ones."""
    val = operand_fns[0]()
    for f in operand_fns[1:]:
        v = _raw(val)
        if isinstance(v, jax.core.Tracer):
            r = _raw(f())
            a = jnp.asarray(v).astype(bool)
            b = jnp.asarray(r).astype(bool)
            val = Tensor(jnp.logical_and(a, b) if op == "and"
                         else jnp.logical_or(a, b))
        elif op == "and":
            if not v:
                return val
            val = f()
        else:
            if v:
                return val
            val = f()
    return val


def convert_not(x):
    v = _raw(x)
    if isinstance(v, jax.core.Tracer):
        return Tensor(jnp.logical_not(jnp.asarray(v).astype(bool)))
    return not v


def convert_for_range(start, stop, step, body_fn, init):
    """``for i in range(...)`` with runtime dispatch.  The loop variable is
    element 0 of ``init`` and of the carry ``body_fn`` receives/returns.

    Concrete bounds run a plain Python ``for`` (exact CPython semantics:
    the loop variable keeps its last-iterated value, an empty range leaves
    it untouched).  Traced bounds lower to ``lax.while_loop`` over a
    precomputed trip count, with the loop variable reconstructed as
    ``start + k*step`` — never the post-loop overshoot value."""
    sv, tv, pv = _raw(start), _raw(stop), _raw(step)
    init = tuple(init)
    if not any(isinstance(v, jax.core.Tracer) for v in (sv, tv, pv)):
        vars_ = init
        for iv in range(int(sv), int(tv), int(pv)):
            vars_ = tuple(body_fn((iv,) + tuple(vars_[1:])))
        return vars_
    undef = [l for l in jax.tree_util.tree_leaves(
        list(init[1:]), is_leaf=lambda x: isinstance(x, _Undefined))
        if isinstance(l, _Undefined)]
    if undef:
        raise ValueError(
            "dy2static: a variable assigned only inside a traced `for` "
            "cannot be loop-carried — initialize it before the loop "
            "(lax.while_loop needs a fixed carry)")
    start_a = jnp.asarray(sv)
    if not jnp.issubdtype(start_a.dtype, jnp.integer):
        start_a = start_a.astype("int32")
    stop_a = jnp.asarray(tv).astype(start_a.dtype)
    step_a = jnp.asarray(pv).astype(start_a.dtype)
    # integer ceil-division trip count (exact; float32 loses precision
    # past 2**24): ceil((stop-start)/step) == -((start-stop)//step).
    # step==0 (ValueError in CPython) degenerates to zero iterations; the
    # divisor is swapped to 1 because XLA evaluates both where() branches.
    safe_step = jnp.where(step_a == 0, jnp.ones_like(step_a), step_a)
    n_iter = jnp.where(
        step_a == 0, 0,
        jnp.maximum(-((start_a - stop_a) // safe_step), 0))
    # the loop-var carry slot must match iv's dtype; a pre-bound value is
    # cast in, and restored after the loop for the n_iter==0 case
    i0 = start_a if isinstance(init[0], _Undefined) \
        else jnp.asarray(_raw(init[0])).astype(start_a.dtype)
    carry0 = (jnp.asarray(0, "int32"),
              _unwrap_tree((i0,) + tuple(init[1:])))

    def cond_w(c):
        return c[0] < n_iter.astype(c[0].dtype)

    def body_w(c):
        k, vars_ = c
        iv = start_a + k.astype(start_a.dtype) * step_a
        new_vars = _unwrap_tree(tuple(body_fn(
            _wrap_tree((iv,) + tuple(vars_[1:])))))
        return k + jnp.asarray(1, "int32"), new_vars

    _, out = lax.while_loop(cond_w, body_w, carry0)
    out = list(out)
    if not isinstance(init[0], _Undefined):
        # empty traced range must leave the pre-bound loop var untouched
        # (including non-integer values the carry slot had to truncate)
        orig = jnp.asarray(_raw(init[0]))
        out[0] = jnp.where(n_iter > 0, out[0].astype(orig.dtype), orig)
    return _wrap_tree(tuple(out))


# ---------------------------------------------------------------------------
# static analysis helpers
# ---------------------------------------------------------------------------

_SKIP_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)
# comprehensions own their iteration targets in py3 — scope boundaries too
_COMPREHENSION_SCOPES = (ast.ListComp, ast.SetComp, ast.DictComp,
                         ast.GeneratorExp)


def _stored_names(stmts):
    """Names assigned at the top scope of `stmts` (nested defs and
    comprehension iteration variables excluded; walrus targets inside
    comprehensions DO bind in the enclosing scope — PEP 572)."""
    out = []

    def walk(node, in_comp=False):
        if isinstance(node, _SKIP_SCOPES):
            return
        if isinstance(node, _COMPREHENSION_SCOPES):
            for child in ast.iter_child_nodes(node):
                walk(child, True)
            return
        if isinstance(node, ast.NamedExpr):
            if (isinstance(node.target, ast.Name)
                    and not node.target.id.startswith("__dy2s")):
                out.append(node.target.id)
            walk(node.value, in_comp)
            return
        if (not in_comp and isinstance(node, ast.Name)
                and isinstance(node.ctx, (ast.Store, ast.Del))):
            if not node.id.startswith("__dy2s"):
                out.append(node.id)
        for child in ast.iter_child_nodes(node):
            walk(child, in_comp)
    for s in stmts:
        walk(s)
    seen, uniq = set(), []
    for n in out:
        if n not in seen:
            seen.add(n)
            uniq.append(n)
    return uniq


def _contains(stmts, types):
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, types) and not isinstance(node,
                                                          _SKIP_SCOPES):
                return True
    return False


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _tuple_of(names, ctx=None):
    return ast.Tuple(elts=[_name(n, ctx or ast.Load()) for n in names],
                     ctx=ctx or ast.Load())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name("_jst"), attr=fn_name, ctx=ast.Load())


def _make_fn(name, args, body):
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=a)
                                                 for a in args],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=body, decorator_list=[], returns=None, type_params=[])


def _guard(name):
    """`try: name / except NameError: name = _jst.UNDEF` — lets possibly
    unbound names ride the init tuple (reference UndefinedVar filling)."""
    return ast.parse(
        f"try:\n    {name}\nexcept NameError:\n"
        f"    {name} = _jst.UNDEF").body[0]


# ---------------------------------------------------------------------------
# the AST transformer
# ---------------------------------------------------------------------------

class Dy2StaticTransformer(ast.NodeTransformer):
    """One bottom-up pass over a function body (reference splits this into
    14 transformer modules; the converter-dispatch design needs only the
    control-flow and boolean rewrites)."""

    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # -- boolean operators --------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = "and" if isinstance(node.op, ast.And) else "or"
        lambdas = [ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=v) for v in node.values]
        return ast.Call(func=_jst_attr("convert_bool_op"),
                        args=[ast.Constant(value=op)] + lambdas, keywords=[])

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_jst_attr("convert_not"),
                            args=[node.operand], keywords=[])
        return node

    def visit_IfExp(self, node):
        self.generic_visit(node)
        # convert_ifelse always calls branch fns with one arg (the init
        # tuple) — the lambdas must accept and ignore it
        mk = lambda b: ast.Lambda(
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg="__dy2s_op")],
                               kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=b)
        return ast.Call(func=_jst_attr("convert_ifelse"),
                        args=[node.test, mk(node.body), mk(node.orelse)],
                        keywords=[])

    # -- if / else ----------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        uid = self._uid()
        body, orelse = node.body, node.orelse or [ast.Pass()]

        has_ret_b = _contains(body, ast.Return)
        has_ret_o = _contains(orelse, ast.Return)
        op = f"__dy2s_op_{uid}"
        if has_ret_b or has_ret_o:
            # only the both-branches-end-in-return shape is convertible
            if (has_ret_b and has_ret_o
                    and isinstance(body[-1], ast.Return)
                    and isinstance(orelse[-1], ast.Return)
                    and not _contains(body[:-1], ast.Return)
                    and not _contains(orelse[:-1], ast.Return)):
                tfn = _make_fn(f"__dy2s_true_{uid}", [op],
                               body[:-1] + [ast.Return(
                                   value=body[-1].value
                                   or ast.Constant(value=None))])
                ffn = _make_fn(f"__dy2s_false_{uid}", [op],
                               orelse[:-1] + [ast.Return(
                                   value=orelse[-1].value
                                   or ast.Constant(value=None))])
                call = ast.Call(func=_jst_attr("convert_ifelse"),
                                args=[node.test,
                                      _name(tfn.name), _name(ffn.name)],
                                keywords=[])
                return [tfn, ffn, ast.Return(value=call)]
            return node  # mixed return shape: leave as python `if`

        assigned = _stored_names(body + orelse)
        b_names = set(_stored_names(body))
        o_names = set(_stored_names(orelse))
        single = [(n in b_names) != (n in o_names) for n in assigned]
        ret = lambda: (_tuple_of(assigned) if assigned
                       else ast.Tuple(elts=[], ctx=ast.Load()))
        unpack = lambda: ([ast.Assign(
            targets=[_tuple_of(assigned, ast.Store())],
            value=_name(op))] if assigned else [])
        tfn = _make_fn(f"__dy2s_true_{uid}", [op],
                       unpack() + body + [ast.Return(value=ret())])
        ffn = _make_fn(f"__dy2s_false_{uid}", [op],
                       unpack() + orelse + [ast.Return(value=ret())])
        call = ast.Call(func=_jst_attr("convert_ifelse"),
                        args=[node.test, _name(tfn.name), _name(ffn.name),
                              ret()],
                        keywords=[ast.keyword(
                            arg="single",
                            value=ast.Tuple(
                                elts=[ast.Constant(value=s)
                                      for s in single],
                                ctx=ast.Load()))] if assigned else [])
        guards = [_guard(n) for n in assigned]
        if assigned:
            out = ast.Assign(targets=[_tuple_of(assigned, ast.Store())],
                             value=call)
        else:
            out = ast.Expr(value=call)
        return guards + [tfn, ffn, out]

    # -- while --------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _contains(node.body,
                                    (ast.Break, ast.Continue, ast.Return)):
            return node  # break/continue/return: python-only semantics
        uid = self._uid()
        carried = _stored_names(node.body)
        return self._lower_loop(uid, node.test, node.body, carried)

    def _lower_loop(self, uid, test, body, carried):
        var = f"__dy2s_vars_{uid}"
        unpack = lambda: ([ast.Assign(
            targets=[_tuple_of(carried, ast.Store())],
            value=_name(var))] if carried else [])
        tup = lambda: (_tuple_of(carried) if carried
                       else ast.Tuple(elts=[], ctx=ast.Load()))
        cond_fn = _make_fn(f"__dy2s_cond_{uid}", [var],
                           unpack() + [ast.Return(value=test)])
        body_fn = _make_fn(f"__dy2s_body_{uid}", [var],
                           unpack() + body + [ast.Return(value=tup())])
        call = ast.Call(func=_jst_attr("convert_while"),
                        args=[_name(cond_fn.name), _name(body_fn.name),
                              tup()], keywords=[])
        guards = [_guard(n) for n in carried]
        if carried:
            out = ast.Assign(targets=[_tuple_of(carried, ast.Store())],
                             value=call)
        else:
            out = ast.Expr(value=call)
        return guards + [cond_fn, body_fn, out]

    # -- for i in range(...) -------------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        if (node.orelse
                or _contains(node.body,
                             (ast.Break, ast.Continue, ast.Return))
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and not node.iter.keywords
                        and 1 <= len(node.iter.args) <= 3)):
            return node
        uid = self._uid()
        a = node.iter.args
        start = a[0] if len(a) >= 2 else ast.Constant(value=0)
        stop = a[1] if len(a) >= 2 else a[0]
        step = a[2] if len(a) == 3 else ast.Constant(value=1)
        i = node.target.id
        # carry layout: loop var first, then everything the body assigns
        carried = [i] + [n for n in _stored_names(node.body) if n != i]
        var = f"__dy2s_vars_{uid}"
        unpack = [ast.Assign(targets=[_tuple_of(carried, ast.Store())],
                             value=_name(var))]
        body_fn = _make_fn(f"__dy2s_body_{uid}", [var],
                           unpack + node.body
                           + [ast.Return(value=_tuple_of(carried))])
        call = ast.Call(func=_jst_attr("convert_for_range"),
                        args=[start, stop, step, _name(body_fn.name),
                              _tuple_of(carried)], keywords=[])
        guards = [_guard(n) for n in carried]
        out = ast.Assign(targets=[_tuple_of(carried, ast.Store())],
                         value=call)
        return guards + [body_fn, out]


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

class _RewriteZeroArgSuper(ast.NodeTransformer):
    """``super()`` → ``super(__class__, self)``.  Zero-arg super() relies
    on the implicit ``__class__`` cell that only class-body-compiled
    functions get; the recompiled function must reference it explicitly
    so it closes over the factory parameter instead."""

    def __init__(self, self_name):
        self._self = self_name

    def _stop(self, node):  # nested scopes have a different `self`
        return node

    visit_FunctionDef = visit_AsyncFunctionDef = _stop
    visit_ClassDef = visit_Lambda = _stop

    def visit_Call(self, node):
        self.generic_visit(node)
        if (isinstance(node.func, ast.Name) and node.func.id == "super"
                and not node.args and not node.keywords):
            node.args = [_name("__class__"), _name(self._self)]
        return node


def ast_transform(fn):
    """Rewrite `fn`'s control flow into converter calls.  Falls back to the
    original function when source is unavailable or the rewrite fails."""
    if not _ENABLED or getattr(fn, "_not_to_static", False):
        return fn
    bound_self = getattr(fn, "__self__", None)
    raw = fn.__func__ if inspect.ismethod(fn) else fn
    if getattr(raw, "__dy2static_transformed__", False):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
        fdef = tree.body[0]
        if not isinstance(fdef, ast.FunctionDef):
            return fn
        fdef.decorator_list = []
        freevars = raw.__code__.co_freevars
        # rewrite super() BEFORE control-flow lowering so the explicit
        # super(__class__, self) form rides into generated branch fns
        # (which would otherwise feed their carry tuple as super()'s obj)
        if "__class__" in freevars and fdef.args.args:
            _RewriteZeroArgSuper(fdef.args.args[0].arg).generic_visit(fdef)
        Dy2StaticTransformer().visit(fdef)
        ns = dict(raw.__globals__)
        from . import dy2static as _jst_mod
        ns["_jst"] = _jst_mod
        if freevars:
            # rebuild the closure with real cells: compile the transformed
            # def inside a factory taking every freevar as a parameter
            factory = _make_fn("__dy2s_factory", list(freevars),
                               [fdef, ast.Return(value=_name(fdef.name))])
            tree.body = [factory]
        ast.fix_missing_locations(tree)
        code = compile(tree, filename=f"<dy2static:{raw.__qualname__}>",
                       mode="exec")
        exec(code, ns)
        new_fn = (ns["__dy2s_factory"](*(c.cell_contents
                                         for c in raw.__closure__))
                  if freevars else ns[fdef.name])
    except Exception:
        return fn
    functools.update_wrapper(new_fn, raw)
    new_fn.__dy2static_transformed__ = True
    if bound_self is not None:
        return new_fn.__get__(bound_self, type(bound_self))
    return new_fn
