from .mesh import (DEFAULT_AXES, P, axis_size, create_mesh, get_mesh,
                   mesh_scope, named_sharding, replicated, set_mesh)
from .pipeline import (gpipe_spmd, make_pipeline_train_step,
                       partition_blocks, pipeline_forward)
from .ring_attention import (ring_attention, shard_map_ring_attention,
                             ulysses_attention)
from .compression import dgc_compress, dgc_init
from .localsgd import local_write_back, make_local_train_step
from .spmd import (batch_placement, batch_sharding, compat_shard_map,
                   make_sharded_train_step, mapped_axis_size, param_sharding,
                   shard_params, tp_mesh, write_back, zero_sharding)
