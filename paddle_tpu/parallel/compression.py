"""Gradient compression strategies: DGC (deep gradient compression).

Reference: `fleet/meta_optimizers/dgc_optimizer.py:19` + the C++/CUDA
`operators/dgc_op.cc` / `dgc_momentum_op` pair.  DGC keeps two
accumulators per parameter — a momentum velocity ``u`` and an error
feedback buffer ``v`` — and each step only applies the top-k fraction of
the accumulated velocity, leaving the rest in ``v`` for later steps
(gradient sparsification with momentum correction, Lin et al. 2018).

TPU-native shape: there is no NCCL sparse-allreduce to feed — XLA owns the
collectives — so compression is expressed as a *pure pytree transform* on
gradients with explicit (u, v) state:

* In the GSPMD path (`spmd.make_sharded_train_step(dgc=True)`) the
  transform runs on the already-reduced global gradient: identical
  error-feedback/top-k dynamics, dense wire format.
* In the shard_map path (`localsgd.make_local_train_step(dgc=True)`)
  gradients are per-worker, so masking happens *before* the explicit
  `lax.psum` — the faithful per-worker DGC dataflow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["dgc_init", "dgc_compress"]


def dgc_init(params_pytree):
    """(u, v) zero state shaped like the params pytree."""
    def one(v):
        # distinct buffers — u and v must be independently donatable
        return {"u": jnp.zeros_like(v), "v": jnp.zeros_like(v)}
    return jax.tree_util.tree_map(one, params_pytree)


def _topk_mask(x, k):
    """Boolean mask keeping the k largest-|x| entries (flattened)."""
    flat = jnp.abs(x).reshape(-1)
    kth = lax.top_k(flat, k)[0][-1]
    return (jnp.abs(x) >= kth)


def dgc_compress(grads, state, momentum=0.9, sparsity=0.999,
                 rampup_step=None, step_no=None):
    """One DGC step.  Returns (sparse_grads, new_state).

    u <- m*u + g ; v <- v + u ; keep top-(1-sparsity) of |v|;
    emitted grad = v*mask ; u,v <- u,v*(1-mask)  (momentum factor masking).
    With ``rampup_step``, sparsity ramps from 75% to the target over the
    first ``rampup_step`` steps (reference dgc_op warmup ladder).
    """
    eff_sparsity = sparsity
    if rampup_step is not None and step_no is not None:
        frac = jnp.clip(step_no / float(rampup_step), 0.0, 1.0)
        eff_sparsity = 0.75 + frac * (sparsity - 0.75)

    def one(g, st):
        u = momentum * st["u"] + g
        v = st["v"] + u
        size = v.size
        if rampup_step is None:
            k = max(1, int(round(size * (1.0 - sparsity))))
            mask = _topk_mask(v, k)
        else:
            # dynamic sparsity: threshold from the static *final* k ladder
            # is not jit-stable, so use the quantile of |v| instead.
            q = jnp.quantile(jnp.abs(v).reshape(-1).astype("float32"),
                             eff_sparsity)
            mask = (jnp.abs(v) >= q.astype(v.dtype))
        keep = mask.astype(v.dtype)
        out = v * keep
        return out, {"u": u * (1 - keep), "v": v * (1 - keep)}

    leaves_g, tdef = jax.tree_util.tree_flatten(grads)
    leaves_s = tdef.flatten_up_to(state)
    outs = [one(g, s) for g, s in zip(leaves_g, leaves_s)]
    new_g = jax.tree_util.tree_unflatten(tdef, [o[0] for o in outs])
    new_s = jax.tree_util.tree_unflatten(tdef, [o[1] for o in outs])
    return new_g, new_s
