"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference (~v2.0) has NO long-context support (SURVEY §5) — this is a
new first-class subsystem, TPU-native by design:

* ring_attention: shard the sequence over the 'sp' mesh axis; each step
  computes a blockwise (online-softmax) attention against the resident
  K/V shard, then rotates K/V one hop around the ICI ring with
  lax.ppermute. Peak memory O(S/sp); comm fully overlapped by XLA's
  latency-hiding scheduler. Causal masking uses block-index arithmetic.
* ulysses_attention: all-to-all re-shard — [B, S/sp, H, D] ⇄
  [B, S, H/sp, D] — so full-sequence attention runs locally per head
  group; two lax.all_to_all ops ride ICI.

Both are pure jnp/lax functions meant to run inside shard_map over 'sp'.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .spmd import mapped_axis_size

__all__ = ["ring_attention", "ulysses_attention", "shard_map_ring_attention"]


def _dot_precision(dtype):
    """bf16/f16 inputs take the fast single-pass MXU path; f32 inputs
    keep full precision. Must be explicit either way: the framework pins
    jax_default_matmul_precision="highest" globally
    (framework/__init__.py), which would upcast bf16 dots, while a bare
    DEFAULT would silently degrade f32 accuracy on TPU."""
    if dtype in (jnp.bfloat16, jnp.float16):
        return jax.lax.Precision.DEFAULT
    return jax.lax.Precision.HIGHEST


def _block_attend(q, k, v, scale, mask_val=None):
    """Partial (un-normalized) attention stats for one K/V block.
    q: [B,H,Sq,D]; k,v: [B,H,Sk,D] → (max, sumexp, acc).

    MXU dots run on the INPUT dtype (bf16 in production — 4x the f32
    path on v5e, same recipe as the Pallas flash kernel); the softmax
    statistics and accumulator stay f32."""
    prec = _dot_precision(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32,
                   precision=prec) * scale
    if mask_val is not None:
        s = jnp.where(mask_val, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32,
                     precision=prec)
    return m, l, acc


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Blockwise ring attention. q,k,v: LOCAL shards [B, H, S_loc, D];
    the global sequence is sp * S_loc, laid out contiguously by rank."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    sp = mapped_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, H, S, D = q.shape

    q_pos = my * S + jnp.arange(S)  # global positions of my queries

    def mask_for(kv_rank):
        if not causal:
            return None
        k_pos = kv_rank * S + jnp.arange(S)
        return q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def body(i, carry):
        m, l, acc, k_cur, v_cur = carry
        kv_rank = (my - i) % sp
        msk = mask_for(kv_rank)
        if msk is not None:
            msk = msk[None, None]
        bm, bl, bacc = _block_attend(q, k_cur, v_cur, scale, msk)
        m_new = jnp.maximum(m, bm)
        scale_old = jnp.exp(m - m_new)
        scale_blk = jnp.exp(bm - m_new)
        l_new = l * scale_old + bl * scale_blk
        acc_new = acc * scale_old + bacc * scale_blk
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_nxt, v_nxt

    # derive carries from q so they inherit the 'sp' varying manual axis;
    # stats/accumulator are f32, K/V rotate in their native (bf16) dtype
    qf = q.astype(jnp.float32)
    m0 = jnp.full_like(qf[..., :1], -1e30)
    l0 = jnp.zeros_like(qf[..., :1])
    acc0 = jnp.zeros_like(qf)
    m, l, acc, _, _ = lax.fori_loop(
        0, sp, body, (m0, l0, acc0, k, v))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.
    Inputs: LOCAL shards [B, H, S_loc, D] with H % sp == 0. Re-shards to
    [B, H/sp, S_global, D], attends locally, re-shards back."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    sp = mapped_axis_size(axis_name)

    def to_seq(x):
        # [B,H,S,D] -> split heads, gather sequence
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    prec = _dot_precision(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", qs, ks,
                   preferred_element_type=jnp.float32,
                   precision=prec) * scale
    if causal:
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vs.dtype), vs,
                     preferred_element_type=jnp.float32,
                     precision=prec)
    # cast BEFORE the all_to_all so the ICI transfer rides bf16
    return to_heads(out.astype(q.dtype))


def shard_map_ring_attention(q, k, v, mesh, causal=False, impl="ring"):
    """Convenience: run (ring|ulysses) attention over global arrays
    [B, H, S, D] sequence-sharded on 'sp'."""
    from jax.sharding import PartitionSpec as P

    from .spmd import compat_shard_map
    attn = ring_attention if impl == "ring" else ulysses_attention
    fn = compat_shard_map(
        functools.partial(attn, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))
    return fn(q, k, v)
