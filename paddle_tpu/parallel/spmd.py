"""SPMD sharded training step builder.

This is the TPU-native replacement for the whole reference multi-device
execution stack: ParallelExecutor's SSA graphs + allreduce op handles
(`framework/details/`), the dygraph Reducer (`imperative/reducer.cc`), the
sharding meta-optimizer (`fleet/meta_optimizers/sharding_optimizer.py`) and
TP split — collapsed into ONE function: lay params/opt-state/batch onto a
mesh with NamedShardings and jit the whole train step; XLA/GSPMD inserts
every collective (grad allreduce over 'dp', TP collectives over 'mp',
ZeRO gather/scatter over 'dp') on ICI.

Sharding rules:
  * params: honor `param.partition_spec` (set by TP layers / user), else
    replicated.
  * optimizer state (ZeRO-1/2, reference sharding_optimizer.py:33): each
    state leaf inherits the param spec, and — when zero_stage >= 1 — its
    largest unsharded divisible axis is additionally sharded over 'dp'.
  * batch: axis 0 over 'dp'; optional sequence axis over 'sp'.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..framework import random as frandom
from ..framework.functional import functionalize, get_buffers, get_params
from ..framework.monitor import STAT_ADD
from ..framework.tensor import Tensor
from .mesh import get_mesh

__all__ = ["param_sharding", "zero_sharding", "batch_sharding",
           "batch_placement", "make_sharded_train_step", "shard_params",
           "sharded_splash_attention", "compat_shard_map", "tp_mesh"]

# jax moved shard_map twice: old releases ship it only at
# jax.experimental.shard_map (keyword `check_rep`), new ones only at
# jax.shard_map (keyword `check_vma`). Resolve ONCE at import so every
# caller — training builders and the serving engine's sharded program
# pack alike — stays version-portable.
try:
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _SM_CHECK_KW = "check_rep"
except ImportError:  # pragma: no cover — jax without the experimental alias
    _shard_map_impl = jax.shard_map
    _SM_CHECK_KW = "check_vma"


def compat_shard_map(f, mesh, in_specs, out_specs, check=False):
    """shard_map across jax versions (maps `check` onto whichever of
    check_rep/check_vma this jax accepts). NOT jitted — wrap the result
    in jax.jit yourself so donation/AOT knobs stay at the call site."""
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **{_SM_CHECK_KW: check})


def mapped_axis_size(axis):
    """`lax.axis_size` inside a shard_map/pmap body, on every jax:
    old releases lack the function but constant-fold psum of a unit
    literal to the (static, Python int) axis size."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)


def tp_mesh(tp, axis="tp", devices=None):
    """A 1-D mesh of `tp` devices for tensor-parallel serving lanes.

    Takes the FIRST `tp` visible devices (a mesh-slice lane is a
    contiguous slice, and the router addresses whole engines, not
    devices). Raises if the host exposes fewer than `tp` devices.
    """
    from jax.sharding import Mesh
    devs = list(devices if devices is not None else jax.devices())
    if len(devs) < tp:
        raise RuntimeError(
            f"tp={tp} needs {tp} devices, host exposes {len(devs)} "
            f"(CPU smoke: XLA_FLAGS=--xla_force_host_platform_"
            f"device_count={tp})")
    return Mesh(np.asarray(devs[:tp]), (axis,))


def _spec_of(param) -> PartitionSpec:
    return getattr(param, "partition_spec", None) or PartitionSpec()


def param_sharding(layer, mesh=None) -> Dict[str, NamedSharding]:
    mesh = mesh or get_mesh()
    out = {}
    for name, p in get_params(layer).items():
        spec = _spec_of(p)
        spec = _filter_spec(spec, mesh)
        out[name] = NamedSharding(mesh, spec)
    return out


def _filter_spec(spec, mesh):
    """Drop axes not present in the mesh (lets TP layers run on dp-only
    meshes unchanged)."""
    parts = []
    for s in tuple(spec):
        if s is None:
            parts.append(None)
        elif isinstance(s, str) and s in mesh.axis_names and \
                mesh.shape[s] > 1:
            parts.append(s)
        else:
            parts.append(None)
    return PartitionSpec(*parts)


def zero_sharding(layer, opt_state, mesh=None, zero_stage=1,
                  dp_axis="dp") -> Dict:
    """Sharding pytree for optimizer state (ZeRO over the dp axis)."""
    mesh = mesh or get_mesh()
    params = get_params(layer)
    dp = mesh.shape.get(dp_axis, 1) if dp_axis in mesh.axis_names else 1

    def one(name):
        p = params[name]
        base = tuple(_filter_spec(_spec_of(p), mesh))
        shape = tuple(p._value.shape)

        def leaf_sharding(leaf):
            if not hasattr(leaf, "shape") or leaf.ndim == 0:
                return NamedSharding(mesh, PartitionSpec())
            spec = list(base[:leaf.ndim]) + [None] * (leaf.ndim - len(base))
            if zero_stage >= 1 and dp > 1:
                for ax in np.argsort([-d for d in leaf.shape]):
                    ax = int(ax)
                    if spec[ax] is None and leaf.shape[ax] % dp == 0:
                        spec[ax] = dp_axis
                        break
            return NamedSharding(mesh, PartitionSpec(*spec))
        return leaf_sharding

    out = {}
    for name, st in opt_state.items():
        f = one(name)
        out[name] = jax.tree_util.tree_map(f, st)
    return out


def batch_sharding(ndim, mesh=None, dp_axis="dp", sp_axis=None,
                   seq_dim=1) -> NamedSharding:
    mesh = mesh or get_mesh()
    spec = [None] * ndim
    if dp_axis in mesh.axis_names and mesh.shape[dp_axis] > 1:
        spec[0] = dp_axis
    if sp_axis and sp_axis in mesh.axis_names and mesh.shape[sp_axis] > 1 \
            and ndim > seq_dim:
        spec[seq_dim] = sp_axis
    return NamedSharding(mesh, PartitionSpec(*spec))


def batch_placement(mesh=None, dp_axis="dp", sp_axis=None, seq_dim=1):
    """Per-leaf placement callable for io.DeviceFeeder: leaf -> the
    NamedSharding a training batch of that rank gets on `mesh`.

    Handing this to the feeder moves the batch split/upload onto the
    feeder thread, so the sharded train step receives arrays already in
    their dp/sp layout and skips its synchronous per-step device_put
    (the step's pre-placed fast path below). Every leaf — labels
    included — gets the same policy; GSPMD reshards inside the step if
    the computation wants a different layout.

    A dimension that does not divide its mesh axis is left unsharded
    (jax.device_put hard-fails on uneven shards). A leaf with no
    shardable dimension at all — e.g. the raw drop_last=False tail
    batch before Model.fit pads it — returns None: it stays on the
    default device and the step (or the padded re-placement) lays it
    out once it is even.
    """
    mesh = mesh or get_mesh()

    def place(x):
        sh = batch_sharding(np.ndim(x), mesh, dp_axis, sp_axis, seq_dim)
        shape = np.shape(x)
        spec = []
        for d, a in enumerate(tuple(sh.spec)):
            if a is not None and shape[d] % mesh.shape[a] != 0:
                a = None
            spec.append(a)
        if not any(s is not None for s in spec):
            return None
        return NamedSharding(mesh, PartitionSpec(*spec))

    return place


def _place_batch(x, mesh, dp_axis, sp_axis):
    """Lay one batch leaf onto the mesh — unless the feeder already did.

    An array that is committed to a NamedSharding on this mesh is consumed
    as-is (zero re-placement; STAT_sharded_batch_puts stays flat), which is
    what makes the sharding-aware DeviceFeeder a true overlap instead of a
    double transfer.
    """
    v = x._value if isinstance(x, Tensor) else x
    if not isinstance(v, jax.Array):
        v = jnp.asarray(v)
    sh = getattr(v, "sharding", None)
    if isinstance(sh, NamedSharding) and sh.mesh == mesh and \
            getattr(v, "committed", False):
        return v
    STAT_ADD("STAT_sharded_batch_puts")
    return jax.device_put(v, batch_sharding(np.ndim(v), mesh, dp_axis,
                                            sp_axis))


def shard_params(layer, mesh=None):
    """Physically lay the layer's parameters out on the mesh."""
    mesh = mesh or get_mesh()
    shardings = param_sharding(layer, mesh)
    for name, p in get_params(layer).items():
        p._value = jax.device_put(p._value, shardings[name])
    return shardings


def make_sharded_train_step(layer, optimizer, loss_fn: Callable,
                            mesh=None, zero_stage=1, dp_axis="dp",
                            sp_axis=None, recompute=False,
                            donate=True, grad_dtype=None,
                            dgc=False, dgc_momentum=0.9,
                            dgc_sparsity=0.999):
    """Returns (step, state) where
      state = {params, buffers, opt_state, step_no}
      step(state, inputs, labels, lr, rng) -> (state, loss)
    fully jit-compiled over the mesh with every parallelism expressed as
    shardings. `loss_fn(outputs, labels)` operates on framework Tensors.
    """
    mesh = mesh or get_mesh()
    apply_fn, pv, bv = functionalize(layer)
    p_shard = param_sharding(layer, mesh)
    pv = {n: jax.device_put(v, p_shard[n]) for n, v in pv.items()}
    repl = NamedSharding(mesh, PartitionSpec())
    bv = {n: jax.device_put(v, repl) for n, v in bv.items()}
    opt_state = optimizer.init_state_pytree(pv)
    o_shard = zero_sharding(layer, opt_state, mesh, zero_stage, dp_axis)
    opt_state = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, s), opt_state, o_shard,
        is_leaf=lambda x: hasattr(x, "shape"))

    if recompute:
        inner_apply = apply_fn

        def apply_remat(pv_, bv_, rng, training, *xs):
            def f(pv2, *xs2):
                return inner_apply(pv2, bv_, rng, training, *xs2)
            return jax.checkpoint(f)(pv_, *xs)
        fwd = apply_remat
    else:
        fwd = apply_fn

    def loss_of(pv_, bv_, rng, inputs, labels):
        from ..framework.autograd import trace_mode
        out, new_bufs = fwd(pv_, bv_, rng, True, *inputs)
        with trace_mode():
            wout = jax.tree_util.tree_map(lambda x: Tensor(x), out)
            wlab = [Tensor(x) for x in labels]
            lv = loss_fn(wout, wlab)
        lv_raw = lv._value if isinstance(lv, Tensor) else lv
        return jnp.mean(lv_raw.astype("float32")), new_bufs

    def step_fn(state, inputs, labels, lr, rng):
        pv_, bv_, opt_state_, step_no = (state["params"], state["buffers"],
                                         state["opt_state"],
                                         state["step_no"])
        (lv, new_bufs), grads = jax.value_and_grad(loss_of, has_aux=True)(
            pv_, bv_, rng, inputs, labels)
        new_dgc = None
        if dgc:
            # DGC on the global gradient: top-k + momentum correction +
            # error feedback (see compression.py for the dataflow note)
            from .compression import dgc_compress
            grads, new_dgc = dgc_compress(grads, state["dgc"],
                                          dgc_momentum, dgc_sparsity)
        if grad_dtype is not None:
            # fp16/bf16-allreduce strategy (reference
            # fp16_allreduce_optimizer.py): compress grads before the
            # (XLA-inserted) dp allreduce, restore for the update
            from ..framework.dtype import to_jax_dtype
            gd = to_jax_dtype(grad_dtype)
            grads = jax.tree_util.tree_map(
                lambda g, p: g.astype(gd).astype(p.dtype), grads, pv_)
        new_pv, new_opt = optimizer.apply_gradients_pytree(
            grads, pv_, opt_state_, lr, step_no + 1)
        new_state = {"params": new_pv, "buffers": new_bufs,
                     "opt_state": new_opt, "step_no": step_no + 1}
        if new_dgc is not None:
            new_state["dgc"] = new_dgc
        return new_state, lv

    state_sharding = {
        "params": p_shard, "buffers": {n: repl for n in bv},
        "opt_state": o_shard, "step_no": repl,
    }
    if dgc:
        from .compression import dgc_init
        dgc_state = dgc_init(pv)
        dgc_shard = {n: {"u": p_shard[n], "v": p_shard[n]}
                     for n in dgc_state}
        dgc_state = jax.tree_util.tree_map(
            lambda v, s: jax.device_put(v, s), dgc_state, dgc_shard)
        state_sharding["dgc"] = dgc_shard
    jit_step = jax.jit(
        step_fn,
        out_shardings=(state_sharding, repl),
        donate_argnums=(0,) if donate else ())

    state = {"params": pv, "buffers": bv, "opt_state": opt_state,
             "step_no": jnp.zeros((), "int32")}
    if dgc:
        state["dgc"] = dgc_state

    cost_noted = set()  # batch signatures whose FLOPs were estimated

    def step(state, inputs, labels, lr=None, rng=None):
        inputs = tuple(_place_batch(x, mesh, dp_axis, sp_axis)
                       for x in inputs)
        labels = tuple(_place_batch(x, mesh, dp_axis, None)
                       for x in labels)
        lr = jnp.asarray(optimizer.get_lr() if lr is None else lr,
                         "float32")
        rng = rng if rng is not None else frandom.get_rng_key()
        out = jit_step(state, inputs, labels, lr, rng)
        # per-step FLOPs for the MFU gauge, scaled by mesh size (the
        # cost analysis sees the global program; peak = per-device peak
        # x participating devices). Keyed per batch signature — jit_step
        # recompiles when batch shapes change and the gauge must track
        # the CURRENT step's cost, not the first-ever one — and gated on
        # the sampler being live, so telemetry enabled mid-training
        # still gets FLOPs while inactive processes never pay the
        # retrace. New state shares the donated input's avals so
        # lowering never touches consumed buffers.
        key = tuple((tuple(x.shape), str(x.dtype))
                    for x in inputs + labels)
        if key not in cost_noted:
            from ..profiler import device_telemetry
            if device_telemetry.active():
                cost_noted.add(key)
                device_telemetry.note_train_step_lowering(
                    jit_step, (out[0], inputs, labels, lr, rng),
                    n_devices=int(mesh.devices.size))
        return out

    step.jitted = jit_step
    step.state_sharding = state_sharding
    return step, state


def sharded_splash_attention(mesh=None, causal=False, scale=None,
                             dropout_p=0.0, dp_axis="dp"):
    """shard_map-wrapped splash attention for packed batches on a mesh.

    GSPMD cannot partition a pallas_call — under plain pjit the kernel
    would be gathered onto every device — so the kernel is wrapped in
    `shard_map` with the batch axis split over `dp_axis` and segment ids
    riding the same split (the SNIPPETS [1]/[3] pattern): each shard
    runs the kernel on its local rows only, which is exactly right
    because packing never creates cross-row attention.

    Returns f(q, k, v, q_seg, kv_seg, seed=None) with q/k/v
    [B, H, S, D] and segment ids [B, S] (B divisible by the dp degree).
    `scale` defaults to 1/sqrt(D) at call time. With dropout_p > 0 a
    fresh int32 seed is drawn per call from the framework RNG stream
    (pass `seed` explicitly for reproducible replay) — the seed is a
    traced argument, NOT baked into the jit, so every step gets a new
    keep mask.
    """
    from ..framework import random as frandom
    from ..ops.splash_ops import splash_attention_raw
    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("sharded_splash_attention needs a live mesh "
                           "(parallel.mesh.set_mesh / fleet.init)")
    dp = dp_axis if dp_axis in mesh.axis_names and \
        mesh.shape[dp_axis] > 1 else None
    qkv_spec = PartitionSpec(dp, None, None, None)
    seg_spec = PartitionSpec(dp, None)

    def call(q, k, v, q_seg, kv_seg, seed):
        sc = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
        if dp is not None and dropout_p > 0.0:
            # the kernel keys its keep mask on SHARD-LOCAL grid indices
            # (pl.program_id over the local batch*heads), so a replicated
            # seed would hand every dp shard the identical dropout
            # pattern — fold the shard index in for independent draws
            seed = seed + jax.lax.axis_index(dp)
        return splash_attention_raw(q, k, v, q_seg, kv_seg, seed, causal,
                                    sc, dropout_p)

    jitted = jax.jit(compat_shard_map(
        call, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec, seg_spec,
                  PartitionSpec()),
        out_specs=qkv_spec, check=False))

    def f(q, k, v, q_seg, kv_seg, seed=None):
        if seed is None:
            if dropout_p > 0.0:
                seed = jax.random.randint(
                    frandom.get_rng_key(), (), 0,
                    np.int32(2 ** 31 - 1), dtype=jnp.int32)
            else:
                seed = jnp.zeros((), jnp.int32)
        return jitted(q, k, v, q_seg, kv_seg,
                      jnp.asarray(seed, jnp.int32))

    return f


def write_back(layer, state):
    """Copy trained param/buffer values back into the imperative Layer."""
    params = get_params(layer)
    for n, v in state["params"].items():
        params[n]._value = v
    buffers = get_buffers(layer)
    for n, v in state["buffers"].items():
        buffers[n]._value = v
