"""Pipeline parallelism (reference: PipelineOptimizer
`fluid/optimizer.py:3718` + `fleet/meta_optimizers/pipeline_optimizer.py`
+ `framework/section_worker.cc:49-105` F-then-B microbatch schedule over
send_v2/recv_v2).

TPU-native redesign: stages live on the 'pp' mesh axis under shard_map;
stage parameters are STACKED on a leading pp-sharded axis (each device
holds its stage's slice), activations flow around the ring with
lax.ppermute, and the GPipe F-then-B schedule is a lax.fori_loop over
micro-steps. XLA overlaps the ppermute with stage compute (the analogue of
the reference's separate comm stream).

Requires homogeneous stages (same params/activation shapes per stage) —
the standard TPU formulation for transformer stacks.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .spmd import compat_shard_map, mapped_axis_size

__all__ = ["gpipe_spmd", "pipeline_forward", "partition_blocks",
           "make_pipeline_train_step"]


def pipeline_forward(stage_fn: Callable, stage_params, x, *, axis_name="pp",
                     n_micro: int):
    """Run inside shard_map over `axis_name`.

    stage_fn(params, micro_x) -> micro_y : one stage's forward.
    stage_params: THIS device's stage params (unstacked leaves).
    x: [n_micro, mb, ...] microbatched input, replicated across pp
       (only stage 0's reads matter).
    Returns [n_micro, mb, ...] outputs valid on the LAST stage.

    GPipe forward schedule: at step t, device d processes microbatch
    t - d (if in range); activations hop d→d+1 each step. Total steps =
    n_micro + pp - 1.
    """
    pp = mapped_axis_size(axis_name)
    d = lax.axis_index(axis_name)
    steps = n_micro + pp - 1
    mb_shape = x.shape[1:]

    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def body(t, carry):
        buf_in, outs = carry
        # stage 0 injects microbatch t (if valid); others use ring input
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inject = lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False)
        cur = jnp.where(d == 0, inject, buf_in)
        my_mb = t - d  # which microbatch this device processes now
        active = (my_mb >= 0) & (my_mb < n_micro)
        y = stage_fn(stage_params, cur)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage stores result
        out_idx = jnp.clip(my_mb, 0, n_micro - 1)
        store = (d == pp - 1) & active
        prev = lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(store, y, prev), out_idx, 0)
        nxt = lax.ppermute(y, axis_name, perm)
        return nxt, outs

    buf0 = jnp.zeros(mb_shape, x.dtype)
    outs0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
    _, outs = lax.fori_loop(0, steps, body, (buf0, outs0))
    return outs[None]  # [1, n_micro, ...] per stage; caller takes [-1]


def gpipe_spmd(stage_fn: Callable, mesh, n_micro: int, axis_name="pp"):
    """Wrap a homogeneous stage function into a pipelined forward over the
    mesh's pp axis.

    Usage:
      fwd = gpipe_spmd(stage_fn, mesh, n_micro=4)
      y = fwd(stacked_params, x)[-1]  # stacked_params leaves: [pp, ...]
                                      # x: [n_micro, mb, ...]
    Output is [pp, n_micro, ...]; index [-1] is the last stage's result.
    Gradients flow through ppermute (its transpose is the reverse
    permute), so jax.grad over this forward IS the backward schedule —
    the reference needs hand-inserted send/recv grad ops
    (`section_worker.cc`), here it's transposition.
    """
    inner = functools.partial(pipeline_forward, stage_fn,
                              axis_name=axis_name, n_micro=n_micro)

    def wrapper(stacked_params, x):
        def shard_fn(params_slice, x_rep):
            params_local = jax.tree_util.tree_map(
                lambda a: jnp.squeeze(a, 0), params_slice)
            return inner(params_local, x_rep)
        param_specs = jax.tree_util.tree_map(
            lambda _: P(axis_name), stacked_params)
        return compat_shard_map(
            shard_fn, mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(axis_name),
            check=False)(stacked_params, x)
    return wrapper


# ---------------------------------------------------------------------------
# Heterogeneous pipeline: real models (embedding / blocks / head)
# ---------------------------------------------------------------------------
#
# Reference capability: PipelineOptimizer splits an arbitrary Program by
# device_guard into stages run by PipelineTrainer/SectionWorker
# (`fluid/optimizer.py:3718`, `framework/section_worker.cc:49-105`).
#
# TPU-native redesign: the model declares (pre, blocks, post) sections via
# `pipeline_sections()`. The homogeneous block stack — where the FLOPs
# are — is pipelined over the 'pp' mesh axis (params stacked [pp, k, ...],
# activations hop with ppermute, GPipe microbatch schedule); the cheap
# bookends (embedding, final head) run SPMD on every device with normal
# dp/mp shardings, exactly like praxis-style TPU pipelining. Backward is
# jax.grad through the schedule (ppermute transposes to the reverse ring;
# the reference hand-inserts send/recv grad ops instead).

def partition_blocks(blocks, pp):
    """Stack an nn.LayerList of homogeneous blocks into pp pipeline
    stages of k = len(blocks)/pp blocks each.

    Returns (block_apply, stacked, k) where stacked leaves are
    [pp, k, *param_shape] and block_apply is the functionalized single
    block: block_apply(params, {}, rng, training, h) -> (h', bufs).
    """
    from ..framework.functional import functionalize, get_params
    L = len(blocks)
    if L % pp != 0:
        raise ValueError(f"{L} blocks not divisible into pp={pp} stages")
    k = L // pp
    block_apply, p0, b0 = functionalize(blocks[0])
    if b0:
        raise ValueError(
            "pipelined blocks must be buffer-free (running-stat layers "
            "like BatchNorm belong in the pre/post sections)")
    stacked = {}
    for name in p0:
        vals = [get_params(blocks[i])[name]._value for i in range(L)]
        stacked[name] = jnp.stack(
            [jnp.stack(vals[s * k:(s + 1) * k]) for s in range(pp)])
    return block_apply, stacked, k


def _make_stage_fn(block_apply, training):
    """One pipeline stage = scan over its k blocks. `key` must already be
    folded with (device, microbatch); the block index is folded here so
    every block gets a distinct dropout mask."""
    def stage_fn(params_k, h, key):
        def body(hh, idx_and_p):
            i, p_one = idx_and_p
            out, _ = block_apply(p_one, {}, jax.random.fold_in(key, i),
                                 training, hh)
            return out, None
        k_blocks = jax.tree_util.tree_leaves(params_k)[0].shape[0]
        h2, _ = lax.scan(body, h, (jnp.arange(k_blocks), params_k))
        return h2
    return stage_fn


def _hetero_pipeline_inner(block_apply, stage_params, x, rng, training,
                           axis_name, n_micro, recompute, schedule):
    """Inside shard_map: GPipe schedule over one stage of k blocks.

    stage_params: this device's stage, leaves [k, ...].
    x: [n_micro, mb_local, ...] microbatched activations (replicated
       over pp, sharded over dp by the caller's in_specs).
    Returns [n_micro, mb_local, ...] — the LAST stage's outputs,
    replicated to every pp rank via a masked psum (its transpose routes
    the head's cotangents back to the last stage).
    """
    pp = mapped_axis_size(axis_name)
    d = lax.axis_index(axis_name)
    steps = n_micro + pp - 1
    mb_shape = x.shape[1:]
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    stage_fn = _make_stage_fn(block_apply, training)

    if recompute:
        stage_fn = jax.checkpoint(stage_fn)

    def body(t, carry):
        buf_in, outs = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inject = lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False)
        cur = jnp.where(d == 0, inject, buf_in)
        my_mb = t - d
        active = (my_mb >= 0) & (my_mb < n_micro)
        key_t = jax.random.fold_in(jax.random.fold_in(rng, d),
                                   jnp.clip(my_mb, 0, n_micro - 1))
        y = stage_fn(stage_params, cur, key_t)
        y = jnp.where(active, y, jnp.zeros_like(y))
        out_idx = jnp.clip(my_mb, 0, n_micro - 1)
        store = (d == pp - 1) & active
        prev = lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(store, y, prev), out_idx, 0)
        nxt = lax.ppermute(y, axis_name, perm)
        return nxt, outs

    buf0 = jnp.zeros(mb_shape, x.dtype)
    outs0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
    _, outs = lax.fori_loop(0, steps, body, (buf0, outs0))
    # replicate the last stage's outputs across pp (masked psum; only the
    # last stage contributed non-zeros)
    return lax.psum(jnp.where(d == pp - 1, outs, jnp.zeros_like(outs)),
                    axis_name)


def make_pipeline_train_step(model, optimizer, loss_fn, *, n_micro,
                             mesh=None, pp_axis="pp", dp_axis="dp",
                             recompute=True, schedule="gpipe",
                             donate=True):
    """Build a jit'd pp×dp training step for a model exposing
    `pipeline_sections() -> (pre, blocks, post)`.

    Returns (step, state) with the same contract as
    `make_sharded_train_step`: state = {params, buffers, opt_state,
    step_no}; step(state, inputs, labels[, lr, rng]) -> (state, loss).
    Block-stack params live in state["params"] under "pp::<name>" keys,
    stacked [pp, k, ...] and sharded over the pp mesh axis.
    """
    from jax.sharding import NamedSharding
    from ..framework import random as frandom
    from ..framework.functional import functionalize
    from ..framework.tensor import Tensor
    from .. import nn as _nn
    from .mesh import get_mesh
    from .spmd import batch_sharding, param_sharding

    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    mesh = mesh or get_mesh()
    pp = mesh.shape[pp_axis]
    pre, blocks, post = model.pipeline_sections()

    class _Outer(_nn.Layer):
        def __init__(self):
            super().__init__()
            self.pre = pre
            self.post = post

    outer = _Outer()
    pre_apply, opv, obv = functionalize(
        outer, forward=lambda *a, **k: outer.pre(*a, **k))
    if schedule == "1f1b" and obv:
        # the manual-vjp 1F1B loop replays pre/post per microbatch and has
        # no way to thread buffer mutations through the schedule; refuse
        # loudly rather than silently serving stale running stats
        raise ValueError(
            "schedule='1f1b' requires buffer-free pre/post sections "
            f"(found buffers: {sorted(obv)}); use schedule='gpipe' or "
            "move running-stat layers out of the pipelined model")
    post_apply, _, _ = functionalize(
        outer, forward=lambda *a, **k: outer.post(*a, **k))
    block_apply, bpv, k = partition_blocks(blocks, pp)

    # -- shardings ----------------------------------------------------------
    o_shard = param_sharding(outer, mesh)
    opv = {n: jax.device_put(v, o_shard[n]) for n, v in opv.items()}
    repl = NamedSharding(mesh, P())
    obv = {n: jax.device_put(v, repl) for n, v in obv.items()}
    bp_shard = {n: NamedSharding(mesh, P(pp_axis))
                for n in bpv}
    bpv = {n: jax.device_put(v, bp_shard[n]) for n, v in bpv.items()}

    pv_all = {**opv, **{f"pp::{n}": v for n, v in bpv.items()}}
    pv_shard = {**o_shard, **{f"pp::{n}": bp_shard[n] for n in bpv}}
    opt_state = optimizer.init_state_pytree(pv_all)
    os_shard = {
        n: jax.tree_util.tree_map(
            lambda leaf: (pv_shard[n]
                          if getattr(leaf, "ndim", 0) == pv_all[n].ndim
                          else repl), st)
        for n, st in opt_state.items()}
    opt_state = jax.tree_util.tree_map(
        lambda v, s: jax.device_put(v, s), opt_state, os_shard,
        is_leaf=lambda x: hasattr(x, "shape"))

    bp_specs = {n: P(pp_axis) for n in bpv}

    def pipelined(bpv_, x, rng, training):
        def shard_fn(bp_local, x_local, rng_):
            bp = jax.tree_util.tree_map(lambda a: jnp.squeeze(a, 0),
                                        bp_local)
            return _hetero_pipeline_inner(
                block_apply, bp, x_local, rng_, training, pp_axis,
                n_micro, recompute, schedule)
        x_spec = (P(None, dp_axis) if dp_axis in mesh.axis_names
                  else P())
        return compat_shard_map(
            shard_fn, mesh=mesh,
            in_specs=(bp_specs, x_spec, P()),
            out_specs=x_spec,
            check=False)(bpv_, x, rng)

    def loss_of(pv_all_, bv_, rng, inputs, labels):
        from ..framework.autograd import trace_mode
        opv_ = {n: pv_all_[n] for n in opv}
        bpv_ = {n: pv_all_[f"pp::{n}"] for n in bpv}
        h, pre_bufs = pre_apply(opv_, bv_, rng, True, *inputs)
        b = h.shape[0]
        dp = mesh.shape.get(dp_axis, 1)
        if b % (n_micro * dp) != 0:
            raise ValueError(
                f"global batch {b} must be divisible by "
                f"n_micro*dp = {n_micro}*{dp}")
        hm = h.reshape((n_micro, b // n_micro) + h.shape[1:])
        y = pipelined(bpv_, hm, rng, True)
        y = y.reshape((b,) + y.shape[2:])
        # thread pre-section buffer updates through post so running-stat
        # layers in either bookend section persist their mutations
        out, new_bufs = post_apply(opv_, pre_bufs, rng, True, y)
        with trace_mode():
            wout = jax.tree_util.tree_map(lambda v: Tensor(v), out)
            wlab = [Tensor(v) for v in labels]
            lv = loss_fn(wout, wlab)
        lv_raw = lv._value if isinstance(lv, Tensor) else lv
        return jnp.mean(lv_raw.astype("float32")), new_bufs

    pp_count = pp
    has_dp = dp_axis in mesh.axis_names and mesh.shape[dp_axis] > 1

    def grads_1f1b(pv_all_, bv_, rng, inputs, labels):
        """Manual-gradient 1F1B: returns (loss, grads dict) without
        jax.grad — activation stash capped at pp microbatches."""
        opv_ = {n: pv_all_[n] for n in opv}
        bpv_ = {n: pv_all_[f"pp::{n}"] for n in bpv}
        dp = mesh.shape.get(dp_axis, 1) if has_dp else 1
        b = inputs[0].shape[0]
        if b % (n_micro * dp) != 0:
            raise ValueError(
                f"global batch {b} must be divisible by "
                f"n_micro*dp = {n_micro}*{dp}")

        def micro(x):
            return x.reshape((n_micro, x.shape[0] // n_micro)
                             + x.shape[1:])

        ids_m = tuple(micro(x) for x in inputs)
        lab_m = tuple(micro(x) for x in labels)
        mb_spec = (P(None, dp_axis) if has_dp else P())

        def shard_fn(bp_local, opv_in, bv_in, ids_in, lab_in, rng_):
            bp = jax.tree_util.tree_map(lambda a: jnp.squeeze(a, 0),
                                        bp_local)
            loss, g_stage, g_outer = _one_f_one_b_inner(
                block_apply, pre_apply, post_apply, loss_fn, bp, opv_in,
                bv_in, ids_in, lab_in, rng_, pp_axis, n_micro, pp_count,
                dp_axis=dp_axis if has_dp else None)
            # restore the leading stage axis stripped by squeeze(0) above:
            # out_specs P(pp) concatenates per-shard leaves on axis 0, so
            # each shard must contribute [1, k, ...], not [k, ...]
            g_stage = jax.tree_util.tree_map(lambda g: g[None], g_stage)
            return loss, g_stage, g_outer

        loss, g_stage, g_outer = compat_shard_map(
            shard_fn, mesh=mesh,
            in_specs=(bp_specs, P(), P(),
                      tuple(mb_spec for _ in ids_m),
                      tuple(mb_spec for _ in lab_m), P()),
            out_specs=(P(), {n: P(pp_axis) for n in bpv}, P()),
            check=False)(bpv_, opv_, bv_, ids_m, lab_m, rng)
        grads = {**g_outer, **{f"pp::{n}": g_stage[n] for n in g_stage}}
        return loss, grads

    def step_fn(state, inputs, labels, lr, rng):
        pv_, bv_, opt_state_, step_no = (state["params"], state["buffers"],
                                         state["opt_state"],
                                         state["step_no"])
        if schedule == "1f1b":
            lv, grads = grads_1f1b(pv_, bv_, rng, inputs, labels)
            new_bufs = bv_  # buffer mutation not tracked under 1f1b
        else:
            (lv, new_bufs), grads = jax.value_and_grad(
                loss_of, has_aux=True)(pv_, bv_, rng, inputs, labels)
        new_pv, new_opt = optimizer.apply_gradients_pytree(
            grads, pv_, opt_state_, lr, step_no + 1)
        return {"params": new_pv, "buffers": new_bufs,
                "opt_state": new_opt, "step_no": step_no + 1}, lv

    state_sharding = {"params": pv_shard, "buffers": {n: repl for n in obv},
                      "opt_state": os_shard, "step_no": repl}
    jit_step = jax.jit(step_fn, out_shardings=(state_sharding, repl),
                       donate_argnums=(0,) if donate else ())
    state = {"params": pv_all, "buffers": obv, "opt_state": opt_state,
             "step_no": jnp.zeros((), "int32")}

    def step(state, inputs, labels, lr=None, rng=None):
        inputs = tuple(
            jax.device_put(x._value if isinstance(x, Tensor)
                           else jnp.asarray(x),
                           batch_sharding(
                               np.ndim(x._value if isinstance(x, Tensor)
                                       else x), mesh, dp_axis))
            for x in inputs)
        labels = tuple(
            jax.device_put(x._value if isinstance(x, Tensor)
                           else jnp.asarray(x),
                           batch_sharding(
                               np.ndim(x._value if isinstance(x, Tensor)
                                       else x), mesh, dp_axis))
            for x in labels)
        lr = jnp.asarray(optimizer.get_lr() if lr is None else lr,
                         "float32")
        rng = rng if rng is not None else frandom.get_rng_key()
        return jit_step(state, inputs, labels, lr, rng)

    step.jitted = jit_step
    step.state_sharding = state_sharding
    return step, state


# ---------------------------------------------------------------------------
# 1F1B schedule (manual-gradient interleaved pipeline)
# ---------------------------------------------------------------------------
#
# Reference: SectionWorker's F-then-B is GPipe; Megatron-style 1F1B caps
# in-flight activations at pp instead of n_micro. Here the whole
# fwd+bwd+grad-accumulation runs as ONE SPMD loop with manual vjps —
# jax.grad is not used, so no AD residuals accumulate across the loop;
# the only activation storage is an x-stash of pp microbatch inputs.
#
# Schedule (derived; makespan-optimal 2*(n_micro+pp-1) half-steps):
#   device d runs F of microbatch m at step tau = d + 2m
#                 B of microbatch m at step tau = 2pp - 1 - d + 2m
# F and B slots have opposite parity per device (never collide), every
# ring hop lands exactly one step before its consumer, and in-flight
# microbatches never exceed pp (stash slot = m mod pp).

def _one_f_one_b_inner(block_apply, pre_apply, post_apply, loss_fn,
                       stage_params, opv, obv, ids_micro, labels_micro,
                       rng, axis_name, n_micro, pp, dp_axis=None):
    from ..framework.autograd import trace_mode
    from ..framework.tensor import Tensor

    d = lax.axis_index(axis_name)
    steps = 2 * (n_micro + pp - 1)
    perm_f = [(i, (i + 1) % pp) for i in range(pp)]
    perm_b = [(i, (i - 1) % pp) for i in range(pp)]

    stage_fn = _make_stage_fn(block_apply, True)

    def stage_key(m):
        return jax.random.fold_in(jax.random.fold_in(rng, d), m)

    def pre_of(m):
        xs = [lax.dynamic_index_in_dim(x, m, 0, keepdims=False)
              for x in ids_micro]
        out, _ = pre_apply(opv, obv, jax.random.fold_in(rng, m), True, *xs)
        return out

    def head_loss(opv_, y, labels_m, key):
        out, _ = post_apply(opv_, obv, key, True, y)
        with trace_mode():
            wout = jax.tree_util.tree_map(lambda v: Tensor(v), out)
            wlab = [Tensor(v) for v in labels_m]
            lv = loss_fn(wout, wlab)
        lv_raw = lv._value if isinstance(lv, Tensor) else lv
        return jnp.mean(lv_raw.astype("float32"))

    # probe shapes with abstract eval only
    act = jax.eval_shape(pre_of, 0)
    mb_shape, act_dtype = act.shape, act.dtype

    zeros_act = jnp.zeros(mb_shape, act_dtype)
    g_stage0 = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    g_outer0 = jax.tree_util.tree_map(jnp.zeros_like, opv)

    def f_branch(op):
        (tau, ring_f, ring_b, x_stash, y_prev, g_stage, g_outer,
         loss_acc) = op
        m_f = (tau - d) // 2
        m_safe = jnp.clip(m_f, 0, n_micro - 1)
        x_in = jnp.where(d == 0, pre_of(m_safe), ring_f)
        y = stage_fn(stage_params, x_in, stage_key(m_safe))
        x_stash = lax.dynamic_update_index_in_dim(
            x_stash, x_in, m_safe % pp, 0)
        y_prev = jnp.where(d == pp - 1, y, y_prev)
        return (y, jnp.zeros_like(ring_b), x_stash, y_prev,
                g_stage, g_outer, loss_acc)

    def b_branch(op):
        (tau, ring_f, ring_b, x_stash, y_prev, g_stage, g_outer,
         loss_acc) = op
        m_b = (tau - (2 * pp - 1 - d)) // 2
        m_safe = jnp.clip(m_b, 0, n_micro - 1)
        labels_m = [lax.dynamic_index_in_dim(l, m_safe, 0, keepdims=False)
                    for l in labels_micro]
        # cotangent into this stage's output: loss head on the last
        # stage (y from the previous step), ring hop elsewhere
        lv_m, (g_post, dy_head) = jax.value_and_grad(
            head_loss, argnums=(0, 1))(opv, y_prev, labels_m,
                                       jax.random.fold_in(rng, m_safe))
        dy = jnp.where(d == pp - 1, dy_head / n_micro, ring_b)
        x_in = lax.dynamic_index_in_dim(x_stash, m_safe % pp, 0,
                                        keepdims=False)
        key_m = stage_key(m_safe)
        _, stage_vjp = jax.vjp(
            lambda p, h: stage_fn(p, h, key_m), stage_params, x_in)
        dstage, dx = stage_vjp(dy)
        g_stage = jax.tree_util.tree_map(jnp.add, g_stage, dstage)
        # pre-section grads: replay pre's vjp with the stage-0 input
        # cotangent (non-zero contribution only on device 0)
        xs_m = [lax.dynamic_index_in_dim(x, m_safe, 0, keepdims=False)
                for x in ids_micro]
        _, pre_vjp = jax.vjp(
            lambda ov: pre_apply(ov, obv, jax.random.fold_in(rng, m_safe),
                                 True, *xs_m)[0], opv)
        (g_pre,) = pre_vjp(dx)
        is_first = (d == 0).astype("float32")
        is_last = (d == pp - 1).astype("float32")
        g_outer = jax.tree_util.tree_map(
            lambda g, a, b: g + is_first * a + is_last * b / n_micro,
            g_outer, g_pre, g_post)
        loss_acc = loss_acc + is_last * lv_m / n_micro
        return (jnp.zeros_like(ring_f), dx, x_stash, y_prev,
                g_stage, g_outer, loss_acc)

    def idle_branch(op):
        (tau, ring_f, ring_b, x_stash, y_prev, g_stage, g_outer,
         loss_acc) = op
        return (jnp.zeros_like(ring_f), jnp.zeros_like(ring_b), x_stash,
                y_prev, g_stage, g_outer, loss_acc)

    def body(tau, carry):
        ring_f, ring_b, x_stash, y_prev, g_stage, g_outer, loss_acc = carry
        mf2 = tau - d
        is_f = (mf2 % 2 == 0) & (mf2 >= 0) & (mf2 < 2 * n_micro)
        mb2 = tau - (2 * pp - 1 - d)
        is_b = (mb2 % 2 == 0) & (mb2 >= 0) & (mb2 < 2 * n_micro)
        idx = jnp.int32(0) + is_f.astype("int32") + 2 * is_b.astype("int32")
        op = (tau, ring_f, ring_b, x_stash, y_prev, g_stage, g_outer,
              loss_acc)
        (y_send, dx_send, x_stash, y_prev, g_stage, g_outer,
         loss_acc) = lax.switch(idx, [idle_branch, f_branch, b_branch], op)
        # collectives run unconditionally (identical program on all ranks)
        ring_f = lax.ppermute(y_send, axis_name, perm_f)
        ring_b = lax.ppermute(dx_send, axis_name, perm_b)
        return (ring_f, ring_b, x_stash, y_prev, g_stage, g_outer,
                loss_acc)

    x_stash0 = jnp.zeros((pp,) + mb_shape, act_dtype)
    carry = (zeros_act, zeros_act, x_stash0, zeros_act, g_stage0, g_outer0,
             jnp.zeros((), "float32"))
    carry = lax.fori_loop(0, steps, body, carry)
    _, _, _, _, g_stage, g_outer, loss_acc = carry
    # outer grads / loss live on one stage each — replicate across pp
    g_outer = lax.psum(g_outer, axis_name)
    loss = lax.psum(loss_acc, axis_name)
    if dp_axis is not None:
        g_stage = lax.pmean(g_stage, dp_axis)
        g_outer = lax.pmean(g_outer, dp_axis)
        loss = lax.pmean(loss, dp_axis)
    return loss, g_stage, g_outer
