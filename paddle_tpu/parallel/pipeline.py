"""Pipeline parallelism (reference: PipelineOptimizer
`fluid/optimizer.py:3718` + `fleet/meta_optimizers/pipeline_optimizer.py`
+ `framework/section_worker.cc:49-105` F-then-B microbatch schedule over
send_v2/recv_v2).

TPU-native redesign: stages live on the 'pp' mesh axis under shard_map;
stage parameters are STACKED on a leading pp-sharded axis (each device
holds its stage's slice), activations flow around the ring with
lax.ppermute, and the GPipe F-then-B schedule is a lax.fori_loop over
micro-steps. XLA overlaps the ppermute with stage compute (the analogue of
the reference's separate comm stream).

Requires homogeneous stages (same params/activation shapes per stage) —
the standard TPU formulation for transformer stacks.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["gpipe_spmd", "pipeline_forward"]


def pipeline_forward(stage_fn: Callable, stage_params, x, *, axis_name="pp",
                     n_micro: int):
    """Run inside shard_map over `axis_name`.

    stage_fn(params, micro_x) -> micro_y : one stage's forward.
    stage_params: THIS device's stage params (unstacked leaves).
    x: [n_micro, mb, ...] microbatched input, replicated across pp
       (only stage 0's reads matter).
    Returns [n_micro, mb, ...] outputs valid on the LAST stage.

    GPipe forward schedule: at step t, device d processes microbatch
    t - d (if in range); activations hop d→d+1 each step. Total steps =
    n_micro + pp - 1.
    """
    pp = lax.axis_size(axis_name)
    d = lax.axis_index(axis_name)
    steps = n_micro + pp - 1
    mb_shape = x.shape[1:]

    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def body(t, carry):
        buf_in, outs = carry
        # stage 0 injects microbatch t (if valid); others use ring input
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        inject = lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False)
        cur = jnp.where(d == 0, inject, buf_in)
        my_mb = t - d  # which microbatch this device processes now
        active = (my_mb >= 0) & (my_mb < n_micro)
        y = stage_fn(stage_params, cur)
        y = jnp.where(active, y, jnp.zeros_like(y))
        # last stage stores result
        out_idx = jnp.clip(my_mb, 0, n_micro - 1)
        store = (d == pp - 1) & active
        prev = lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(store, y, prev), out_idx, 0)
        nxt = lax.ppermute(y, axis_name, perm)
        return nxt, outs

    buf0 = jnp.zeros(mb_shape, x.dtype)
    outs0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
    _, outs = lax.fori_loop(0, steps, body, (buf0, outs0))
    return outs[None]  # [1, n_micro, ...] per stage; caller takes [-1]


def gpipe_spmd(stage_fn: Callable, mesh, n_micro: int, axis_name="pp"):
    """Wrap a homogeneous stage function into a pipelined forward over the
    mesh's pp axis.

    Usage:
      fwd = gpipe_spmd(stage_fn, mesh, n_micro=4)
      y = fwd(stacked_params, x)[-1]  # stacked_params leaves: [pp, ...]
                                      # x: [n_micro, mb, ...]
    Output is [pp, n_micro, ...]; index [-1] is the last stage's result.
    Gradients flow through ppermute (its transpose is the reverse
    permute), so jax.grad over this forward IS the backward schedule —
    the reference needs hand-inserted send/recv grad ops
    (`section_worker.cc`), here it's transposition.
    """
    inner = functools.partial(pipeline_forward, stage_fn,
                              axis_name=axis_name, n_micro=n_micro)

    def wrapper(stacked_params, x):
        def shard_fn(params_slice, x_rep):
            params_local = jax.tree_util.tree_map(
                lambda a: jnp.squeeze(a, 0), params_slice)
            return inner(params_local, x_rep)
        param_specs = jax.tree_util.tree_map(
            lambda _: P(axis_name), stacked_params)
        return jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(param_specs, P()),
            out_specs=P(axis_name),
            check_vma=False)(stacked_params, x)
    return wrapper
