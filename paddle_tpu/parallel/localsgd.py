"""LocalSGD / adaptive LocalSGD and per-worker DGC as a shard_map step.

Reference: `fleet/meta_optimizers/localsgd_optimizer.py` (plain LocalSGD at
`:24`, adaptive at `:195` whose next-interval rule is
``k = sqrt(lr_0 * avg_loss / (lr * loss_0) * init_k)`` at `:422`) and
`dgc_optimizer.py:19`.

GSPMD cannot express "replicas that *diverge* between syncs" — it owns the
gradient allreduce.  So this builder drops down to `jax.shard_map` over the
'dp' mesh axis: every parameter / optimizer-state leaf carries a leading
replica axis sharded over 'dp', each worker runs an independent SGD
trajectory on its own batch shard (its own dropout rng, its own momentum),
and every ``k_steps`` the replicas are averaged with one `lax.pmean` over
ICI.  Between syncs NO parameter collective is issued — the actual point of
LocalSGD (comm every k steps instead of every step).

With ``dgc=True`` the step instead syncs every step, but each worker
top-k-masks its *local* gradient with error feedback before the explicit
`lax.psum` — the faithful per-worker DGC dataflow (see compression.py).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework import random as frandom
from ..framework.functional import functionalize
from ..framework.tensor import Tensor
from .compression import dgc_compress, dgc_init
from .mesh import get_mesh
from .spmd import compat_shard_map

__all__ = ["make_local_train_step", "local_write_back"]


def make_local_train_step(layer, optimizer, loss_fn: Callable, mesh=None,
                          k_steps=4, begin_step=1, adaptive=False,
                          max_k_steps=16, dgc=False, dgc_momentum=0.9,
                          dgc_sparsity=0.999, dp_axis="dp"):
    """Returns (step, state); same contract as make_sharded_train_step but
    params/opt-state/buffers carry a leading per-replica axis over 'dp'.

    state = {params, buffers, opt_state, dgc?, step_no, since_sync, k,
             loss0, lr0}; step(state, inputs, labels, lr, rng) ->
    (state, loss) with loss already averaged over replicas.
    """
    mesh = mesh or get_mesh()
    dp = int(mesh.shape[dp_axis])
    apply_fn, pv, bv = functionalize(layer)
    opt_state = optimizer.init_state_pytree(pv)

    def stack(v):
        return jnp.broadcast_to(v[None], (dp,) + v.shape)

    shd = NamedSharding(mesh, P(dp_axis))
    rep = NamedSharding(mesh, P())
    put_s = lambda t: jax.tree_util.tree_map(
        lambda v: jax.device_put(stack(v), shd), t)

    state = {
        "params": put_s(pv), "buffers": put_s(bv),
        "opt_state": put_s(opt_state),
        "step_no": jnp.zeros((), "int32"),
        "since_sync": jnp.zeros((), "int32"),
        "k": jnp.asarray(k_steps, "int32"),
        "loss0": jnp.zeros((), "float32"),
        "lr0": jnp.zeros((), "float32"),
    }
    if dgc:
        state["dgc"] = put_s(dgc_init(pv))

    def loss_of(pv_, bv_, rng, inputs, labels):
        from ..framework.autograd import trace_mode
        out, new_bufs = apply_fn(pv_, bv_, rng, True, *inputs)
        with trace_mode():
            wout = jax.tree_util.tree_map(lambda x: Tensor(x), out)
            wlab = [Tensor(x) for x in labels]
            lv = loss_fn(wout, wlab)
        lv_raw = lv._value if isinstance(lv, Tensor) else lv
        return jnp.mean(lv_raw.astype("float32")), new_bufs

    unblk = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
    reblk = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)

    def local_step(state_, inputs, labels, lr, rng):
        pv_ = unblk(state_["params"])
        bv_ = unblk(state_["buffers"])
        ov_ = unblk(state_["opt_state"])
        step_no = state_["step_no"]
        since = state_["since_sync"]
        k = state_["k"]
        widx = lax.axis_index(dp_axis)
        my_rng = jax.random.fold_in(rng, widx)

        (lv, new_bufs), grads = jax.value_and_grad(
            loss_of, has_aux=True)(pv_, bv_, my_rng, inputs, labels)
        avg_loss = lax.pmean(lv, dp_axis)

        new_state = dict(state_)
        if dgc:
            # per-worker top-k + error feedback, then explicit allreduce
            grads, new_dgc = dgc_compress(grads, unblk(state_["dgc"]),
                                          dgc_momentum, dgc_sparsity)
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, dp_axis), grads)
            new_state["dgc"] = reblk(new_dgc)

        new_pv, new_ov = optimizer.apply_gradients_pytree(
            grads, pv_, ov_, lr, step_no + 1)

        if not dgc:
            do_sync = jnp.logical_and(step_no + 1 >= begin_step,
                                      since + 1 >= k)
            new_pv = lax.cond(
                do_sync,
                lambda t: jax.tree_util.tree_map(
                    lambda p: lax.pmean(p, dp_axis), t),
                lambda t: t, new_pv)
            new_state["since_sync"] = jnp.where(do_sync, 0, since + 1)
            if adaptive:
                # first sync pins (loss0, lr0); later syncs rescale k
                first = state_["loss0"] <= 0.0
                loss0 = jnp.where(jnp.logical_and(do_sync, first),
                                  avg_loss, state_["loss0"])
                lr0 = jnp.where(jnp.logical_and(do_sync, first),
                                lr, state_["lr0"])
                next_k = jnp.ceil(jnp.sqrt(
                    lr0 * avg_loss / (lr * jnp.maximum(loss0, 1e-12))
                    * float(k_steps)))
                next_k = jnp.clip(next_k, 1, max_k_steps).astype("int32")
                new_state["k"] = jnp.where(
                    jnp.logical_and(do_sync, jnp.logical_not(first)),
                    next_k, k)
                new_state["loss0"] = loss0
                new_state["lr0"] = lr0

        new_state["params"] = reblk(new_pv)
        new_state["buffers"] = reblk(new_bufs)
        new_state["opt_state"] = reblk(new_ov)
        new_state["step_no"] = step_no + 1
        return new_state, avg_loss

    blk = lambda t: jax.tree_util.tree_map(lambda _: P(dp_axis), t)
    scalar = P()
    state_spec = {n: (blk(v) if n in ("params", "buffers", "opt_state",
                                      "dgc") else scalar)
                  for n, v in state.items()}

    def sharded(state_, inputs, labels, lr, rng):
        in_specs = (state_spec,
                    tuple(P(dp_axis) for _ in inputs),
                    tuple(P(dp_axis) for _ in labels), scalar, scalar)
        fn = compat_shard_map(local_step, mesh=mesh, in_specs=in_specs,
                              out_specs=(state_spec, scalar),
                              check=False)
        return fn(state_, inputs, labels, lr, rng)

    jit_step = jax.jit(sharded, donate_argnums=(0,))

    def step(state_, inputs, labels, lr=None, rng=None):
        inputs = tuple(
            jax.device_put(x._value if isinstance(x, Tensor)
                           else jnp.asarray(x), shd) for x in inputs)
        labels = tuple(
            jax.device_put(x._value if isinstance(x, Tensor)
                           else jnp.asarray(x), shd) for x in labels)
        lr = jnp.asarray(optimizer.get_lr() if lr is None else lr,
                         "float32")
        rng = rng if rng is not None else frandom.get_rng_key()
        return jit_step(state_, inputs, labels, lr, rng)

    step.jitted = jit_step
    return step, state


def local_write_back(layer, state):
    """Average the per-replica params back into the imperative Layer."""
    from ..framework.functional import get_buffers, get_params
    params = get_params(layer)
    for n, v in state["params"].items():
        params[n]._value = jnp.mean(v, axis=0)
    buffers = get_buffers(layer)
    for n, v in state["buffers"].items():
        buffers[n]._value = jnp.mean(
            v, axis=0).astype(v.dtype) if jnp.issubdtype(
            v.dtype, jnp.floating) else v[0]
