"""Device mesh management.

The reference manages NCCL rings keyed by ring_id
(`platform/collective_helper.h:65` NCCLCommContext) with TCP bootstrap
(`gen_comm_id_helper.cc`). TPU-native replacement: ONE `jax.sharding.Mesh`
whose named axes (dp/mp/pp/sp/ep) take the place of rings; collectives are
compiled into programs over those axes. Bootstrap = jax.distributed
(coordinator address), no nccl-id plumbing.
"""
from __future__ import annotations

import contextlib
import math
import threading
from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["create_mesh", "get_mesh", "set_mesh", "mesh_scope", "axis_size",
           "named_sharding", "DEFAULT_AXES", "replicated", "P"]

P = PartitionSpec
DEFAULT_AXES = ("dp", "mp", "pp", "sp", "ep")


class _State(threading.local):
    def __init__(self):
        self.mesh: Optional[Mesh] = None


_state = _State()


def create_mesh(axes: Dict[str, int] = None, devices=None) -> Mesh:
    """create_mesh({'dp': 2, 'mp': 4}) — -1 means 'rest of the devices'."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes or {"dp": n})
    known = math.prod(v for v in axes.values() if v > 0)
    rest = [k for k, v in axes.items() if v in (-1, None)]
    if rest:
        assert len(rest) == 1, "only one -1 axis allowed"
        axes[rest[0]] = n // known
        known = n
    need = math.prod(axes.values())
    assert need <= n, f"mesh {axes} needs {need} devices, only {n} present"
    arr = np.asarray(devices[:need]).reshape(tuple(axes.values()))
    mesh = Mesh(arr, tuple(axes.keys()))
    _state.mesh = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _state.mesh


def set_mesh(mesh: Optional[Mesh]):
    _state.mesh = mesh


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    prev = _state.mesh
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def axis_size(axis: str) -> int:
    mesh = get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return 1
    return mesh.shape[axis]


def named_sharding(*spec) -> Optional[NamedSharding]:
    mesh = get_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated() -> Optional[NamedSharding]:
    return named_sharding()
