"""Protocol-buffer wire-format encoder/decoder (no protobuf runtime needed).

The ONNX model format is an ordinary proto3 message; its wire encoding is
just tagged varints/length-delimited fields. This module implements exactly
that subset so `paddle.onnx.export` can emit real `.onnx` bytes in an image
without the `onnx`/`protobuf` packages (reference `python/paddle/onnx/
export.py` delegates to the external paddle2onnx package instead).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

__all__ = ["varint", "tag", "field_varint", "field_bytes", "field_string",
           "field_message", "field_float", "decode"]


def varint(n: int) -> bytes:
    """Unsigned LEB128."""
    if n < 0:  # two's-complement 64-bit, as protobuf encodes negative ints
        n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field_no: int, wire_type: int) -> bytes:
    return varint((field_no << 3) | wire_type)


def field_varint(field_no: int, value: int) -> bytes:
    return tag(field_no, 0) + varint(int(value))


def field_bytes(field_no: int, payload: bytes) -> bytes:
    return tag(field_no, 2) + varint(len(payload)) + payload


def field_string(field_no: int, s: str) -> bytes:
    return field_bytes(field_no, s.encode("utf-8"))


field_message = field_bytes


def field_float(field_no: int, value: float) -> bytes:
    return tag(field_no, 5) + struct.pack("<f", float(value))


# ---------------------------------------------------------------------------
# decoding (for tests / introspection of emitted models)
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def decode(buf: bytes) -> Dict[int, List]:
    """Parse one message level into {field_no: [raw values]}.

    Varint fields decode to int; length-delimited fields stay `bytes`
    (call decode() again for nested messages); fixed32 floats decode to
    float. Repeated fields accumulate in list order.
    """
    out: Dict[int, List] = {}
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field_no, wire_type = key >> 3, key & 7
        if wire_type == 0:
            val, i = _read_varint(buf, i)
        elif wire_type == 2:
            ln, i = _read_varint(buf, i)
            val = buf[i:i + ln]
            i += ln
        elif wire_type == 5:
            val = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire_type == 1:
            val = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire_type}")
        out.setdefault(field_no, []).append(val)
    return out

