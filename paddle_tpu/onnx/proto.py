"""ONNX message builders over the raw wire encoder.

Field numbers follow the public onnx.proto3 schema (onnx/onnx.proto):
ModelProto{ir_version=1, producer_name=2, producer_version=3, domain=4,
model_version=5, doc_string=6, graph=7, opset_import=8},
GraphProto{node=1, name=2, initializer=5, doc_string=10, input=11,
output=12, value_info=13},
NodeProto{input=1, output=2, name=3, op_type=4, attribute=5, doc_string=6,
domain=7},
AttributeProto{name=1, f=2, i=3, s=4, t=5, g=6, floats=7, ints=8,
strings=9, type=20},
TensorProto{dims=1, data_type=2, name=8, raw_data=9},
ValueInfoProto{name=1, type=2}, TypeProto{tensor_type=1},
TypeProto.Tensor{elem_type=1, shape=2}, TensorShapeProto{dim=1},
Dimension{dim_value=1, dim_param=2},
OperatorSetIdProto{domain=1, version=2}.
"""
from __future__ import annotations

import numpy as np

from . import wire

__all__ = ["DTYPE_MAP", "np_dtype_to_onnx", "tensor_proto", "attr",
           "node_proto", "value_info", "graph_proto", "model_proto"]

# onnx TensorProto.DataType
DTYPE_MAP = {
    "float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
    "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
    "uint32": 12, "uint64": 13, "bfloat16": 16,
}

ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


def np_dtype_to_onnx(dt) -> int:
    name = np.dtype(dt).name if np.dtype(dt).name in DTYPE_MAP else str(dt)
    if name not in DTYPE_MAP:
        raise ValueError(f"no ONNX dtype for {dt}")
    return DTYPE_MAP[name]


def tensor_proto(name: str, array) -> bytes:
    """TensorProto with raw_data (little-endian)."""
    arr = np.asarray(array)
    if arr.dtype.name == "bfloat16" or str(arr.dtype) == "bfloat16":
        onnx_dt = 16
        raw = arr.view(np.uint16)
        raw = np.ascontiguousarray(raw, dtype="<u2").tobytes()
    else:
        onnx_dt = np_dtype_to_onnx(arr.dtype)
        raw = np.ascontiguousarray(
            arr.astype(arr.dtype.newbyteorder("<"))).tobytes()
    msg = b"".join(wire.field_varint(1, d) for d in arr.shape)
    msg += wire.field_varint(2, onnx_dt)
    msg += wire.field_string(8, name)
    msg += wire.field_bytes(9, raw)
    return msg


def attr(name: str, value) -> bytes:
    """AttributeProto from a python value (type inferred)."""
    msg = wire.field_string(1, name)
    if isinstance(value, bool):
        msg += wire.field_varint(3, int(value))
        msg += wire.field_varint(20, ATTR_INT)
    elif isinstance(value, int):
        msg += wire.field_varint(3, value)
        msg += wire.field_varint(20, ATTR_INT)
    elif isinstance(value, float):
        msg += wire.field_float(2, value)
        msg += wire.field_varint(20, ATTR_FLOAT)
    elif isinstance(value, str):
        msg += wire.field_bytes(4, value.encode())
        msg += wire.field_varint(20, ATTR_STRING)
    elif isinstance(value, bytes):
        msg += wire.field_bytes(4, value)
        msg += wire.field_varint(20, ATTR_STRING)
    elif isinstance(value, np.ndarray):
        msg += wire.field_message(5, tensor_proto(name, value))
        msg += wire.field_varint(20, ATTR_TENSOR)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, np.integer)) for v in value):
            for v in value:
                msg += wire.field_varint(8, int(v))
            msg += wire.field_varint(20, ATTR_INTS)
        elif all(isinstance(v, (float, np.floating)) for v in value):
            import struct
            payload = b"".join(struct.pack("<f", float(v)) for v in value)
            msg += wire.field_bytes(7, payload)
            msg += wire.field_varint(20, ATTR_FLOATS)
        else:
            for v in value:
                msg += wire.field_bytes(9, str(v).encode())
            msg += wire.field_varint(20, ATTR_STRINGS)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return msg


def node_proto(op_type: str, inputs, outputs, name: str = "",
               attrs: dict | None = None) -> bytes:
    msg = b"".join(wire.field_string(1, i) for i in inputs)
    msg += b"".join(wire.field_string(2, o) for o in outputs)
    if name:
        msg += wire.field_string(3, name)
    msg += wire.field_string(4, op_type)
    for k, v in (attrs or {}).items():
        msg += wire.field_message(5, attr(k, v))
    return msg


def value_info(name: str, shape, np_dtype) -> bytes:
    dims = b""
    for d in shape:
        if isinstance(d, str):
            dims += wire.field_message(1, wire.field_string(2, d))
        else:
            dims += wire.field_message(1, wire.field_varint(1, int(d)))
    shape_msg = dims
    tensor_type = wire.field_varint(1, np_dtype_to_onnx(np_dtype))
    tensor_type += wire.field_message(2, shape_msg)
    type_msg = wire.field_message(1, tensor_type)
    return wire.field_string(1, name) + wire.field_message(2, type_msg)


def graph_proto(nodes, name, initializers, inputs, outputs) -> bytes:
    msg = b"".join(wire.field_message(1, n) for n in nodes)
    msg += wire.field_string(2, name)
    msg += b"".join(wire.field_message(5, t) for t in initializers)
    msg += b"".join(wire.field_message(11, i) for i in inputs)
    msg += b"".join(wire.field_message(12, o) for o in outputs)
    return msg


def model_proto(graph: bytes, opset_version: int = 13,
                producer: str = "paddle_tpu") -> bytes:
    opset = wire.field_string(1, "") + wire.field_varint(2, opset_version)
    msg = wire.field_varint(1, 7)                     # ir_version 7
    msg += wire.field_string(2, producer)
    msg += wire.field_string(3, "1.0")
    msg += wire.field_message(7, graph)
    msg += wire.field_message(8, opset)
    return msg
