"""paddle.onnx.export — real ONNX emission from the XLA trace.

Reference `python/paddle/onnx/export.py` shells out to the external
paddle2onnx package, which walks the ProgramDesc op list. The TPU-native
design exports from the *jaxpr* instead: the layer's forward is traced once
(exactly what jit/XLA compile), and each jaxpr primitive maps onto an ONNX
op. That gives the exporter the same closed, small vocabulary XLA itself
consumes — softmax/layernorm/gelu arrive pre-decomposed into primitives, so
one table covers every model the framework can jit.

Parameters/buffers become ONNX initializers under their state_dict names.
Primitives whose inputs are all compile-time constants are folded eagerly
(so iota/eye/masks melt into initializers instead of op chains).
"""
from __future__ import annotations

import numpy as np

from . import proto

__all__ = ["export", "JaxprToOnnx", "UnsupportedOnnxExport"]


class UnsupportedOnnxExport(NotImplementedError):
    pass


_FOLD_LIMIT_BYTES = 1 << 20   # don't materialize folded constants above 1MB


def _np(x):
    arr = np.asarray(x)
    if str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    return arr


class JaxprToOnnx:
    def __init__(self):
        self.nodes = []            # encoded NodeProto bytes
        self.initializers = {}     # name -> encoded TensorProto
        self.consts = {}           # jaxpr Var -> np value (foldable)
        self.names = {}            # jaxpr Var -> onnx tensor name
        self._n = 0

    # -- naming -----------------------------------------------------------
    def fresh(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def add_initializer(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers[name] = proto.tensor_proto(name, _np(arr))
        return name

    def name_of(self, atom):
        """ONNX tensor name for a jaxpr atom (Var or Literal)."""
        from jax.extend.core import Literal
        if isinstance(atom, Literal):
            return self.add_initializer(np.asarray(atom.val,
                                                   atom.aval.dtype), "lit")
        if atom not in self.names:
            if atom in self.consts:
                self.names[atom] = self.add_initializer(self.consts[atom])
            else:
                self.names[atom] = self.fresh()
        return self.names[atom]

    def const_of(self, atom):
        """numpy value if the atom is compile-time constant, else None."""
        from jax.extend.core import Literal
        if isinstance(atom, Literal):
            return np.asarray(atom.val)
        return self.consts.get(atom)

    def emit(self, op_type, in_names, out_names, attrs=None):
        self.nodes.append(proto.node_proto(
            op_type, in_names, out_names, self.fresh(op_type.lower()),
            attrs))

    def emit1(self, op_type, in_names, eqn, attrs=None):
        out = self.name_for_out(eqn.outvars[0])
        self.emit(op_type, in_names, [out], attrs)

    def name_for_out(self, var):
        if var not in self.names:
            self.names[var] = self.fresh()
        return self.names[var]

    # -- conversion -------------------------------------------------------
    def run_jaxpr(self, jaxpr):
        for eqn in jaxpr.eqns:
            self.eqn(eqn)

    def eqn(self, eqn):
        prim = eqn.primitive.name
        # inline call-like primitives (jit boundaries, custom grads, remat)
        inner = None
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            if key in eqn.params:
                inner = eqn.params[key]
                break
        if inner is not None and prim not in ("while", "cond", "scan"):
            closed = inner if hasattr(inner, "jaxpr") else None
            ij = closed.jaxpr if closed is not None else inner
            consts = closed.consts if closed is not None else []
            sub = ij.invars
            for cv, c in zip(ij.constvars, consts):
                self.consts[cv] = _np(c)
            # custom_jvp_call passes (fn-consts..., args); align from the end
            args = list(eqn.invars)[-len(sub):] if sub else []
            for iv, outer in zip(sub, args):
                cval = self.const_of(outer)
                if cval is not None:
                    # stay foldable; name_of materializes lazily on demand
                    self.consts[iv] = cval
                else:
                    self.names[iv] = self.name_of(outer)
            self.run_jaxpr(ij)
            for ov, inner_ov in zip(eqn.outvars, ij.outvars):
                cval = self.const_of(inner_ov)
                if cval is not None:
                    self.consts[ov] = cval
                else:
                    self.names[ov] = self.name_of(inner_ov)
            return

        # constant folding
        in_consts = [self.const_of(a) for a in eqn.invars]
        if all(c is not None for c in in_consts) and prim not in (
                "while", "cond", "scan"):
            try:
                vals = eqn.primitive.bind(
                    *[np.asarray(c) for c in in_consts], **eqn.params)
                if not eqn.primitive.multiple_results:
                    vals = [vals]
                if sum(_np(v).nbytes for v in vals) <= _FOLD_LIMIT_BYTES:
                    for var, val in zip(eqn.outvars, vals):
                        self.consts[var] = _np(val)
                    return
            except Exception:
                pass

        handler = _HANDLERS.get(prim)
        if handler is None:
            raise UnsupportedOnnxExport(
                f"jaxpr primitive '{prim}' has no ONNX mapping "
                f"(eqn: {eqn})")
        handler(self, eqn)


# ---------------------------------------------------------------------------
# primitive handlers
# ---------------------------------------------------------------------------

_HANDLERS = {}


def _handles(*names):
    def deco(fn):
        for n in names:
            _HANDLERS[n] = fn
        return fn
    return deco


_SIMPLE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "neg": "Neg",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign", "floor": "Floor",
    "ceil": "Ceil", "erf": "Erf", "sin": "Sin", "cos": "Cos",
    "tan": "Tan", "asin": "Asin", "acos": "Acos", "atan": "Atan",
    "sinh": "Sinh", "cosh": "Cosh", "eq": "Equal", "lt": "Less",
    "le": "LessOrEqual", "gt": "Greater", "ge": "GreaterOrEqual",
    "and": "And", "or": "Or", "xor": "Xor", "not": "Not",
    "stop_gradient": "Identity", "copy": "Identity",
    "round": "Round", "rem": "Mod",
}


def _simple(conv, eqn):
    op = _SIMPLE[eqn.primitive.name]
    ins = [conv.name_of(a) for a in eqn.invars]
    attrs = {"fmod": 1} if op == "Mod" else None
    conv.emit1(op, ins, eqn, attrs)


for _name in _SIMPLE:
    _HANDLERS[_name] = _simple


@_handles("ne")
def _ne(conv, eqn):
    ins = [conv.name_of(a) for a in eqn.invars]
    tmp = conv.fresh("eq")
    conv.emit("Equal", ins, [tmp])
    conv.emit1("Not", [tmp], eqn)


@_handles("rsqrt")
def _rsqrt(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    tmp = conv.fresh("sqrt")
    conv.emit("Sqrt", [x], [tmp])
    conv.emit1("Reciprocal", [tmp], eqn)


@_handles("log1p")
def _log1p(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    one = conv.add_initializer(
        np.ones((), eqn.invars[0].aval.dtype), "one")
    tmp = conv.fresh("add")
    conv.emit("Add", [x, one], [tmp])
    conv.emit1("Log", [tmp], eqn)


@_handles("expm1")
def _expm1(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    one = conv.add_initializer(
        np.ones((), eqn.invars[0].aval.dtype), "one")
    tmp = conv.fresh("exp")
    conv.emit("Exp", [x], [tmp])
    conv.emit1("Sub", [tmp, one], eqn)


@_handles("integer_pow")
def _integer_pow(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    y = conv.add_initializer(
        np.asarray(eqn.params["y"], eqn.invars[0].aval.dtype), "exp")
    conv.emit1("Pow", [x, y], eqn)


@_handles("clamp")
def _clamp(conv, eqn):
    lo, x, hi = [conv.name_of(a) for a in eqn.invars]
    conv.emit1("Clip", [x, lo, hi], eqn)


@_handles("select_n")
def _select_n(conv, eqn):
    if len(eqn.invars) != 3:
        raise UnsupportedOnnxExport("select_n with >2 cases")
    pred, on_false, on_true = [conv.name_of(a) for a in eqn.invars]
    conv.emit1("Where", [pred, on_true, on_false], eqn)


@_handles("convert_element_type")
def _cast(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    to = proto.np_dtype_to_onnx(np.dtype(eqn.params["new_dtype"]))
    conv.emit1("Cast", [x], eqn, {"to": to})


@_handles("reshape")
def _reshape(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    shape = conv.add_initializer(
        np.asarray(eqn.params["new_sizes"], np.int64), "shape")
    conv.emit1("Reshape", [x, shape], eqn)


@_handles("squeeze")
def _squeeze(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    shape = conv.add_initializer(
        np.asarray(eqn.outvars[0].aval.shape, np.int64), "shape")
    conv.emit1("Reshape", [x, shape], eqn)


@_handles("expand_dims")
def _expand_dims(conv, eqn):
    _squeeze(conv, eqn)


@_handles("transpose")
def _transpose(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    conv.emit1("Transpose", [x], eqn,
               {"perm": [int(p) for p in eqn.params["permutation"]]})


@_handles("concatenate")
def _concat(conv, eqn):
    ins = [conv.name_of(a) for a in eqn.invars]
    conv.emit1("Concat", ins, eqn, {"axis": int(eqn.params["dimension"])})


@_handles("broadcast_in_dim")
def _broadcast_in_dim(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    shape = eqn.params["shape"]
    bd = eqn.params["broadcast_dimensions"]
    interim = [1] * len(shape)
    for src, dst in enumerate(bd):
        interim[dst] = eqn.invars[0].aval.shape[src]
    rs = conv.fresh("reshape")
    ishape = conv.add_initializer(np.asarray(interim, np.int64), "shape")
    conv.emit("Reshape", [x, ishape], [rs])
    target = conv.add_initializer(np.asarray(shape, np.int64), "shape")
    conv.emit1("Expand", [rs, target], eqn)


@_handles("slice")
def _slice(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    starts = np.asarray(eqn.params["start_indices"], np.int64)
    ends = np.asarray(eqn.params["limit_indices"], np.int64)
    strides = eqn.params["strides"]
    steps = np.asarray(strides if strides is not None
                       else [1] * len(starts), np.int64)
    axes = np.arange(len(starts), dtype=np.int64)
    ins = [x, conv.add_initializer(starts, "starts"),
           conv.add_initializer(ends, "ends"),
           conv.add_initializer(axes, "axes"),
           conv.add_initializer(steps, "steps")]
    conv.emit1("Slice", ins, eqn)


@_handles("rev")
def _rev(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    dims = list(eqn.params["dimensions"])
    n = len(dims)
    ins = [x,
           conv.add_initializer(np.full(n, -1, np.int64), "starts"),
           conv.add_initializer(
               np.full(n, np.iinfo(np.int64).min, np.int64), "ends"),
           conv.add_initializer(np.asarray(dims, np.int64), "axes"),
           conv.add_initializer(np.full(n, -1, np.int64), "steps")]
    conv.emit1("Slice", ins, eqn)


@_handles("pad")
def _pad(conv, eqn):
    cfg = eqn.params["padding_config"]
    if any(inner != 0 for _, _, inner in cfg):
        raise UnsupportedOnnxExport("interior padding")
    if any(lo < 0 or hi < 0 for lo, hi, _ in cfg):
        raise UnsupportedOnnxExport("negative padding")
    x = conv.name_of(eqn.invars[0])
    value = conv.name_of(eqn.invars[1])
    pads = [lo for lo, _, _ in cfg] + [hi for _, hi, _ in cfg]
    ins = [x, conv.add_initializer(np.asarray(pads, np.int64), "pads"),
           value]
    conv.emit1("Pad", ins, eqn, {"mode": "constant"})


@_handles("reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
          "reduce_or", "reduce_and")
def _reduce(conv, eqn):
    prim = eqn.primitive.name
    x = conv.name_of(eqn.invars[0])
    axes = [int(a) for a in eqn.params["axes"]]
    if prim == "reduce_sum":
        ax = conv.add_initializer(np.asarray(axes, np.int64), "axes")
        conv.emit1("ReduceSum", [x, ax], eqn, {"keepdims": 0})
        return
    if prim in ("reduce_or", "reduce_and"):
        # bool reduce: cast to int32, reduce, cast back
        op = "ReduceMax" if prim == "reduce_or" else "ReduceMin"
        t1, t2 = conv.fresh("cast"), conv.fresh("red")
        conv.emit("Cast", [x], [t1], {"to": 6})
        conv.emit(op, [t1], [t2], {"axes": axes, "keepdims": 0})
        conv.emit1("Cast", [t2], eqn, {"to": 9})
        return
    op = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
          "reduce_prod": "ReduceProd"}[prim]
    conv.emit1(op, [x], eqn, {"axes": axes, "keepdims": 0})


@_handles("argmax", "argmin")
def _argminmax(conv, eqn):
    op = "ArgMax" if eqn.primitive.name == "argmax" else "ArgMin"
    x = conv.name_of(eqn.invars[0])
    axes = eqn.params["axes"]
    out_dt = np.dtype(eqn.params["index_dtype"])
    raw = conv.fresh("arg")
    conv.emit(op, [x], [raw], {"axis": int(axes[0]), "keepdims": 0})
    conv.emit1("Cast", [raw], eqn,
               {"to": proto.np_dtype_to_onnx(out_dt)})


@_handles("iota")
def _iota(conv, eqn):
    # iota has no inputs, so the constant folder normally handles it;
    # reaching here means folding failed (e.g. result above the size cap)
    raise UnsupportedOnnxExport("iota larger than the fold limit")


@_handles("dot_general")
def _dot_general(conv, eqn):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars
    nl, nr = len(lhs.aval.shape), len(rhs.aval.shape)
    letters = iter("abcdefghijklmnopqrstuvwxyz")
    l_sub = [None] * nl
    r_sub = [None] * nr
    for i, j in zip(lb, rb):
        c = next(letters)
        l_sub[i] = c
        r_sub[j] = c
    for i, j in zip(lc, rc):
        c = next(letters)
        l_sub[i] = c
        r_sub[j] = c
    for i in range(nl):
        if l_sub[i] is None:
            l_sub[i] = next(letters)
    for j in range(nr):
        if r_sub[j] is None:
            r_sub[j] = next(letters)
    out = [l_sub[i] for i in lb]
    out += [l_sub[i] for i in range(nl) if i not in lb and i not in lc]
    out += [r_sub[j] for j in range(nr) if j not in rb and j not in rc]
    eqn_str = f"{''.join(l_sub)},{''.join(r_sub)}->{''.join(out)}"
    ins = [conv.name_of(lhs), conv.name_of(rhs)]
    conv.emit1("Einsum", ins, eqn, {"equation": eqn_str})


@_handles("conv_general_dilated")
def _conv(conv, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    nd = len(eqn.invars[0].aval.shape)
    identity = tuple(range(nd))
    if (tuple(dn.lhs_spec) != identity or tuple(dn.rhs_spec) != identity
            or tuple(dn.out_spec) != identity):
        raise UnsupportedOnnxExport(
            f"conv layout {dn} (exporter expects NCHW/OIHW)")
    if any(d != 1 for d in p["lhs_dilation"]):
        raise UnsupportedOnnxExport("transposed conv")
    x, w = [conv.name_of(a) for a in eqn.invars]
    pads = [lo for lo, _ in p["padding"]] + [hi for _, hi in p["padding"]]
    attrs = {"strides": [int(s) for s in p["window_strides"]],
             "pads": [int(v) for v in pads],
             "dilations": [int(d) for d in p["rhs_dilation"]],
             "group": int(p["feature_group_count"]),
             "kernel_shape": [int(k) for k in
                              eqn.invars[1].aval.shape[2:]]}
    conv.emit1("Conv", [x, w], eqn, attrs)


@_handles("reduce_window_max", "reduce_window_sum")
def _reduce_window(conv, eqn):
    p = eqn.params
    wd = p["window_dimensions"]
    ws = p["window_strides"]
    pad = p["padding"]
    if any(d != 1 for d in p.get("base_dilation", (1,) * len(wd))) or \
       any(d != 1 for d in p.get("window_dilation", (1,) * len(wd))):
        raise UnsupportedOnnxExport("dilated pooling")
    if wd[0] != 1 or wd[1] != 1:
        raise UnsupportedOnnxExport(f"pooling window {wd} (expect NCHW)")
    x = conv.name_of(eqn.invars[0])
    kernel = [int(k) for k in wd[2:]]
    attrs = {"kernel_shape": kernel,
             "strides": [int(s) for s in ws[2:]],
             "pads": [int(lo) for lo, _ in pad[2:]] +
                     [int(hi) for _, hi in pad[2:]]}
    if eqn.primitive.name == "reduce_window_max":
        conv.emit1("MaxPool", [x], eqn, attrs)
        return
    # sum-pool = AveragePool(count_include_pad) * prod(window)
    attrs["count_include_pad"] = 1
    avg = conv.fresh("avgpool")
    conv.emit("AveragePool", [x], [avg], attrs)
    scale = conv.add_initializer(
        np.asarray(float(np.prod(kernel)), eqn.invars[0].aval.dtype),
        "winsize")
    conv.emit1("Mul", [avg, scale], eqn)


@_handles("gather")
def _gather(conv, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    operand, indices = eqn.invars
    oshape = operand.aval.shape
    slice_sizes = p["slice_sizes"]
    cs = dn.collapsed_slice_dims
    sim = dn.start_index_map
    if len(cs) == 1 and tuple(sim) == tuple(cs):
        axis = cs[0]
        ok = all((slice_sizes[j] == oshape[j]) if j != axis
                 else slice_sizes[j] == 1 for j in range(len(oshape)))
        if ok:
            x = conv.name_of(operand)
            idx = conv.name_of(indices)
            ishape = indices.aval.shape
            if ishape and ishape[-1] == 1:
                rs = conv.fresh("idx")
                tgt = conv.add_initializer(
                    np.asarray(ishape[:-1], np.int64), "shape")
                conv.emit("Reshape", [idx, tgt], [rs])
                idx = rs
            conv.emit1("Gather", [x, idx], eqn, {"axis": int(axis)})
            return
    raise UnsupportedOnnxExport(f"general gather {dn}")


@_handles("dynamic_slice")
def _dynamic_slice(conv, eqn):
    starts = [conv.const_of(a) for a in eqn.invars[1:]]
    if any(s is None for s in starts):
        raise UnsupportedOnnxExport("dynamic_slice with traced start")
    x = conv.name_of(eqn.invars[0])
    sizes = eqn.params["slice_sizes"]
    shape = eqn.invars[0].aval.shape
    st = [int(np.clip(int(s), 0, shape[i] - sizes[i]))
          for i, s in enumerate(starts)]
    ends = [st[i] + sizes[i] for i in range(len(sizes))]
    ins = [x, conv.add_initializer(np.asarray(st, np.int64), "starts"),
           conv.add_initializer(np.asarray(ends, np.int64), "ends"),
           conv.add_initializer(np.arange(len(st), dtype=np.int64),
                                "axes"),
           conv.add_initializer(np.ones(len(st), np.int64), "steps")]
    conv.emit1("Slice", ins, eqn)


@_handles("cumsum")
def _cumsum(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    ax = conv.add_initializer(
        np.asarray(eqn.params["axis"], np.int64), "axis")
    conv.emit1("CumSum", [x, ax], eqn,
               {"reverse": int(eqn.params.get("reverse", False))})


@_handles("top_k")
def _top_k(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    k = conv.add_initializer(
        np.asarray([eqn.params["k"]], np.int64), "k")
    vals = conv.name_for_out(eqn.outvars[0])
    idx64 = conv.fresh("topk_idx")
    conv.emit("TopK", [x, k], [vals, idx64])
    conv.emit("Cast", [idx64], [conv.name_for_out(eqn.outvars[1])],
              {"to": 6})


@_handles("square")
def _square(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    conv.emit1("Mul", [x, x], eqn)


@_handles("exp2")
def _exp2(conv, eqn):
    x = conv.name_of(eqn.invars[0])
    two = conv.add_initializer(
        np.asarray(2.0, eqn.invars[0].aval.dtype), "two")
    conv.emit1("Pow", [two, x], eqn)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def export(layer, path, input_spec=None, opset_version=13,
           enable_onnx_checker=True, **configs):
    """Trace `layer.forward` (inference mode) and write `{path}.onnx`.

    Same call surface as the reference's paddle2onnx delegation; returns
    the written file path.
    """
    import jax

    from ..framework.functional import functionalize
    from ..jit import _spec_to_sds
    from ..nn.layer.layers import Layer

    if not isinstance(layer, Layer):
        raise TypeError("paddle.onnx.export expects an nn.Layer")
    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec")
    if opset_version < 13:
        # the emitted op forms (Einsum, axes-as-input ReduceSum/Slice/Pad)
        # need opset 13; stamping a lower version would be an invalid model
        import warnings
        warnings.warn(f"opset_version={opset_version} unsupported; "
                      "emitting opset 13")
        opset_version = 13

    apply_fn, pv, bv = functionalize(layer)
    sds = [_spec_to_sds(s) for s in input_spec]
    rng = jax.random.PRNGKey(0)

    pv_items = sorted(pv.items())
    bv_items = sorted(bv.items())

    def infer(params, buffers, *xs):
        out, _ = apply_fn(dict(params), dict(buffers), rng, False, *xs)
        return out

    closed = jax.make_jaxpr(infer)(
        dict(pv_items), dict(bv_items), *sds)

    # invars order: flattened params dict, flattened buffers dict, inputs.
    n_params = len(pv_items)
    n_bufs = len(bv_items)
    param_map = {}
    for i, (name, val) in enumerate(pv_items + bv_items):
        param_map[i] = (name, np.asarray(val))
    conv = JaxprToOnnx()
    in_names = []
    jaxpr = closed.jaxpr
    input_vars = jaxpr.invars[n_params + n_bufs:]
    for i, var in enumerate(input_vars):
        spec = input_spec[i] if i < len(input_spec) else None
        name = getattr(spec, "name", None) or f"x{i}"
        in_names.append(name)

    # rebind: params first in invars, so pass names accordingly
    all_names = []
    for i, var in enumerate(jaxpr.invars):
        if i < n_params + n_bufs:
            all_names.append(None)      # comes from param_map
        else:
            all_names.append(in_names[i - n_params - n_bufs])
    for var, val in zip(jaxpr.constvars, closed.consts):
        conv.consts[var] = _np(val)
    for i, var in enumerate(jaxpr.invars):
        if all_names[i] is None:
            name, val = param_map[i]
            conv.names[var] = name
            conv.initializers[name] = proto.tensor_proto(name, _np(val))
        else:
            conv.names[var] = all_names[i]
    conv.run_jaxpr(jaxpr)
    out_names = [conv.name_of(v) for v in jaxpr.outvars]

    inputs = [proto.value_info(all_names[n_params + n_bufs + i],
                               var.aval.shape, var.aval.dtype)
              for i, var in enumerate(input_vars)]
    outputs = [proto.value_info(n, v.aval.shape,
                                np.float32 if str(v.aval.dtype) ==
                                "bfloat16" else v.aval.dtype)
               for n, v in zip(out_names, jaxpr.outvars)]
    graph = proto.graph_proto(conv.nodes, "paddle_tpu_graph",
                              conv.initializers.values(), inputs, outputs)
    model = proto.model_proto(graph, opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path
