"""paddle.onnx — real ONNX export, no external packages.

The reference (`python/paddle/onnx/export.py`) delegates to the separate
paddle2onnx package, which walks the saved ProgramDesc. Here the exporter
is native: the layer is traced to a jaxpr (the same trace XLA compiles)
and each primitive maps to an ONNX-13 op; the protobuf wire format is
emitted directly (`wire.py`/`proto.py`), so the export works in an image
with neither `onnx` nor `protobuf` installed.
"""
from __future__ import annotations

from .export import JaxprToOnnx, UnsupportedOnnxExport, export

__all__ = ["export", "JaxprToOnnx", "UnsupportedOnnxExport"]
