"""paddle.onnx (reference `python/paddle/onnx/export.py` delegates to the
external paddle2onnx package). That package isn't in this image; export()
produces the framework's native serving artifact instead (StableHLO via
jit.save) and raises a clear error for strict ONNX requests."""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9,
           enable_onnx_checker=True, **configs):
    try:
        import paddle2onnx  # noqa: F401
    except ImportError:
        import warnings
        warnings.warn(
            "paddle2onnx is unavailable in this offline image; exporting "
            "the portable StableHLO serving artifact (jit.save) at the "
            "same path instead — loadable with paddle_tpu.jit.load / the "
            "inference predictor.")
        from .. import jit
        jit.save(layer, path, input_spec=input_spec)
        return path + ".pdmodel"
    raise NotImplementedError("paddle2onnx delegation not wired")
