"""Test env: force CPU with 8 virtual devices BEFORE jax import, so every
sharding/collective test runs the same code path the driver's
dryrun_multichip uses (xla_force_host_platform_device_count)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")
