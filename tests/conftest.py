"""Test env: force CPU with 8 virtual devices so every sharding/collective
test runs the same code path the driver's dryrun_multichip uses.

NOTE: this image's sitecustomize imports jax at interpreter start (axon TPU
tunnel), so setting JAX_PLATFORMS in os.environ here is too late — we must
go through jax.config before the first backend initialization instead.
"""
import os

# PADDLE_TPU_TEST_ON_CHIP=1 leaves the real TPU backend in place so the
# chip-only tests actually run. Use it with a -k selection of chip tests
# (e.g. `PADDLE_TPU_TEST_ON_CHIP=1 pytest -k bf16_parity_on_tpu`): the
# rest of the suite assumes the 8-virtual-device CPU mesh and will error
# on a 1-chip host.
_ON_CHIP = os.environ.get("PADDLE_TPU_TEST_ON_CHIP") == "1"

_flags = os.environ.get("XLA_FLAGS", "")
if not _ON_CHIP and "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")
if not _ON_CHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (already imported by sitecustomize; config still open)

if not _ON_CHIP:
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
