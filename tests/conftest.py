"""Test env: force CPU with 8 virtual devices so every sharding/collective
test runs the same code path the driver's dryrun_multichip uses.

NOTE: this image's sitecustomize imports jax at interpreter start (axon TPU
tunnel), so setting JAX_PLATFORMS in os.environ here is too late — we must
go through jax.config before the first backend initialization instead.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (already imported by sitecustomize; config still open)

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass
