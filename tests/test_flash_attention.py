"""Pallas flash-attention kernel parity tests (interpreter mode on CPU).

The reference has no fused attention op (MultiHeadAttention is composed in
Python, `python/paddle/nn/layer/transformer.py:87`); these tests guard OUR
kernel (paddle_tpu/ops/pallas_ops.py) against the reference math: fwd +
dq/dk/dv parity vs the dense jnp path across causal / padding-mask /
cross-attention shapes, plus dispatch-gate rules and dropout semantics.
Runs via FLAGS_flash_attention_interpret so CPU CI exercises the exact
kernel code the TPU runs.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.framework.flags import set_flags, get_flags
from paddle_tpu.ops import pallas_ops as po


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = get_flags(["FLAGS_flash_attention_interpret",
                     "FLAGS_use_flash_attention",
                     "FLAGS_flash_attention_min_seq"])
    set_flags({"FLAGS_flash_attention_interpret": True,
               "FLAGS_use_flash_attention": True,
               "FLAGS_flash_attention_min_seq": 128})
    yield
    set_flags(old)


def _mk(shape, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32), dtype)


def _dense_ref(q, k, v, bias, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias[:, None, None, :]
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((Sq, Sk), bool)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def _flash(q, k, v, bias, causal, scale):
    seed = jnp.zeros((), jnp.int32)
    return po.flash_attention_raw(q, k, v, bias, seed, causal, scale, 0.0)


CASES = [
    # (Sq, Sk, causal, padded)
    (128, 128, False, False),
    (128, 128, True, False),
    (256, 128, False, False),   # cross-attention, S_q != S_kv
    (128, 256, False, False),   # decoder memory attention shape
    (128, 128, False, True),
    (256, 256, True, True),
]


@pytest.mark.parametrize("sq,sk,causal,padded", CASES)
def test_flash_forward_parity(sq, sk, causal, padded):
    B, H, D = 2, 2, 32
    q = _mk((B, H, sq, D), 1)
    k = _mk((B, H, sk, D), 2)
    v = _mk((B, H, sk, D), 3)
    scale = 1.0 / D ** 0.5
    if padded:
        valid = np.ones((B, sk), np.float32)
        valid[0, sk // 2:] = 0.0       # half of batch-0's keys padded out
        bias = jnp.asarray(np.where(valid, 0.0, -1e30).astype(np.float32))
    else:
        bias = jnp.zeros((B, sk), jnp.float32)
    out = _flash(q, k, v, bias, causal, scale)
    ref = _dense_ref(q, k, v, bias, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("sq,sk,causal,padded", CASES)
def test_flash_grad_parity(sq, sk, causal, padded):
    B, H, D = 1, 2, 16
    q = _mk((B, H, sq, D), 4)
    k = _mk((B, H, sk, D), 5)
    v = _mk((B, H, sk, D), 6)
    scale = 1.0 / D ** 0.5
    if padded:
        valid = np.ones((B, sk), np.float32)
        valid[0, sk - sk // 4:] = 0.0
        bias = jnp.asarray(np.where(valid, 0.0, -1e30).astype(np.float32))
    else:
        bias = jnp.zeros((B, sk), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(_flash(q, k, v, bias, causal, scale)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_dense_ref(q, k, v, bias, causal, scale)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(gf, gr, "q k v".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{nm} mismatch")


def test_flash_bf16_forward_close():
    B, H, S, D = 2, 2, 128, 64
    q = _mk((B, H, S, D), 7, jnp.bfloat16)
    k = _mk((B, H, S, D), 8, jnp.bfloat16)
    v = _mk((B, H, S, D), 9, jnp.bfloat16)
    bias = jnp.zeros((B, S), jnp.float32)
    out = _flash(q, k, v, bias, True, 0.125)
    ref = _dense_ref(q, k, v, bias, True, 0.125)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


# ---------------------------------------------------------------------------
# dispatch gate
# ---------------------------------------------------------------------------

def test_gate_min_seq_default():
    # at the bench shape (seq 128) the dense path must win the dispatch:
    # flash was measured ~25% slower there (VERDICT r3) — regression guard
    assert not po.flash_supported((8, 12, 128, 64), min_seq=512)
    assert po.flash_supported((8, 12, 512, 64), min_seq=512)


def test_gate_reads_flag():
    set_flags({"FLAGS_flash_attention_min_seq": 256})
    assert not po.flash_supported((2, 2, 128, 64))
    assert po.flash_supported((2, 2, 256, 64))


def test_gate_cross_attention_shapes():
    q, kv = (2, 4, 256, 64), (2, 4, 128, 64)
    assert po.flash_supported(q, kv, kv, min_seq=128)
    # causal with S_q != S_kv: diagonals don't align — refuse
    assert not po.flash_supported(q, kv, kv, is_causal=True, min_seq=128)
    # k/v disagree
    assert not po.flash_supported(q, kv, (2, 4, 256, 64), min_seq=128)
    # head-count mismatch (GQA) unsupported
    assert not po.flash_supported(q, (2, 2, 128, 64), (2, 2, 128, 64),
                                  min_seq=128)
    # non-multiple-of-block kv length
    assert not po.flash_supported(q, (2, 4, 100, 64), (2, 4, 100, 64),
                                  min_seq=128)


def test_gate_mask_keyed_on_kv_length():
    q, kv = (2, 4, 256, 64), (2, 4, 128, 64)
    good = jnp.zeros((2, 1, 1, 128), jnp.float32)
    bad = jnp.zeros((2, 1, 1, 256), jnp.float32)   # q-length mask: refuse
    assert po.flash_supported(q, kv, kv, good, min_seq=128)
    assert not po.flash_supported(q, kv, kv, bad, min_seq=128)


def test_fallback_causal_decode_bottom_right_aligned():
    """is_causal with S_q < S_kv (KV-cache decode) must attend the whole
    prefix — bottom-right aligned diagonal, not jnp.tril's top-left."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.tensor import Tensor
    set_flags({"FLAGS_use_flash_attention": False})
    B, H, Sk, D = 1, 1, 16, 8
    q = Tensor(_mk((B, H, 1, D), 20))       # one new token
    k = Tensor(_mk((B, H, Sk, D), 21))
    v = Tensor(_mk((B, H, Sk, D), 22))
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    ref = _dense_ref(q._value, k._value, v._value, None, False,
                     1.0 / D ** 0.5)        # full attention over the cache
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_functional_cross_attention_no_crash():
    """Regression: maskless cross-attention S_q != S_kv used to pass the
    gate and die inside _flash_call's reshape (VERDICT r3 weak #3)."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.tensor import Tensor
    q = Tensor(_mk((1, 2, 256, 32), 10))
    kv = Tensor(_mk((1, 2, 128, 32), 11))
    out = F.scaled_dot_product_attention(q, kv, kv)
    ref = _dense_ref(q._value, kv._value, kv._value, None, False,
                     1.0 / 32 ** 0.5)
    np.testing.assert_allclose(np.asarray(out._value), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# dropout semantics
# ---------------------------------------------------------------------------

def test_fallback_dropout_on_probabilities():
    """The fallback must drop softmax PROBABILITIES (kernel semantics), not
    attention outputs: with p=0.5 an output row is a sub-sum of upscaled
    prob*V terms — its expectation matches the dense output, and rows are
    NOT exactly zero/2x-scaled copies (which output-dropout would give)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.tensor import Tensor
    set_flags({"FLAGS_use_flash_attention": False})
    B, H, S, D = 1, 1, 8, 4
    q = Tensor(_mk((B, H, S, D), 12))
    k = Tensor(_mk((B, H, S, D), 13))
    v = Tensor(jnp.ones((B, H, S, D), jnp.float32))
    paddle.seed(123)
    out = F.scaled_dot_product_attention(q, k, v, dropout_p=0.5,
                                         training=True)
    a = np.asarray(out._value)
    base = np.asarray(
        F.scaled_dot_product_attention(q, k, v, dropout_p=0.0)._value)
    # v == ones → dense output rows are exactly 1.0; prob-dropout rows are
    # sums of a random subset of upscaled probs — generically neither 0,
    # 1, nor 2 exactly, and different across rows
    assert not np.allclose(a, base)          # dropout did something
    zero_or_double = np.isclose(a, 0.0) | np.isclose(a, 2.0 * base)
    assert not zero_or_double.all(), \
        "looks like output-dropout, not probability-dropout"


def test_kernel_dropout_keep_rate_and_determinism():
    if not po._HAS_PALLAS:
        pytest.skip("no pallas")
    B, H, S, D = 1, 2, 128, 32
    q = _mk((B, H, S, D), 14)
    k = _mk((B, H, S, D), 15)
    v = jnp.ones((B, H, S, D), jnp.float32)
    bias = jnp.zeros((B, S), jnp.float32)
    seed = jnp.asarray(42, jnp.int32)
    call = functools.partial(po.flash_attention_raw, causal=False,
                             scale=1.0 / D ** 0.5, dropout_p=0.5)
    try:
        o1 = call(q, k, v, bias, seed)
    except Exception as e:  # TPU PRNG primitives may not interpret on CPU
        pytest.skip(f"in-kernel PRNG not interpretable here: {e}")
    o2 = call(q, k, v, bias, seed)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    o3 = call(q, k, v, bias, jnp.asarray(7, jnp.int32))
    assert not np.allclose(np.asarray(o1), np.asarray(o3))
    # keep-rate: with v=1 each output element is sum(upscaled kept probs);
    # mean over all rows ≈ 1.0 (unbiased estimator)
    assert abs(float(jnp.mean(o1)) - 1.0) < 0.15


def test_bf16_parity_on_tpu():
    """bf16 COMPILED-kernel parity vs dense SDPA on REAL TPU hardware
    (skipped on the CPU test mesh; run via PADDLE_TPU_TEST_ON_CHIP=1
    pytest -k bf16_parity). Must defeat the module fixture's interpret
    flag or it would validate interpreter math, not the Mosaic kernel."""
    plats = {d.platform for d in jax.devices()}
    if not ({"tpu", "axon"} & plats):
        pytest.skip("needs a real TPU chip")
    set_flags({"FLAGS_flash_attention_interpret": False})

    B, H, S, D = 2, 4, 1024, 64
    q = _mk((B, H, S, D), 0, jnp.bfloat16)
    k = _mk((B, H, S, D), 1, jnp.bfloat16)
    v = _mk((B, H, S, D), 2, jnp.bfloat16)
    bias = jnp.zeros((B, S), jnp.float32)
    scale = 1.0 / D ** 0.5

    out_f = jax.jit(lambda q, k, v: _flash(q, k, v, bias, True,
                                           scale))(q, k, v)
    out_d = jax.jit(lambda q, k, v: _dense_ref(q, k, v, None, True,
                                               scale))(q, k, v)
    err = float(jnp.max(jnp.abs(out_f.astype(jnp.float32)
                                - out_d.astype(jnp.float32))))
    assert err < 0.05, err

    gf = jax.jit(jax.grad(lambda q, k, v: _flash(
        q, k, v, bias, True, scale).astype(jnp.float32).sum(),
        argnums=(0, 1, 2)))(q, k, v)
    gd = jax.jit(jax.grad(lambda q, k, v: _dense_ref(
        q, k, v, None, True, scale).astype(jnp.float32).sum(),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gf, gd):
        e = float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b.astype(jnp.float32))))
        assert e < 0.3, e
