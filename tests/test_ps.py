"""Parameter-server tests (reference strategy: in-process localhost
cluster, `test_dist_fleet_base.py`). Tables are the native C++ core."""
import os

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (AsyncCommunicator, DenseTable,
                                       GeoCommunicator, PsClient, PsServer,
                                       SparseTable, TableConfig,
                                       native_available)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native ps core not built")


def test_dense_table_sgd():
    t = DenseTable(4, rule="sgd", lr=0.1)
    t.set(np.ones(4, np.float32))
    t.push(np.ones(4, np.float32))
    np.testing.assert_allclose(t.pull(), [0.9] * 4, rtol=1e-6)


def test_sparse_table_init_and_update():
    t = SparseTable(8, rule="sgd", lr=0.5, init_range=0.05)
    ids = np.array([3, 7, 3], np.int64)
    rows = t.pull(ids)
    assert rows.shape == (3, 8)
    np.testing.assert_allclose(rows[0], rows[2])  # same id, same init
    assert np.abs(rows).max() <= 0.05 + 1e-6
    g = np.ones((3, 8), np.float32)
    t.push(ids, g)
    rows2 = t.pull(ids)
    # id 3 got two grad rows → -0.5*2; id 7 one row → -0.5
    np.testing.assert_allclose(rows2[1], rows[1] - 0.5, rtol=1e-5)
    np.testing.assert_allclose(rows2[0], rows[0] - 1.0, rtol=1e-5)
    assert len(t) == 2


def test_sparse_table_save_load(tmp_path):
    t = SparseTable(4, rule="sgd", lr=0.1)
    ids = np.arange(10, dtype=np.int64)
    rows = t.pull(ids)
    p = str(tmp_path / "table.bin")
    assert t.save(p) == 10
    t2 = SparseTable(4, rule="sgd", lr=0.1)
    assert t2.load(p) == 10
    np.testing.assert_allclose(t2.pull(ids), rows)


@pytest.fixture
def cluster():
    tables = [TableConfig(0, "dense", size=8, rule="sgd", lr=0.1),
              TableConfig(1, "sparse", dim=4, rule="adam", lr=0.05)]
    server = PsServer("127.0.0.1:0", tables, n_workers=1)
    server.start()
    client = PsClient([f"127.0.0.1:{server.port}"])
    yield server, client
    client.close()
    server.stop()


def test_ps_dense_roundtrip(cluster):
    _, client = cluster
    client.set_dense(0, np.arange(8, dtype=np.float32))
    np.testing.assert_allclose(client.pull_dense(0), np.arange(8))
    client.push_dense(0, np.ones(8, np.float32))
    np.testing.assert_allclose(client.pull_dense(0),
                               np.arange(8) - 0.1, rtol=1e-5)


def test_ps_sparse_train_converges(cluster):
    """Worker pulls embedding rows, computes a toy loss grad, pushes —
    rows must move toward the target (server-side adam)."""
    _, client = cluster
    ids = np.array([1, 5, 9], np.int64)
    target = np.full((3, 4), 0.5, np.float32)
    for _ in range(200):
        rows = client.pull_sparse(1, ids, 4)
        grad = 2 * (rows - target)
        client.push_sparse(1, ids, grad)
    final = client.pull_sparse(1, ids, 4)
    np.testing.assert_allclose(final, target, atol=0.05)


def test_ps_barrier_and_save(cluster, tmp_path):
    _, client = cluster
    client.barrier()  # n_workers=1 → immediate
    client.pull_sparse(1, np.array([2], np.int64), 4)
    client.save(str(tmp_path / "ckpt"))
    assert os.path.exists(str(tmp_path / "ckpt") + ".table1")


def test_async_communicator_merges(cluster):
    _, client = cluster
    comm = AsyncCommunicator(client, send_interval_s=0.005).start()
    ids = np.array([11, 12], np.int64)
    before = client.pull_sparse(1, ids, 4)
    for _ in range(5):
        comm.push_sparse_async(1, ids, np.ones((2, 4), np.float32))
    comm.stop()
    after = client.pull_sparse(1, ids, 4)
    assert (after < before).all()  # grads applied


def test_geo_communicator(cluster):
    _, client = cluster
    # geo needs rule=sum on its dense table: table 2 not configured, use a
    # fresh server
    tables = [TableConfig(0, "dense", size=4, rule="sum")]
    srv = PsServer("127.0.0.1:0", tables, n_workers=1)
    srv.start()
    cl = PsClient([f"127.0.0.1:{srv.port}"])
    geo = GeoCommunicator(cl, k_steps=2)
    local = np.zeros(4, np.float32)
    geo.register_dense(0, local)
    local = local + 1.0
    local = geo.maybe_sync_dense(0, local)  # step 1: no sync
    local = local + 1.0
    local = geo.maybe_sync_dense(0, local)  # step 2: sync (delta=2)
    np.testing.assert_allclose(local, [2.0] * 4)
    np.testing.assert_allclose(cl.pull_dense(0), [2.0] * 4)
    cl.close()
    srv.stop()
