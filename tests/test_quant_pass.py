"""Program-level quantization passes (reference `fluid/contrib/slim/
quantization/quantization_pass.py` QuantizationTransformPass /
QuantizationFreezePass)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.quantization import (QuantizationFreezePass,
                                     QuantizationTransformPass)
from paddle_tpu.static import nn as snn


def _build(tmp_scope=False):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 4], "float32")
        h = snn.fc(x, 16, activation="relu")
        out = snn.fc(h, 2)
    return main, startup, out


def test_transform_pass_marks_and_preserves_function():
    paddle.enable_static()
    try:
        main, startup, out = _build()
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.random.RandomState(0).rand(8, 4).astype("float32")}
        before, = exe.run(main, feed=feed, fetch_list=[out])

        QuantizationTransformPass().apply(main)
        qops = [op for op in main.ops if op.attrs.get("quant")]
        assert qops, "no op was marked for QAT"
        after, = static.Executor().run(main, feed=feed, fetch_list=[out])
        # 8-bit fake-quant: close to the float program but not identical
        np.testing.assert_allclose(after, before, rtol=0.2, atol=0.1)
        assert not np.array_equal(after, before)
    finally:
        paddle.disable_static()


def test_freeze_pass_bakes_int8_weights():
    paddle.enable_static()
    try:
        main, startup, out = _build()
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.random.RandomState(1).rand(8, 4).astype("float32")}
        before, = exe.run(main, feed=feed, fetch_list=[out])

        QuantizationFreezePass().apply(main)
        frozen = [op for op in main.ops if op.attrs.get("frozen")]
        assert frozen, "no op was frozen"
        for op in frozen:
            consts = [ref for tag, ref in op.in_refs if tag == "c"]
            assert any(np.asarray(c).dtype == np.int8 for c in consts), \
                "frozen op carries no int8 constant"
        after, = static.Executor().run(main, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(after, before, rtol=0.05, atol=0.05)
    finally:
        paddle.disable_static()


def test_frozen_program_serializes_and_reloads(tmp_path):
    paddle.enable_static()
    try:
        main, startup, out = _build()
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.ones((8, 4), "float32")}
        before, = exe.run(main, feed=feed, fetch_list=[out])
        QuantizationFreezePass().apply(main)
        path = str(tmp_path / "q.json")
        main.save(path)
        loaded, params = static.Program.load(path)
        lop = [op for op in loaded.ops if op.attrs.get("frozen")]
        assert lop and lop[0].attr("weight_bits") == 8
        sc = dict(static.global_scope())
        sc.update(params)
        with static.scope_guard(sc):
            got, = static.Executor().run(
                loaded, feed=feed,
                fetch_list=[loaded.vars[out.slot]])
        np.testing.assert_allclose(got, before, rtol=0.05, atol=0.05)
    finally:
        paddle.disable_static()
