"""Program-level quantization passes (reference `fluid/contrib/slim/
quantization/quantization_pass.py` QuantizationTransformPass /
QuantizationFreezePass)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.quantization import (QuantizationFreezePass,
                                     QuantizationTransformPass)
from paddle_tpu.static import nn as snn


def _build(tmp_scope=False):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 4], "float32")
        h = snn.fc(x, 16, activation="relu")
        out = snn.fc(h, 2)
    return main, startup, out


def test_transform_pass_marks_and_preserves_function():
    paddle.enable_static()
    try:
        main, startup, out = _build()
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.random.RandomState(0).rand(8, 4).astype("float32")}
        before, = exe.run(main, feed=feed, fetch_list=[out])

        QuantizationTransformPass().apply(main)
        qops = [op for op in main.ops if op.attrs.get("quant")]
        assert qops, "no op was marked for QAT"
        # SAME executor must not serve the stale pre-pass jit cache
        after, = exe.run(main, feed=feed, fetch_list=[out])
        # 8-bit fake-quant: close to the float program but not identical
        np.testing.assert_allclose(after, before, rtol=0.2, atol=0.1)
        assert not np.array_equal(after, before)
    finally:
        paddle.disable_static()


def test_freeze_pass_bakes_int8_weights():
    paddle.enable_static()
    try:
        main, startup, out = _build()
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.random.RandomState(1).rand(8, 4).astype("float32")}
        before, = exe.run(main, feed=feed, fetch_list=[out])

        n_params_before = len(main.param_vars)
        QuantizationFreezePass().apply(main)
        frozen = [op for op in main.ops if op.attrs.get("frozen")]
        assert frozen, "no op was frozen"
        for op in frozen:
            consts = [np.asarray(ref) for tag, ref in op.in_refs
                      if tag == "c"]
            int8s = [c for c in consts if c.dtype == np.int8]
            assert int8s, "frozen op carries no int8 constant"
            # the WEIGHT (>=2-D) got frozen, not the bias
            assert all(c.ndim >= 2 for c in int8s), \
                [c.shape for c in int8s]
            # int8 quantization is lossy: the baked constant must not
            # dequantize exactly back (that would mean a no-op freeze)
            assert int8s[0].std() > 0
        # frozen weights left the parameter table (artifact shrinks)
        assert len(main.param_vars) < n_params_before
        after, = exe.run(main, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(after, before, rtol=0.05, atol=0.05)
        assert not np.array_equal(after, before), \
            "freeze must introduce int8 rounding"
    finally:
        paddle.disable_static()


def test_frozen_program_serializes_and_reloads(tmp_path):
    paddle.enable_static()
    try:
        main, startup, out = _build()
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.ones((8, 4), "float32")}
        before, = exe.run(main, feed=feed, fetch_list=[out])
        QuantizationFreezePass().apply(main)
        path = str(tmp_path / "q.json")
        main.save(path)
        loaded, params = static.Program.load(path)
        lop = [op for op in loaded.ops if op.attrs.get("frozen")]
        assert lop and lop[0].attr("weight_bits") == 8
        sc = dict(static.global_scope())
        sc.update(params)
        with static.scope_guard(sc):
            got, = static.Executor().run(
                loaded, feed=feed,
                fetch_list=[loaded.vars[out.slot]])
        np.testing.assert_allclose(got, before, rtol=0.05, atol=0.05)
    finally:
        paddle.disable_static()


def test_transform_then_freeze_unwraps_qat():
    """freeze after QAT must replace the fake-quant wrapper, not stack a
    second quantization grid on the dequantized weight."""
    paddle.enable_static()
    try:
        main, startup, out = _build()
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.random.RandomState(2).rand(8, 4).astype("float32")}
        ref, = exe.run(main, feed=feed, fetch_list=[out])
        QuantizationTransformPass().apply(main)
        QuantizationFreezePass().apply(main)
        frozen = [op for op in main.ops if op.attrs.get("frozen")]
        assert frozen
        for op in frozen:
            assert not op.attrs.get("quant")        # wrapper removed
            assert op.attrs.get("qat_trained")
        got, = exe.run(main, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.05)
    finally:
        paddle.disable_static()


def test_freeze_conv_per_output_channel():
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 3, 8, 8], "float32")
            h = snn.conv2d(x, 4, 3, padding=1)
            out = h.sum()
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.random.RandomState(3).rand(
            2, 3, 8, 8).astype("float32")}
        ref, = exe.run(main, feed=feed, fetch_list=[out])
        QuantizationFreezePass().apply(main)
        frozen = [op for op in main.ops if op.attrs.get("frozen")]
        assert frozen, [op.type for op in main.ops]
        got, = exe.run(main, feed=feed, fetch_list=[out])
        np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.5)
    finally:
        paddle.disable_static()
