"""Model.fit end-to-end (the reference's LeNet/MNIST correctness gate,
`python/paddle/tests/test_model.py`)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def _toy_classification(n=256, dim=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim).astype("float32") * 3
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.randn(n, dim).astype("float32")
    return x.astype("float32"), y.astype("int64")


def test_model_fit_linear_classifier():
    paddle.seed(0)
    x, y = _toy_classification()
    ds = TensorDataset([x, y])
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(0.01, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    model.fit(ds, batch_size=32, epochs=3, verbose=0)
    logs = model.evaluate(ds, batch_size=64, verbose=0)
    assert logs["acc"] > 0.9, logs


def test_model_fit_lenet_mnist_synthetic():
    paddle.seed(1)
    train = MNIST(mode="train")
    model = paddle.Model(LeNet())
    model.prepare(paddle.optimizer.Adam(0.001,
                                        parameters=model.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    model.fit(train, batch_size=64, epochs=1, verbose=0)
    # synthetic labels are random → just assert the pipeline ran & loss finite
    logs = model.evaluate(train, batch_size=64, verbose=0)
    assert np.isfinite(logs["loss"])


def test_model_save_load(tmp_path):
    x, y = _toy_classification(64)
    ds = TensorDataset([x, y])
    net = nn.Sequential(nn.Linear(16, 4))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    model.fit(ds, batch_size=32, epochs=1, verbose=0)
    p = str(tmp_path / "ckpt")
    model.save(p)
    w_before = net[0].weight.numpy().copy()
    net[0].weight.set_value(np.zeros_like(w_before))
    model.load(p)
    np.testing.assert_allclose(net[0].weight.numpy(), w_before)


def test_model_predict():
    x, y = _toy_classification(64)
    ds = TensorDataset([x, y])
    net = nn.Sequential(nn.Linear(16, 4))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    out = model.predict(ds, batch_size=32, stack_outputs=True)
    assert np.asarray(out).shape == (64, 4)


def test_dataloader_workers():
    x, y = _toy_classification(128)
    ds = TensorDataset([x, y])
    dl = DataLoader(ds, batch_size=16, num_workers=2, shuffle=True)
    batches = list(dl)
    assert len(batches) == 8
    assert batches[0][0].shape == [16, 16]


def test_lr_scheduler_steps_during_fit():
    x, y = _toy_classification(64)
    ds = TensorDataset([x, y])
    net = nn.Sequential(nn.Linear(16, 4))
    sched = paddle.optimizer.lr.StepDecay(0.1, step_size=1, gamma=0.5)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(sched, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    model.fit(ds, batch_size=32, epochs=1, verbose=0)
    assert sched.last_epoch >= 2
