"""tracecheck rule corpus: every pass must flag its seeded bad example
and stay silent on the good twin, suppressions need written reasons,
and the lint.py CLI honors the 0/1/2 exit-code contract with a clean
--json round trip.

The fixtures live in tests/tracecheck_fixtures/<rule>/: each holds a
mini repo (pkg/ tree + optional COVERAGE.md) so the doc-cross-checking
passes exercise both directions without touching the real docs.
"""
from __future__ import annotations

import importlib.util
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import tracecheck  # noqa: E402

FIX = os.path.join(ROOT, "tests", "tracecheck_fixtures")
LINT = os.path.join(ROOT, "tools", "lint.py")


def run_fixture(name, rules=None):
    root = os.path.join(FIX, name)
    ctx = tracecheck.load_context(os.path.join(root, "pkg"), root)
    return tracecheck.run_rules(ctx, rules)


def lint_main():
    spec = importlib.util.spec_from_file_location("lint", LINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def _bad_only(findings, rule, bad="bad.py", good="good.py"):
    """Every finding carries `rule`, touches the bad file, and never the
    good twin."""
    assert findings, f"{rule}: seeded violation not flagged"
    for f in findings:
        assert f.rule == rule
        assert good not in f.path, f"{rule} flagged the good twin: {f.format()}"
        assert bad in f.path, f"{rule} flagged the wrong file: {f.format()}"


# ---------------------------------------------------------------------------
# one test per rule: seeded bad flagged, good twin silent
# ---------------------------------------------------------------------------

def test_flag_in_trace_corpus():
    fs = run_fixture("flag_in_trace", ["flag-in-trace"])
    _bad_only(fs, "flag-in-trace")
    # the direct flag() call, the bare FLAGS_* global, the
    # transitively-reachable helper, the jit(partial(f, ...)) form, and
    # the jit-wrapped lambda inside a traced function — which must be
    # reported exactly ONCE despite being walked from two FuncInfos
    assert len(fs) == 5
    assert any("FLAGS_scale" in f.message for f in fs)
    assert any("_inner" in f.message for f in fs)
    assert any("part_kernel" in f.message for f in fs)
    assert sum("<lambda" in f.message for f in fs) == 1


def test_use_after_donate_corpus():
    fs = run_fixture("use_after_donate", ["use-after-donate"])
    _bad_only(fs, "use-after-donate")
    # the donate_argnums positional seed, the donate_argnames keyword
    # seed, the same-local-name no-clobber seed, the factory-closure
    # (lexical scoping) seed, the loop-without-rebind seed, the
    # same-line sequencing seed, the store-on-the-load's-own-line seed
    # (`step(carry, x)` then `carry = carry + 1` — the rebind executes
    # AFTER the read), and the never-bound inline `jax.jit(...)(args)`
    # seed
    assert len(fs) == 8
    assert all("`carry`" in f.message for f in fs)
    assert any("named_step" in f.message for f in fs)
    assert any("jstep" in f.message for f in fs)
    assert any("inside a loop" in f.message for f in fs)
    assert any("jax.jit(...)" in f.message for f in fs)


def test_scatter_batch_dim_corpus():
    fs = run_fixture("scatter_batch_dim", ["scatter-batch-dim"])
    _bad_only(fs, "scatter-batch-dim")
    # the .at[...] update and the pool-like gather
    assert len(fs) == 2


def test_gauge_discipline_corpus():
    fs = run_fixture("gauge_discipline", ["gauge-discipline"])
    _bad_only(fs, "gauge-discipline")
    # mixed-discipline name + counter ops on a documented gauge
    assert len(fs) == 2
    assert any("STAT_fix_mixed_level" in f.message for f in fs)
    assert any("STAT_fix_doc_gauge" in f.message for f in fs)


def test_lock_discipline_corpus():
    fs = run_fixture("lock_discipline", ["lock-discipline"])
    _bad_only(fs, "lock-discipline")
    # Engine: both unlocked sites of the contended attribute (loop +
    # caller); HostStore: both sites of the attribute shared between a
    # declared step-thread method and an undeclared caller method
    # (ISSUE 18 — the _TRACECHECK_THREADS extension)
    assert len(fs) == 4
    count = [f for f in fs if "_count" in f.message]
    tier = [f for f in fs if "_bytes" in f.message]
    assert len(count) == 2 and len(tier) == 2
    assert all("HostStore" in f.message for f in tier)


def test_flags_inventory_corpus():
    fs = run_fixture("flags_inventory", ["flags-inventory"])
    assert {f.rule for f in fs} == {"flags-inventory"}
    missing = [f for f in fs if "FLAGS_fix_missing_doc" in f.message]
    ghost = [f for f in fs if "FLAGS_fix_ghost" in f.message]
    assert len(fs) == 2 and missing and ghost
    assert missing[0].path.endswith(os.path.join("framework", "flags.py"))
    assert ghost[0].path == "COVERAGE.md"
    # the documented flag is clean in both directions
    assert not any("FLAGS_fix_documented" in f.message for f in fs)


def test_audit_reasons_corpus():
    fs = run_fixture("audit_reasons", ["audit-reasons"])
    assert {f.rule for f in fs} == {"audit-reasons"}
    undoc = [f for f in fs if "FIX_UNDOCUMENTED_CODE" in f.message]
    stale = [f for f in fs if "FIX_STALE_CODE" in f.message]
    assert len(fs) == 2 and undoc and stale
    assert undoc[0].path.endswith("bad.py")
    assert stale[0].path == "COVERAGE.md"
    # the documented codes — including both IfExp branches and the
    # detail-kwarg shapes the prefix-cache decisions use — are clean
    for code in ("FIX_DOC_ADMIT", "FIX_DOC_EOS", "FIX_DOC_BUDGET",
                 "FIX_DOC_PREFIX_HIT", "FIX_DOC_COW_SPLIT",
                 "FIX_DOC_EVICT_LRU"):
        assert not any(code in f.message for f in fs)


def test_except_pass_corpus():
    fs = run_fixture("except_pass", ["except-pass"])
    _bad_only(fs, "except-pass")
    # both seeded forms flagged: the typed handler and the bare except
    assert len(fs) == 2
    assert any("except Exception" in f.message for f in fs)
    assert any("bare except" in f.message for f in fs)
    # the subtree scope holds: pkg/other.py sits OUTSIDE serving/ and
    # carries the same pattern — never flagged
    assert not any("other.py" in f.path for f in fs)


def test_stats_doc_corpus():
    fs = run_fixture("stats_doc", ["stats-doc"])
    assert {f.rule for f in fs} == {"stats-doc"}
    undoc = [f for f in fs if "STAT_fix_undocumented_thing" in f.message]
    stale = [f for f in fs if "STAT_fix_stale_thing" in f.message]
    assert len(fs) == 2 and undoc and stale
    assert undoc[0].path.endswith("mod.py")
    assert stale[0].path == "COVERAGE.md"


# ---------------------------------------------------------------------------
# suppressions: reasoned allow() silences, reasonless is itself a finding
# ---------------------------------------------------------------------------

def test_reasoned_allow_suppresses():
    fs = run_fixture("suppression", ["scatter-batch-dim"])
    assert not any("suppressed.py" in f.path for f in fs)


def test_reasonless_allow_is_reported_and_does_not_suppress():
    fs = run_fixture("suppression", ["scatter-batch-dim"])
    reasonless = [f for f in fs if "reasonless.py" in f.path]
    assert {f.rule for f in reasonless} == \
        {"scatter-batch-dim", "bad-suppression"}


def test_malformed_allow_is_reported_and_does_not_suppress():
    fs = run_fixture("suppression", ["scatter-batch-dim"])
    malformed = [f for f in fs if "malformed.py" in f.path]
    assert {f.rule for f in malformed} == \
        {"scatter-batch-dim", "bad-suppression"}
    assert any("malformed" in f.message for f in malformed)


def test_unknown_rule_allow_is_reported():
    fs = run_fixture("suppression", ["scatter-batch-dim"])
    unknown = [f for f in fs if "unknown.py" in f.path]
    assert len(unknown) == 1 and unknown[0].rule == "bad-suppression"
    assert "no-such-rule" in unknown[0].message


def test_parse_error_is_a_finding_not_a_crash():
    fs = run_fixture("parse_error")
    assert len(fs) == 1 and fs[0].rule == "parse-error"
    assert "broken.py" in fs[0].path


def test_allow_text_in_strings_is_inert():
    """Allow-shaped text inside docstrings/string literals neither
    suppresses the adjacent violation nor reports bad-suppression."""
    fs = run_fixture("suppression", ["scatter-batch-dim"])
    quoted = [f for f in fs if "quoted.py" in f.path]
    assert [f.rule for f in quoted] == ["scatter-batch-dim"]


def test_run_rules_rejects_unknown_rule_name():
    with pytest.raises(KeyError):
        run_fixture("suppression", ["not-a-rule"])


def test_repeated_rule_selection_does_not_duplicate_findings():
    """`--rule x --rule x` must behave exactly like `--rule x`."""
    once = run_fixture("scatter_batch_dim", ["scatter-batch-dim"])
    twice = run_fixture("scatter_batch_dim",
                        ["scatter-batch-dim", "scatter-batch-dim"])
    assert [(f.path, f.line) for f in twice] == \
        [(f.path, f.line) for f in once]


def test_fstring_normalizers_agree_on_format_specs():
    """The regex normalizer (stats-doc / the check_stats shim) and the
    AST normalizer (gauge-discipline) must wildcard the same name to
    the same token, or the doc Kind cross-check silently lapses."""
    import ast as _ast
    from tracecheck.rules.stats_doc import _normalize, \
        normalize_fstring_ast
    for text in ('STAT_lat{ms:.0f}_bucket', 'STAT_x{n!r}_y',
                 'STAT_serving_lane{self.index}_batches'):
        via_ast = normalize_fstring_ast(
            _ast.parse(f'f"{text}"', mode="eval").body)
        assert _normalize(text, True) == via_ast, text


# ---------------------------------------------------------------------------
# lint.py CLI: --json round trip + the 0/1/2 exit-code contract
# ---------------------------------------------------------------------------

def test_json_round_trip(capsys):
    root = os.path.join(FIX, "scatter_batch_dim")
    code = lint_main()(["--json", "--rule", "scatter-batch-dim",
                        "--pkg", os.path.join(root, "pkg"),
                        "--repo", root])
    payload = json.loads(capsys.readouterr().out)
    assert code == 1
    assert payload["ok"] is False
    assert payload["rules"] == ["scatter-batch-dim"]
    assert payload["modules"] == 3  # __init__, bad, good
    got = {(f["rule"], f["path"], f["line"]) for f in payload["findings"]}
    ctx = tracecheck.load_context(os.path.join(root, "pkg"), root)
    want = {(f.rule, f.path, f.line)
            for f in tracecheck.run_rules(ctx, ["scatter-batch-dim"])}
    assert got == want  # JSON carries exactly the API's findings


def test_exit_zero_on_clean_tree(capsys):
    root = os.path.join(FIX, "scatter_batch_dim")
    code = lint_main()(["--json", "--rule", "flag-in-trace",
                        "--pkg", os.path.join(root, "pkg"),
                        "--repo", root])
    payload = json.loads(capsys.readouterr().out)
    assert code == 0 and payload["ok"] is True and not payload["findings"]


def test_exit_two_on_internal_error(capsys):
    code = lint_main()(["--rule", "no-such-rule",
                        "--pkg", os.path.join(FIX, "suppression", "pkg"),
                        "--repo", os.path.join(FIX, "suppression")])
    capsys.readouterr()
    assert code == 2


def test_exit_two_on_missing_pkg_path(capsys, tmp_path):
    """A typo'd --pkg must never report a clean tree it never scanned."""
    code = lint_main()(["--pkg", str(tmp_path / "no-such-tree"),
                        "--repo", str(tmp_path)])
    capsys.readouterr()
    assert code == 2


def test_cli_subprocess_contract():
    """The real `python tools/lint.py --json` process honors the same
    contract (no jax import, so this stays cheap)."""
    root = os.path.join(FIX, "use_after_donate")
    proc = subprocess.run(
        [sys.executable, LINT, "--json", "--rule", "use-after-donate",
         "--pkg", os.path.join(root, "pkg"), "--repo", root],
        capture_output=True, text=True)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["findings"], "seeded corpus produced no findings"
    assert {f["rule"] for f in payload["findings"]} == {"use-after-donate"}


def test_list_rules_names_all_eight(capsys):
    assert lint_main()(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in ("flag-in-trace", "use-after-donate", "scatter-batch-dim",
                 "gauge-discipline", "lock-discipline", "flags-inventory",
                 "stats-doc", "audit-reasons"):
        assert name in out
