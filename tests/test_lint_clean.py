"""Tier-1 gate: the whole tracecheck suite runs green over paddle_tpu/.

Every invariant pass (flag-in-trace, use-after-donate,
scatter-batch-dim, gauge-discipline, lock-discipline, flags-inventory,
stats-doc) must report zero findings — a new violation lands either
with a fix or with a reasoned `# lint: allow(<rule>): <reason>`
comment, and a reasonless suppression is itself a finding
(bad-suppression), so the tree stays at zero unexplained suppressions.
"""
from __future__ import annotations

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

import tracecheck  # noqa: E402


def test_lint_clean():
    ctx = tracecheck.load_context(os.path.join(ROOT, "paddle_tpu"), ROOT)
    findings = tracecheck.run_rules(ctx)
    assert ctx.modules, "loader found no modules — broken paths"
    assert not findings, (
        "tracecheck findings (fix, or suppress with a reasoned "
        "`# lint: allow(<rule>): <reason>`):\n"
        + "\n".join(f.format() for f in findings))


def test_every_suppression_carries_a_reason():
    """Belt and braces over the bad-suppression machinery: grep every
    allow() in the tree and demand the `: <reason>` tail."""
    ctx = tracecheck.load_context(os.path.join(ROOT, "paddle_tpu"), ROOT)
    n_allows = 0
    for mod in ctx.modules:
        for line, entries in mod.allows.items():
            for rule_name, reason in entries:
                n_allows += 1
                assert reason, (
                    f"{mod.rel}:{line}: allow({rule_name}) without a "
                    f"written reason")
    assert n_allows >= 3  # the audited trace/donation allows exist


def test_check_stats_shim_cli():
    """`python tools/check_stats.py` keeps its original contract."""
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_stats.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
