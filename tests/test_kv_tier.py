"""Tiered KV cache: host-RAM demotion tier under the prefix cache
(ISSUE 18).

The load-bearing anchors:

- **Cross-tier token identity** — a chain that was demoted to host RAM
  and promoted back decodes exactly like a never-evicted one, in fp32
  AND int8 (raw page bytes + fp32 scale rows round-trip bit-identical;
  the PR 9 scale-grid poisoning class, now across tiers).
- **No leak under faults** — both failpoints
  (`kv_tier.promote_upload`, `kv_tier.demote_gather`) leave ZERO
  leaked pages on either tier: an abandoned promotion zeroes its
  partially-written targets and falls back to cold prefill (correct
  tokens, exactly one KV_PROMOTE_ABANDON audit record); a failed
  demote gather degrades to the plain PR 12 eviction.
- **Budget discipline** — the tier's own byte budget LRU-evicts
  (demote-of-demoted = final eviction, KV_TIER_EVICT), refuses entries
  that alone exceed it, and never evicts a protected in-flight
  promotion run.
- **Observability** — stats()/step-ring/pressure all carry the tier
  fields, and tools/engine_report.py summarizes them.
"""
from contextlib import contextmanager

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.framework import monitor
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import failpoints
from paddle_tpu.serving.kv_tier import HostEntry, HostTier


@pytest.fixture(scope="module")
def model():
    paddle.seed(11)
    cfg = GPTConfig.tiny(dropout=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    paddle.set_flags({"FLAGS_failpoints": ""})
    failpoints.reset()


@contextmanager
def flags(**kw):
    old = paddle.get_flags(list(kw))
    paddle.set_flags(kw)
    try:
        yield
    finally:
        paddle.set_flags(old)


def _engine(model, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 12)          # 11 usable: floods evict
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("max_new_tokens", 4)
    kw.setdefault("request_timeout_ms", 0)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("kv_tier", True)
    kw.setdefault("kv_tier_host_bytes", 64 << 20)
    kw.setdefault("kv_tier_chunk_pages", 2)
    return serving.GenerationEngine(model, **kw)


def _prompts(n=8, pfx=8, tail=3, seed=0, vocab=512):
    """n prompts with DISTINCT pfx-token leads (each registers its own
    2-page chain at the 4-token test page size) + tail tokens."""
    rng = np.random.RandomState(seed)
    return [np.concatenate([rng.randint(0, vocab, size=(pfx,)),
                            rng.randint(0, vocab, size=(tail,))])
            .astype("int64") for _ in range(n)]


def _tier_consistent(tier: HostTier) -> bool:
    """Byte ledger reconciles exactly with the stored entries."""
    return tier.host_bytes == sum(e.nbytes
                                  for e in tier._entries.values())


def _pool_reconciles(eng) -> bool:
    """No live sequences: every allocated page is cache-held, one
    reference per cached page."""
    cache = eng._cache
    refs = cache.refcounts()
    cached = set(cache.cached_pages())
    return (cache.owners() == {} and set(refs) == cached
            and sum(refs.values()) == len(cached)
            and cache.pages_in_use == len(cached))


# -- HostTier store (unit) ---------------------------------------------------

def _entry(nbytes=16):
    half = nbytes // 2
    return HostEntry(np.zeros(half, np.int8), np.zeros(half, np.int8))


def test_host_tier_put_get_pop_accounting():
    t = HostTier(max_bytes=64, engine="tier_unit")
    stored, evicted = t.put(b"a", _entry())
    assert stored and evicted == []
    assert t.host_bytes == 16 and len(t) == 1 and b"a" in t
    # re-put under the same digest replaces without double counting
    stored, _ = t.put(b"a", _entry(32))
    assert stored and t.host_bytes == 32 and len(t) == 1
    assert t.get(b"a") is not None and t.get(b"zz") is None
    e = t.pop(b"a")
    assert e is not None and e.nbytes == 32
    assert t.host_bytes == 0 and len(t) == 0
    assert t.pop(b"a") is None              # absent pop is a no-op
    assert t.evictions == 0                 # plain pops aren't evictions
    t.put(b"b", _entry())
    t.pop(b"b", final=True)                 # cascade/abandon discard IS
    assert t.evictions == 1
    s = t.stats()
    assert s["demotions"] == 3 and s["host_bytes"] == 0
    assert _tier_consistent(t)


def test_host_tier_lru_eviction_respects_recency_and_protect():
    t = HostTier(max_bytes=40, engine="tier_lru")
    t.put(b"a", _entry())
    t.put(b"b", _entry())
    stored, evicted = t.put(b"c", _entry())  # 48 > 40: LRU "a" goes
    assert stored and evicted == [b"a"]
    assert t.digests() == [b"b", b"c"] and t.host_bytes == 32
    t.get(b"b")                              # touch: "c" is now LRU
    _, evicted = t.put(b"d", _entry())
    assert evicted == [b"c"]
    # a protected digest survives even as the LRU victim
    _, evicted = t.put(b"e", _entry(), protect=(b"b",))
    assert b"b" not in evicted and b"b" in t
    assert _tier_consistent(t)


def test_host_tier_refuses_entry_alone_over_budget():
    t = HostTier(max_bytes=8, engine="tier_reject")
    stored, evicted = t.put(b"big", _entry(16))
    assert not stored and evicted == []
    assert len(t) == 0 and t.host_bytes == 0
    assert t.rejects == 1 and t.demotions == 0


# -- engine demote/promote round-trip ----------------------------------------

def test_demote_promote_token_identical_fp32(model):
    prompts = _prompts(n=8, seed=31)
    ref = [model.generate(paddle.to_tensor(p[None]),
                          max_new_tokens=4).numpy()[0] for p in prompts]
    with _engine(model, name="tier_fp32") as eng:
        flood = [eng.generate(p, max_new_tokens=4) for p in prompts]
        pfx = eng.stats()["kv"]["prefix"]
        assert pfx["tier_enabled"] and pfx["demotions"] >= 2
        assert pfx["host_nodes"] >= 2 and pfx["host_bytes"] > 0
        # revisit the LRU-evicted (earliest) chain: misses HBM, hits
        # the host tier, promotes through the chunked upload pipeline
        again = eng.generate(prompts[0], max_new_tokens=4)
        s = eng.stats()
        reasons = [ev["reason"] for ev in eng._audit.tail(256)]
        tier = eng._tier.stats()
    for o, r in zip(flood, ref):
        np.testing.assert_array_equal(o, r)
    np.testing.assert_array_equal(again, ref[0])
    assert tier["promotions"] >= 2 and tier["hits"] >= 1
    assert "KV_DEMOTE" in reasons and "KV_PROMOTE" in reasons
    assert s["kv"]["prefix"]["promotions"] >= 2
    assert s["kv"]["prefix"]["tier_hit_rate"] > 0
    # promotion rode the warmed tier programs: one compile each, ever
    assert s["compiles"]["tier_gather"] == 1
    assert all(v == 1 for k, v in s["compiles"].items()
               if k.startswith("tier_write"))


def test_promoted_int8_chain_token_identical_to_never_evicted(model):
    """The regression the raw-bytes storage exists for: an int8 chain
    demoted (pages + fp32 scale rows gathered to host) and promoted
    back must decode exactly like the never-evicted original."""
    prompts = _prompts(n=8, seed=37)
    with _engine(model, kv_cache_dtype="int8", name="tier_int8") as eng:
        # never-evicted baseline: cold prefill, then a pure-HBM hit
        base = eng.generate(prompts[0], max_new_tokens=4)
        warm = eng.generate(prompts[0], max_new_tokens=4)
        np.testing.assert_array_equal(base, warm)
        # flood with distinct chains until prompts[0]'s chain demotes
        for p in prompts[1:]:
            eng.generate(p, max_new_tokens=4)
        assert eng.stats()["kv"]["prefix"]["demotions"] >= 2
        promoted = eng.generate(prompts[0], max_new_tokens=4)
        tier = eng._tier.stats()
        reasons = [ev["reason"] for ev in eng._audit.tail(256)]
    np.testing.assert_array_equal(promoted, base)
    assert tier["promotions"] >= 2
    assert "KV_PROMOTE" in reasons


# -- failpoints: no leak on either tier --------------------------------------

def test_promote_upload_failpoint_falls_back_cold_no_leak(model):
    """Abandon mid-upload (after the first 1-page chunk): the written
    target page is zeroed (stale int8 scales would otherwise poison the
    requanting tail prefill), the admission falls back to cold prefill
    with CORRECT tokens, exactly one KV_PROMOTE_ABANDON is audited, and
    neither tier leaks a page."""
    prompts = _prompts(n=8, seed=41)
    with _engine(model, kv_cache_dtype="int8", kv_tier_chunk_pages=1,
                 name="tier_abandon") as eng:
        base = eng.generate(prompts[0], max_new_tokens=4)
        for p in prompts[1:]:
            eng.generate(p, max_new_tokens=4)
        assert eng.stats()["kv"]["prefix"]["demotions"] >= 2
        failpoints.reset()
        with flags(FLAGS_failpoints="kv_tier.promote_upload@2"):
            out = eng.generate(prompts[0], max_new_tokens=4)
        abandons = [ev for ev in eng._audit.tail(256)
                    if ev["reason"] == "KV_PROMOTE_ABANDON"]
        tier = eng._tier
        assert tier.abandons == 1 and tier.promotions == 0
        assert _tier_consistent(tier)
        assert _pool_reconciles(eng)
        # the cold prefill re-registered the chain: a fresh revisit is
        # a plain HBM hit again, still token-identical
        again = eng.generate(prompts[0], max_new_tokens=4)
    np.testing.assert_array_equal(out, base)
    np.testing.assert_array_equal(again, base)
    assert len(abandons) == 1
    assert abandons[0]["pages"] == 2 and abandons[0]["written"] == 1


def test_demote_gather_failpoint_degrades_to_plain_eviction(model):
    """Every demote gather fails: evictions proceed exactly like PR 12
    (content discarded), the tier stays empty, nothing leaks."""
    prompts = _prompts(n=8, seed=43)
    ref = model.generate(paddle.to_tensor(prompts[0][None]),
                         max_new_tokens=4).numpy()[0]
    with _engine(model, name="tier_nogather") as eng:
        with flags(FLAGS_failpoints="kv_tier.demote_gather@every:1"):
            for p in prompts:
                eng.generate(p, max_new_tokens=4)
            out = eng.generate(prompts[0], max_new_tokens=4)
        pfx = eng.stats()["kv"]["prefix"]
        tier = eng._tier
        assert len(tier) == 0 and tier.host_bytes == 0
        assert tier.demotions == 0 and pfx["host_nodes"] == 0
        assert pfx["evictions"] >= 1          # plain LRU evictions ran
        assert _pool_reconciles(eng)
    np.testing.assert_array_equal(out, ref)


# -- config validation -------------------------------------------------------

def test_kv_tier_requires_prefix_cache(model):
    with pytest.raises(InvalidArgumentError):
        _engine(model, prefix_cache=False, name="tier_cfg")


# -- observability plumbing --------------------------------------------------

def test_step_ring_pressure_and_report_carry_tier_fields(model, tmp_path):
    import importlib.util
    import json
    import os
    from paddle_tpu.profiler import step_log

    d0 = monitor.stat_get("STAT_kv_tier_demotions")
    p0 = monitor.stat_get("STAT_kv_tier_promotions")
    prompts = _prompts(n=8, seed=47)
    with _engine(model, name="tier_obs") as eng:
        for p in prompts:
            eng.generate(p, max_new_tokens=4)
        eng.generate(prompts[0], max_new_tokens=4)   # promote
        payload = step_log.steps_payload()
        recs = payload["engines"]["tier_obs"]["records"]
        pressure = eng._compute_pressure()
    assert sum(r["tier_demotions"] for r in recs) >= 2
    assert sum(r["tier_promotions"] for r in recs) >= 2
    assert monitor.stat_get("STAT_kv_tier_demotions") - d0 >= 2
    assert monitor.stat_get("STAT_kv_tier_promotions") - p0 >= 2
    assert pressure["tier"]["hit_rate"] > 0
    assert pressure["tier"]["host_bytes"] >= 0

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    spec = importlib.util.spec_from_file_location(
        "engine_report", os.path.join(tools, "engine_report.py"))
    er = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(er)
    summ = er.summarize(recs)
    assert summ["tier_demotions"] >= 2 and summ["tier_promotions"] >= 2
    path = str(tmp_path / "steps.json")
    with open(path, "w") as f:
        json.dump(payload, f)
    assert er.main([path, "--engine", "tier_obs"]) == 0
