"""Block-structured Program IR (reference `framework/block_desc.h:40`,
Python `fluid/framework.py` Program/Block/Operator): control-flow ops
carry sub-block mirrors, OpDesc-style introspection, serde preserves
nesting, and static while replay stays feed-dependent."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static.nn import cond, while_loop


def _fresh_programs():
    return static.Program(), static.Program()


def test_cond_records_sub_blocks():
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            out = cond(x.sum() > 0, lambda: x * 2.0, lambda: x - 1.0)
        assert main.num_blocks == 3          # global + true + false
        op = main.ops[-1]
        assert op.type == "cond"
        tb, fb = op.attr("sub_block"), op.attr("sub_block_false")
        assert {tb, fb} == {1, 2}
        # branch bodies were mirrored into the sub-blocks
        assert main.block(tb).ops and main.block(fb).ops
        assert main.block(tb).parent_idx == 0
        types = [o.type for o in main.block(tb).ops]
        assert any(t in ("scale", "multiply", "elementwise_mul", "mul")
                   for t in types), types

        exe = static.Executor()
        pos, = exe.run(main, feed={"x": np.ones(4, "float32")},
                       fetch_list=[out])
        neg, = exe.run(main, feed={"x": -np.ones(4, "float32")},
                       fetch_list=[out])
        np.testing.assert_allclose(pos, 2.0 * np.ones(4), rtol=1e-6)
        np.testing.assert_allclose(neg, -2.0 * np.ones(4), rtol=1e-6)
    finally:
        paddle.disable_static()


def test_static_while_is_feed_dependent():
    """Regression: the old direct-eager while_loop baked the placeholder
    result into the Program as a constant."""
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with static.program_guard(main, startup):
            x = static.data("x", [1], "float32")
            i0 = paddle.zeros([1], "int32")
            iN, acc = while_loop(
                lambda i, s: (i < 3).all(),
                lambda i, s: (i + 1, s + x),
                (i0, paddle.zeros([1], "float32")))
        wop = [op for op in main.ops if op.type == "while"]
        assert len(wop) == 1
        assert wop[0].has_attr("sub_block")
        assert main.block(wop[0].attr("sub_block")).ops

        exe = static.Executor()
        a, = exe.run(main, feed={"x": np.asarray([2.0], "float32")},
                     fetch_list=[acc])
        b, = exe.run(main, feed={"x": np.asarray([5.0], "float32")},
                     fetch_list=[acc])
        assert float(a[0]) == 6.0
        assert float(b[0]) == 15.0
    finally:
        paddle.disable_static()


def test_block_var_lookup_and_operator_surface():
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 3], "float32")
            y = x * 3.0
        blk = main.global_block()
        assert blk.idx == 0 and blk.parent_block is None
        assert blk.var("x") is x
        op = main.ops[-1]
        assert op.out_slots == [y.slot]
        assert x.slot in op.input_slots
        assert isinstance(op.all_attrs(), dict)
    finally:
        paddle.disable_static()


def test_serde_preserves_block_structure(tmp_path):
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with static.program_guard(main, startup):
            x = static.data("x", [2], "float32")
            out = cond(x.sum() > 0, lambda: x * 2.0, lambda: x * -1.0)
        path = str(tmp_path / "prog.json")
        main.save(path)
        loaded, _ = static.Program.load(path)
        assert loaded.num_blocks == main.num_blocks == 3
        lop = loaded.ops[-1]
        assert lop.type == "cond"
        assert loaded.block(lop.attr("sub_block")).ops
        # loaded program still executes (block-0 fused lax op replays)
        exe = static.Executor()
        got, = exe.run(loaded, feed={"x": np.asarray([1., 1.], "float32")},
                       fetch_list=[loaded.vars[out.slot]])
        np.testing.assert_allclose(got, [2., 2.], rtol=1e-6)
    finally:
        paddle.disable_static()


def test_dygraph_control_flow_unchanged():
    x = paddle.to_tensor(3.0)
    out = cond(x > 2, lambda: x * 2, lambda: x - 1)
    assert float(out) == 6.0
    i2, s2 = while_loop(lambda i, s: i < 5,
                        lambda i, s: (i + 1, s + 2.0),
                        (paddle.to_tensor(0), paddle.to_tensor(0.0)))
    assert int(i2) == 5 and float(s2) == 10.0


def test_branch_captured_parameters_stay_live():
    """Review regression: nn.Layer weights used inside a branch must be
    explicit op inputs, so optimizer/scope updates reach the lowered
    branch and grads flow."""
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with static.program_guard(main, startup):
            lin = paddle.nn.Linear(2, 2)
            x = static.data("x", [1, 2], "float32")
            out = cond(x.sum() > -1e9, lambda: lin(x), lambda: x)
        cop = main.ops[-1]
        param_slots = {p.slot for p in main.all_parameters()}
        assert param_slots & set(cop.input_slots), \
            "branch-captured parameters missing from cond op inputs"

        exe = static.Executor()
        xv = np.ones((1, 2), "float32")
        before, = exe.run(main, feed={"x": xv}, fetch_list=[out])
        # simulate an optimizer step: overwrite weights in the scope
        scope = static.global_scope()
        wname = [n for n in main.param_vars
                 if scope[n].shape == (2, 2)][0]
        bname = [n for n in main.param_vars
                 if scope[n].shape == (2,)][0]
        scope[wname] = scope[wname] * 0.0
        scope[bname] = scope[bname] * 0.0 + 7.0
        after, = exe.run(main, feed={"x": xv}, fetch_list=[out])
        np.testing.assert_allclose(after, np.full((1, 2), 7.0), rtol=1e-6)
        assert not np.allclose(before, after)
    finally:
        paddle.disable_static()


def test_static_while_nested_pytree_loop_vars():
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with static.program_guard(main, startup):
            x = static.data("x", [1], "float32")
            i0 = paddle.zeros([1], "int32")
            state = {"s": paddle.zeros([1], "float32")}
            iN, stN = while_loop(
                lambda i, st: (i < 3).all(),
                lambda i, st: (i + 1, {"s": st["s"] + x}), (i0, state))
        assert isinstance(stN, dict) and "s" in stN
        exe = static.Executor()
        got, = exe.run(main, feed={"x": np.asarray([4.0], "float32")},
                       fetch_list=[stN["s"]])
        assert float(got[0]) == 12.0
    finally:
        paddle.disable_static()


def test_prune_keeps_sub_block_attrs_resolvable():
    paddle.enable_static()
    try:
        main, startup = _fresh_programs()
        with static.program_guard(main, startup):
            x = static.data("x", [2], "float32")
            out = cond(x.sum() > 0, lambda: x * 2.0, lambda: x * -1.0)
        pruned = main.prune([out])
        cop = [op for op in pruned.ops if op.type == "cond"][0]
        sb = pruned.block(cop.attr("sub_block"))
        assert sb.ops, "pruned program lost the cond sub-block"
        assert pruned.num_blocks == main.num_blocks
    finally:
        paddle.disable_static()
