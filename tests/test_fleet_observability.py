"""Fleet flight deck (ISSUE 20): cross-replica trace propagation, the
time-series metrics ring, and per-step goodput attribution.

Trace propagation: the Router mints one 16-hex trace id per request at
placement; the id rides the placement audit (`trace=`), the engine
request, the per-incarnation GenSpan (reqspan `,tid=` field + the
`fleet_request` flow chain), and the supervisor's ReplayEntry — so ONE
id names the request across re-routes and supervised restarts, and
tools/fleet_trace.py can merge N replicas' chrome exports into one
arrow chain per request.

Metrics ring: profiler/timeseries.py samples counters-as-rates,
gauges-as-levels, and per-replica pressure into bounded per-name rings
served as /history; scrapes must stay race-free against engine death
and drain.

Attribution: every engine iteration's wall is split into
admit/prefill/promote/decode/bookkeep/idle buckets that sum EXACTLY to
the stored wall (the bookkeep bucket is the rounded remainder), ridden
on StepRecord era-compat append fields.
"""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.profiler import (exporter, step_log, timeseries,
                                 trace_context, tracer)
from paddle_tpu.serving import EngineOverloaded, Router
from paddle_tpu.serving import failpoints


@pytest.fixture(scope="module")
def model():
    paddle.seed(17)
    cfg = GPTConfig.tiny(dropout=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.reset()
    yield
    paddle.set_flags({"FLAGS_failpoints": ""})
    failpoints.reset()


@pytest.fixture(autouse=True)
def _clean_timeseries():
    timeseries.clear()
    yield
    timeseries.clear()


def _router(model, name, **kw):
    kw.setdefault("num_replicas", 2)
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 64)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("max_new_tokens", 5)
    kw.setdefault("request_timeout_ms", 0)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("pressure_ttl_ms", 0.0)
    return Router(model, name=name, **kw)


def _engine(model, name, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 64)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("max_new_tokens", 8)
    kw.setdefault("request_timeout_ms", 0)
    return serving.GenerationEngine(model, name=name, **kw)


def _prompts_shared_prefix(n, prefix_pages=2, page=4, tail=4, seed=3,
                           vocab=200):
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, vocab, size=prefix_pages * page)
    return [np.concatenate([prefix,
                            rng.randint(0, vocab, size=tail)])
            .astype("int64") for _ in range(n)]


def _audit(router):
    return router.stats()["router"]["audit_tail"]


def _reqspan_tids():
    """{rid: tid} parsed from the tracer's reqspan instants."""
    out = {}
    for name, *_ in tracer.events(with_threads=True):
        if name.startswith("reqspan:") and ",tid=" in name:
            rid = name.split(":")[1]
            out.setdefault(rid, []).append(name.rsplit(",tid=", 1)[1])
    return out


# -- tentpole: trace-id minting and validation -------------------------------

def test_trace_id_mint_and_validate():
    tid = trace_context.new_trace_id()
    assert trace_context.is_trace_id(tid)
    assert len(tid) == 16
    assert not trace_context.is_trace_id("xyz")
    assert not trace_context.is_trace_id(tid.upper())
    assert not trace_context.is_trace_id(None)
    # the chrome flow id is a pure function of the trace id, so two
    # processes derive the SAME id without coordination
    assert trace_context.flow_id(tid) == trace_context.flow_id(tid)
    assert 0 <= trace_context.flow_id(tid) < 2 ** 63


def test_trace_rides_audit_reqspan_and_flow(model):
    tracer.clear()
    r = _router(model, "fleet_tid")
    try:
        r.submit(np.arange(6, dtype=np.int64),
                 max_new_tokens=5).result(timeout=60)
        placed = [e for e in _audit(r) if e["reason"] in
                  ("ROUTE_AFFINITY", "ROUTE_LEAST_PRESSURE")]
        assert placed and trace_context.is_trace_id(placed[-1]["trace"])
        tid = placed[-1]["trace"]
        # the reqspan instant carries the SAME id the audit logged
        tids = _reqspan_tids()
        assert [tid] in list(tids.values())
        # the flow chain for it: router start + replica step + finish
        fid = trace_context.flow_id(tid)
        phs = sorted(ph.split("#")[0] for name, ph, *_ in
                     tracer.events(with_threads=True)
                     if name == "fleet_request"
                     and ph.endswith(f"#{fid}"))
        assert phs == ["f", "s", "t"]
    finally:
        r.shutdown()


def test_trace_id_stable_across_reroute(model):
    prompts = _prompts_shared_prefix(2, seed=11)
    tracer.clear()
    r = _router(model, "fleet_reroute")
    try:
        # warm the sketch so affinity pins the follow-up to `first`
        r.submit(prompts[0], max_new_tokens=5).result(timeout=60)
        first = [rep for rep in r._replicas if rep.placements == 1][0]
        real = first.sup.submit

        def overloaded_once(prompt_ids, **kw):
            first.sup.submit = real
            raise EngineOverloaded("queue full (injected)")

        first.sup.submit = overloaded_once
        r.submit(prompts[1], max_new_tokens=5).result(timeout=60)
        evs = _audit(r)
        reroute = [e for e in evs if e["reason"] == "ROUTE_REROUTE"]
        assert reroute and trace_context.is_trace_id(
            reroute[-1]["trace"])
        tid = reroute[-1]["trace"]
        # the SAME id on the placement attempts before and after the
        # re-route — one trace id names the request wherever it lands
        attempts = [e for e in evs if e.get("trace") == tid]
        assert len(attempts) >= 3  # place, reroute edge, re-place
        assert tid in [t for ts in _reqspan_tids().values() for t in ts]
    finally:
        r.shutdown()


def test_trace_id_stable_across_supervised_restart(model):
    prompts = _prompts_shared_prefix(4, seed=13)
    prev = paddle.get_flags(["FLAGS_failpoints",
                             "FLAGS_gen_restart_backoff_ms"])
    tracer.clear()
    try:
        paddle.set_flags({"FLAGS_failpoints": "decode_step_raise@6",
                          "FLAGS_gen_restart_backoff_ms": 5.0})
        r = _router(model, "fleet_restart")
        try:
            futs = [r.submit(q, max_new_tokens=5) for q in prompts]
            for f in futs:
                f.result(timeout=120)
            assert sum(rep.sup.restarts for rep in r._replicas) == 1
            # the replay admissions audited the ids the ReplayEntries
            # preserved into the rebuilt engine
            from paddle_tpu.profiler import audit as audit_log
            replay_tids = {
                e["trace"] for rep in r._replicas
                for e in audit_log.tail_for(rep.name, 256)
                if e["reason"] == "REPLAY_ADMIT"}
            assert replay_tids
            assert all(trace_context.is_trace_id(t)
                       for t in replay_tids)
            # a replayed request FINISHES under the same id it was
            # first placed with (the dead incarnation's span never
            # finishes, so the resolving reqspan is incarnation 1's)
            finished = {t for ts in _reqspan_tids().values()
                        for t in ts}
            carried = replay_tids & finished
            assert carried, (replay_tids, finished)
            # flow chain of a replayed request: one start, >=2 steps
            # (one per incarnation's span), at least one finish
            tid = next(iter(carried))
            fid = trace_context.flow_id(tid)
            phs = [ph.split("#")[0] for name, ph, *_ in
                   tracer.events(with_threads=True)
                   if name == "fleet_request"
                   and ph.endswith(f"#{fid}")]
            assert phs.count("s") == 1 and phs.count("t") >= 2
            assert phs.count("f") >= 1
        finally:
            r.shutdown()
    finally:
        paddle.set_flags(prev)


def test_engine_accepts_and_validates_caller_trace_id(model):
    tracer.clear()
    eng = _engine(model, "fleet_direct")
    try:
        tid = trace_context.new_trace_id()
        eng.submit(np.arange(6, dtype=np.int64), max_new_tokens=4,
                   trace_id=tid).result(timeout=60)
        assert [tid] in list(_reqspan_tids().values())
        # a malformed id is REJECTED, not propagated: the engine mints
        # its own instead of forging fleet correlation
        eng.submit(np.arange(6, dtype=np.int64), max_new_tokens=4,
                   trace_id="not-a-trace").result(timeout=60)
        all_tids = [t for ts in _reqspan_tids().values() for t in ts]
        assert "not-a-trace" not in all_tids
        assert len(all_tids) == 2
        # stream delivery exposes the id to the caller
        stream = eng.submit_stream(np.arange(6, dtype=np.int64),
                                   max_new_tokens=4)
        for _ in stream:
            pass
        stream.result(timeout=60)
        assert trace_context.is_trace_id(stream.trace_id)
    finally:
        eng.shutdown()


def test_flag_off_is_zero_cost(model):
    prev = paddle.get_flags(["FLAGS_trace_propagation"])
    tracer.clear()
    try:
        paddle.set_flags({"FLAGS_trace_propagation": False})
        r = _router(model, "fleet_off")
        try:
            r.submit(np.arange(6, dtype=np.int64),
                     max_new_tokens=5).result(timeout=60)
            # no ids minted anywhere: audits carry no trace=, reqspans
            # no ,tid=, and no fleet_request flow events exist
            assert all("trace" not in e for e in _audit(r))
            assert not _reqspan_tids()
            assert not [1 for name, *_ in
                        tracer.events(with_threads=True)
                        if name == "fleet_request"]
        finally:
            r.shutdown()
    finally:
        paddle.set_flags(prev)


# -- tentpole: time-series metrics ring --------------------------------------

def test_history_records_rates_levels_and_pressure(model):
    eng = _engine(model, "fleet_hist")
    try:
        eng.submit(np.arange(6, dtype=np.int64),
                   max_new_tokens=6).result(timeout=60)
        timeseries.sample()
        eng.submit(np.arange(6, dtype=np.int64),
                   max_new_tokens=6).result(timeout=60)
        timeseries.sample()
        payload = timeseries.history_payload()
        series = payload["series"]
        # a counter shows up kind=rate and needs TWO samples (rates
        # are deltas; the first sample only anchors). The background
        # sampler may add at most one extra tick mid-test, so bound,
        # don't pin, the point count
        gen = series.get("STAT_gen_tokens")
        assert gen and gen["kind"] == "rate"
        assert 1 <= len(gen["points"]) <= 3
        # some recorded interval covered a submit, so tokens/sec moved
        assert max(v for _, v in gen["points"]) > 0
        # pressure ticks ride per-replica series
        for field in ("queue_depth", "live", "free_pages",
                      "oldest_age_ms"):
            s = series[f"pressure:fleet_hist:{field}"]
            assert s["kind"] == "level" and 2 <= len(s["points"]) <= 3
        # the payload round-trips as JSON (the /history contract)
        json.dumps(payload)
    finally:
        eng.shutdown()


def test_history_ring_is_bounded_under_churn(model):
    prev = paddle.get_flags(["FLAGS_metrics_history_samples"])
    try:
        paddle.set_flags({"FLAGS_metrics_history_samples": 4})
        eng = _engine(model, "fleet_cap")
        try:
            for _ in range(9):
                timeseries.sample()
            series = timeseries.history_payload()["series"]
            assert series  # pressure ticks at minimum
            for name, s in series.items():
                assert len(s["points"]) <= 4, name
            # oldest-first within the cap, timestamps monotonic
            pts = series["pressure:fleet_cap:queue_depth"]["points"]
            assert len(pts) == 4
            assert [p[0] for p in pts] == sorted(p[0] for p in pts)
        finally:
            eng.shutdown()
    finally:
        paddle.set_flags(prev)


def test_history_scrape_race_free_vs_die_and_drain(model):
    """Concurrent /history scrapes + sampler ticks while one engine
    dies mid-decode and another drains: no scrape may error and every
    payload must parse — the exporter contract under a torn fleet."""
    stop = threading.Event()
    failures = []

    def scraper():
        while not stop.is_set():
            try:
                json.dumps(timeseries.history_payload())
                timeseries.sample()
            except Exception as e:  # noqa: BLE001
                failures.append(repr(e))

    threads = [threading.Thread(target=scraper, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        eng1 = _engine(model, "fleet_race_die")
        f1 = eng1.submit(np.arange(6, dtype=np.int64), max_new_tokens=8)
        f1.result(timeout=60)
        eng1._die(RuntimeError("die under scrape"))
        eng2 = _engine(model, "fleet_race_drain")
        f2 = eng2.submit(np.arange(6, dtype=np.int64), max_new_tokens=6)
        eng2.shutdown(drain=True, timeout_s=60)
        assert f2.result(timeout=5) is not None
        time.sleep(0.1)  # several scrape rounds against the torn state
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        eng1.shutdown(drain=False, timeout_s=30)
    assert not failures, failures[:5]


def test_history_endpoint_and_chrome_counters(model):
    srv = exporter.start_metrics_server(0)
    assert srv is not None
    try:
        eng = _engine(model, "fleet_http")
        try:
            eng.submit(np.arange(6, dtype=np.int64),
                       max_new_tokens=5).result(timeout=60)
            timeseries.sample()
            timeseries.sample()
            import urllib.request
            with urllib.request.urlopen(f"{srv.url}/history",
                                        timeout=10) as resp:
                payload = json.loads(resp.read())
            assert payload["samples"] >= 1
            assert "pressure:fleet_http:queue_depth" in \
                payload["series"]
            # /trace embeds the same series as chrome "C" counter rows
            with urllib.request.urlopen(f"{srv.url}/trace",
                                        timeout=10) as resp:
                trace = json.loads(resp.read())
            hist = [e for e in trace["traceEvents"]
                    if e.get("ph") == "C"
                    and str(e.get("name", "")).startswith("history:")]
            assert hist
        finally:
            eng.shutdown()
    finally:
        srv.close()


def test_history_sampler_off_at_interval_zero(model):
    prev = paddle.get_flags(["FLAGS_metrics_history_interval_s"])
    try:
        paddle.set_flags({"FLAGS_metrics_history_interval_s": 0.0})
        timeseries.touch()
        assert not timeseries.active()
        payload = timeseries.history_payload()
        assert payload["enabled"] is False
    finally:
        paddle.set_flags(prev)


# -- tentpole: per-step goodput attribution ----------------------------------

def test_attribution_buckets_sum_exactly_to_wall(model):
    eng = _engine(model, "fleet_attr")
    try:
        futs = [eng.submit(np.arange(6, dtype=np.int64) + i,
                           max_new_tokens=8) for i in range(4)]
        for f in futs:
            f.result(timeout=60)
        payload = step_log.steps_payload()
        recs = payload["engines"]["fleet_attr"]["records"]
        attributed = [r for r in recs if r.get("attr_wall_ms", 0) > 0]
        assert attributed
        for r in attributed:
            total = (r["attr_admit_ms"] + r["prefill_ms"]
                     + r["attr_promote_ms"] + r["decode_ms"]
                     + r["attr_bookkeep_ms"] + r["attr_idle_ms"])
            # EXACT reconciliation: bookkeep is the rounded remainder,
            # so the stored buckets sum to the stored wall to the
            # float, not approximately
            assert abs(total - r["attr_wall_ms"]) < 1e-9, r
        # work actually landed in the work buckets
        assert sum(r["prefill_ms"] for r in attributed) > 0
        assert sum(r["decode_ms"] for r in attributed) > 0
    finally:
        eng.shutdown()


def test_attribution_histograms_and_report(model):
    from paddle_tpu.framework import monitor
    base = {n: h.get("count", 0)
            for n, h in monitor.all_histograms().items()}
    eng = _engine(model, "fleet_attr_hist")
    try:
        eng.submit(np.arange(6, dtype=np.int64),
                   max_new_tokens=8).result(timeout=60)
        # read /steps while the engine is live — shutdown unregisters
        # its ring from the payload
        recs = [r for e in step_log.steps_payload()["engines"].values()
                for r in e["records"]]
    finally:
        eng.shutdown()
    hists = monitor.all_histograms()
    # one observation per bucket per attributed iteration — the whole
    # STAT_gen_step_attr_* family moves in lockstep
    for short in ("admit", "prefill", "promote", "decode", "bookkeep",
                  "idle"):
        name = f"STAT_gen_step_attr_{short}_ms"
        assert hists.get(name, {}).get("count", 0) > \
            base.get(name, 0), name
    # the engine_report goodput section reconciles the same records
    import importlib.util
    import os
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    spec = importlib.util.spec_from_file_location(
        "engine_report", os.path.join(tools, "engine_report.py"))
    er = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(er)
    g = er.goodput(recs)
    assert g and g["wall_ms"] > 0
    for buckets in g["by_incarnation"].values():
        parts = sum(v for k, v in buckets.items() if k != "wall_ms")
        assert abs(parts - buckets["wall_ms"]) < 1e-6


def test_step_log_off_still_safe(model):
    prev = paddle.get_flags(["FLAGS_gen_step_log"])
    try:
        paddle.set_flags({"FLAGS_gen_step_log": False})
        eng = _engine(model, "fleet_attr_off")
        try:
            out = eng.submit(np.arange(6, dtype=np.int64),
                             max_new_tokens=5).result(timeout=60)
            assert out is not None
            assert "fleet_attr_off" not in \
                step_log.steps_payload()["engines"]
        finally:
            eng.shutdown()
    finally:
        paddle.set_flags(prev)


# -- satellite: the fleet_trace merge tool -----------------------------------

def _fleet_trace():
    import importlib.util
    import os
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    spec = importlib.util.spec_from_file_location(
        "fleet_trace", os.path.join(tools, "fleet_trace.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_trace_merges_synthesized_replicas(tmp_path):
    ft = _fleet_trace()
    tid_ok = trace_context.new_trace_id()
    tid_cut = trace_context.new_trace_id()
    fid_ok = trace_context.flow_id(tid_ok)
    fid_cut = trace_context.flow_id(tid_cut)

    def flow(ph, fid, ts, pid):
        return {"name": "fleet_request", "ph": ph, "id": fid,
                "ts": ts, "pid": pid, "tid": 1, "cat": "serving"}

    # router file: starts both requests; replica file: steps + finishes
    # only the first; the second request's replica file is "lost"
    router = [flow("s", fid_ok, 10, 1), flow("s", fid_cut, 11, 1)]
    replica = [
        flow("t", fid_ok, 20, 2), flow("f", fid_ok, 90, 2),
        {"name": f"reqspan:1:r0:slot0:n=4:ttft=1.0,tpot=1.0,e=4.0,"
                 f"pfx=0,acc=0,inc=0,tid={tid_ok}",
         "ph": "i", "ts": 91, "pid": 2, "tid": 1},
        # an overlapping-scrape duplicate that must dedup away
        flow("t", fid_ok, 20, 2),
    ]
    a, b = tmp_path / "router.json", tmp_path / "replica.json"
    a.write_text(json.dumps({"traceEvents": router}))
    b.write_text(json.dumps({"traceEvents": replica}))

    trace, report = ft.merge([str(a), str(b)])
    assert report["chains"] == 2
    assert report["resolved"] == 1
    assert report["multi_hop"] == 1
    # the cut chain is named by flow id (no reqspan carried its tid)
    assert report["unresolved"] == [f"flow#{fid_cut}"]
    assert report["trace_ids"] == [tid_ok]
    # dedup dropped the doubled step; the merged file adds one
    # process_name row per source pid
    flows = [e for e in trace["traceEvents"]
             if e.get("name") == "fleet_request"]
    assert len(flows) == 4
    names = [e for e in trace["traceEvents"]
             if e.get("ph") == "M"]
    assert {e["pid"] for e in names} == {1, 2}
    # CLI contract: a merge with a cut chain exits 1, a complete merge
    # exits 0 (bench's router-mode smoke gates on this)
    out = tmp_path / "merged.json"
    assert ft.main([str(a), str(b), "--out", str(out), "--json"]) == 1
    assert json.loads(out.read_text())["traceEvents"]
    c = tmp_path / "complete.json"
    c.write_text(json.dumps({"traceEvents": router[:1] + replica}))
    assert ft.main([str(c), "--json"]) == 0
