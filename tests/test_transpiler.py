"""Legacy PS program split (reference
`python/paddle/fluid/transpiler/distribute_transpiler.py:156`): a static
train Program transpiles into trainer pull→grad→push wrappers and
pserver table configs; the distributed trajectory must equal local SGD."""
import socket

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.distributed import DistributeTranspiler
from paddle_tpu.distributed.ps import PsServer, native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native ps_core not built")


def _build_train_program():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [8, 4], "float32")
        y = static.data("y", [8, 1], "float32")
        w = paddle.create_parameter([4, 1], "float32", name="w")
        b = paddle.create_parameter([1], "float32", name="b")
        pred = paddle.matmul(x, w) + b
        loss = paddle.mean((pred - y) * (pred - y))
        opt = paddle.optimizer.SGD(0.1)
        opt.minimize(loss)
    return main, loss


def test_transpile_split_and_loss_parity(tmp_path):
    rs = np.random.RandomState(0)
    feed_x = rs.standard_normal((8, 4)).astype("float32")
    feed_y = rs.standard_normal((8, 1)).astype("float32")

    # ---- local baseline ---------------------------------------------------
    static.enable_static()
    try:
        with static.scope_guard({}):
            paddle.seed(42)
            main, loss = _build_train_program()
            exe = static.Executor()
            local_losses = [
                exe.run(main, feed={"x": feed_x, "y": feed_y},
                        fetch_list=[loss])[0] for _ in range(4)]

        # ---- transpiled cluster (2 pservers, 1 trainer) -------------------
        with static.scope_guard({}):
            paddle.seed(42)
            main2, loss2 = _build_train_program()
            socks = []
            for _ in range(2):            # two distinct free ports
                s = socket.socket()
                s.bind(("127.0.0.1", 0))
                socks.append(s)
            eps_str = ",".join(f"127.0.0.1:{s.getsockname()[1]}"
                               for s in socks)
            for s in socks:
                s.close()
            t = DistributeTranspiler()
            t.transpile(0, program=main2, pservers=eps_str, trainers=1)
            # placement split across endpoints
            eps = {ep for ep, _ in t._placement.values()}
            assert len(eps) == 2

            servers = []
            for ep in t._pservers:
                cfgs = t.get_pserver_program(ep)
                assert cfgs, f"no tables for {ep}"
                servers.append(PsServer(ep, cfgs, n_workers=1).start())

            trainer = t.get_trainer_program()
            real_eps = t._pservers
            # seed tables with the initial param values
            srv_of = {ep: i for i, ep in enumerate(real_eps)}
            for n, (ep, tid) in t._placement.items():
                init = t.get_startup_program(ep)[tid]
                trainer.client.set_dense(tid, init, server=srv_of[ep])

            dist_losses = [trainer.run({"x": feed_x, "y": feed_y})
                           for _ in range(4)]
            trainer.close()
            for s in servers:
                s.stop()
    finally:
        static.disable_static()

    np.testing.assert_allclose(
        dist_losses, [float(np.asarray(l)) for l in local_losses],
        rtol=2e-4, atol=2e-5)
    assert dist_losses[-1] < dist_losses[0]
