"""DownpourWorker (reference `framework/device_worker.h:148` +
`downpour_worker.cc`): per-batch sparse pull → device fwd/bwd → async
grad push over FleetWrapper, driven from a Dataset stream."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet import DownpourWorker, FleetWrapper
from paddle_tpu.distributed.ps import native_available
from paddle_tpu.distributed.ps.service import TableConfig

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native ps_core not built")

DIM, SEQ, B = 8, 4, 6


class Head(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(DIM, 2)

    def forward(self, emb_flat, labels):
        from paddle_tpu.framework.tensor import Tensor
        e = Tensor(emb_flat).reshape([B, SEQ, DIM])
        return self.fc(e.mean(axis=1))


def _batches(n, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = rs.randint(0, 30, size=(B, SEQ)).astype("int64")
        labels = rs.randint(0, 2, size=(B,)).astype("int64")
        out.append((ids, labels))
    return out


def test_downpour_worker_trains():
    paddle.seed(0)
    fw = FleetWrapper()
    ep = fw.init_server("127.0.0.1:0",
                        [TableConfig(0, "sparse", dim=DIM, rule="sgd",
                                     lr=0.1)])
    fw.init_worker([ep])
    try:
        head = Head()
        opt = paddle.optimizer.SGD(0.1, parameters=head.parameters())
        ce = nn.CrossEntropyLoss()

        def loss_fn(out, data):
            from paddle_tpu.framework.tensor import Tensor
            return ce(out, Tensor(data[0]))

        worker = DownpourWorker(fw, sparse_table_id=0, fea_dim=DIM,
                                dense_layer=head, optimizer=opt,
                                loss_fn=loss_fn)
        # repeat the same 3 batches so the loss must go down
        losses = worker.train_from_dataset(_batches(3) * 5, epochs=1,
                                           flush_every=3)
        assert len(losses) == 15
        assert all(np.isfinite(losses))
        assert np.mean(losses[-3:]) < np.mean(losses[:3])
        # sparse rows actually moved server-side
        ids0 = _batches(1)[0][0].reshape(-1)
        rows = fw.pull_sparse_vars_sync(0, np.unique(ids0))
        assert np.abs(rows).sum() > 0
    finally:
        fw.stop_server()
