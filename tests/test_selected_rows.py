"""SelectedRows sparse-row gradients (reference
`framework/selected_rows.h` + the sparse optimizer kernels in
`operators/optimizers/` + MergeAdd in
`operators/math/selected_rows_functor.cc`)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.selected_rows import (SelectedRows,
                                                rows_of_embedding_grad)
from paddle_tpu.ops.legacy import (get_tensor_from_selected_rows,
                                   merge_selected_rows)


def test_merge_sums_duplicates():
    s = SelectedRows([3, 1, 3], np.array([[1., 2.], [3., 4.], [5., 6.]],
                                         np.float32), height=5)
    m = s.merge()
    np.testing.assert_array_equal(m.rows, [1, 3])
    np.testing.assert_allclose(m.value, [[3., 4.], [6., 8.]])
    dense = m.to_dense()
    assert dense.shape == (5, 2)
    np.testing.assert_allclose(dense[3], [6., 8.])
    np.testing.assert_allclose(dense[0], [0., 0.])


def test_legacy_ops_accept_selected_rows():
    s = SelectedRows([0, 0], np.ones((2, 3), np.float32), height=4)
    m = merge_selected_rows(s)
    assert isinstance(m, SelectedRows) and m.rows.size == 1
    t = get_tensor_from_selected_rows(s)
    np.testing.assert_allclose(np.asarray(t.numpy())[0], [2., 2., 2.])


def test_embedding_grad_builder():
    ids = np.array([[1, 2], [2, 1]], np.int64)
    dout = np.ones((2, 2, 4), np.float32)
    s = rows_of_embedding_grad(ids, dout, height=10)
    np.testing.assert_array_equal(s.rows, [1, 2])
    np.testing.assert_allclose(s.value, np.full((2, 4), 2.0))


def _sparse_vs_dense(opt_cls, **kw):
    """Sparse row update must equal the dense update on touched rows and
    leave untouched rows (params AND accumulators) alone."""
    V, D = 6, 3
    w0 = np.random.RandomState(0).standard_normal((V, D)).astype("float32")
    g_rows = np.array([1, 4], np.int64)
    g_vals = np.random.RandomState(1).standard_normal((2, D)).astype(
        "float32")

    p_sparse = paddle.create_parameter([V, D], "float32")
    p_sparse.set_value(w0.copy())
    opt_s = opt_cls(0.1, parameters=[p_sparse], **kw)
    opt_s.apply_selected_rows(
        p_sparse, SelectedRows(g_rows, g_vals, height=V))

    p_dense = paddle.create_parameter([V, D], "float32")
    p_dense.set_value(w0.copy())
    opt_d = opt_cls(0.1, parameters=[p_dense], **kw)
    dense_g = np.zeros((V, D), np.float32)
    dense_g[g_rows] = g_vals
    from paddle_tpu.framework.tensor import Tensor
    p_dense._grad = Tensor(dense_g)._value
    opt_d.step()

    sp, dn = p_sparse.numpy(), p_dense.numpy()
    np.testing.assert_allclose(sp[g_rows], dn[g_rows], rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_array_equal(sp[[0, 2, 3, 5]], w0[[0, 2, 3, 5]])


def test_sparse_sgd_matches_dense():
    _sparse_vs_dense(paddle.optimizer.SGD)


def test_sparse_momentum_matches_dense_on_touched_rows():
    _sparse_vs_dense(paddle.optimizer.Momentum)


def test_sparse_adam_updates_only_touched_state():
    V, D = 5, 2
    p = paddle.create_parameter([V, D], "float32")
    p.set_value(np.ones((V, D), np.float32))
    opt = paddle.optimizer.Adam(0.01, parameters=[p])
    opt.apply_selected_rows(
        p, SelectedRows([2], np.ones((1, D), np.float32), height=V))
    st = opt._accumulators[id(p)]
    m = np.asarray(st["m"]) if "m" in st else None
    if m is not None:
        assert np.any(m[2] != 0)
        np.testing.assert_array_equal(m[0], np.zeros(D))
