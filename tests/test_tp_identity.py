"""Mesh-slice lanes (ISSUE 19): a tensor-parallel GenerationEngine
replica must be OUTPUT-IDENTICAL to the single-chip lane.

The engine's programs rebuild under shard_map over a 'tp' mesh axis —
attention/MLP projections and the paged K/V pools (plus the int8 scale
grids) head-sharded, page tables and logits replicated, one psum per
block at the row-parallel projections. None of that may be observable
from outside: greedy AND sampled tokens must match tp=1 exactly on the
CPU virtual-device mesh (conftest forces 8 host devices), across fp32
and int8 KV, through a prefix-cache hit's tail prefill and through a
speculative verify step. Compile discipline carries over unchanged —
the warmed ledger is exactly-once and no live request traces.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.framework import monitor
from paddle_tpu.framework.errors import InvalidArgumentError
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.kv_cache import PagedKVCache


@pytest.fixture(scope="module")
def model():
    paddle.seed(11)
    cfg = GPTConfig.tiny(dropout=0.0)   # 4 heads: tp in {1, 2, 4}
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


def _engine(model, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 64)
    kw.setdefault("prefill_buckets", (8,))
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("request_timeout_ms", 0)
    return serving.GenerationEngine(model, **kw)


def _prompts(n=3, S=7, seed=0, vocab=512):
    return [np.random.RandomState(seed + i).randint(
        0, vocab, size=(S,)).astype("int64") for i in range(n)]


def _run(model, tp, prompts, sample=False, **kw):
    with _engine(model, tp=tp, name=f"tpid{tp}{'s' if sample else ''}",
                 **kw) as eng:
        outs = [eng.generate(p, max_new_tokens=6, do_sample=sample,
                             temperature=0.8 if sample else 1.0)
                for p in prompts]
        return outs, eng.stats()


# -- token identity ---------------------------------------------------------

@pytest.mark.parametrize("tp", [2, 4])
def test_tp_greedy_token_identity_fp32(model, tp):
    prompts = _prompts()
    ref, s1 = _run(model, 1, prompts)
    got, sN = _run(model, tp, prompts)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a, b)
    # same warmed exactly-once ledger on both lanes — the sharded pack
    # minted no extra programs and no live request traced
    assert sN["compiles"] == s1["compiles"]
    assert all(v == 1 for v in sN["compiles"].values())
    assert sN["tp"] == tp and s1["tp"] == 1


def test_tp_sampled_token_identity(model):
    """Sampling shares the engine PRNG stream: the replicated key and
    the (psum-identical) logits must draw the same tokens per shard —
    and the same tokens as the single-chip lane."""
    prompts = _prompts(seed=3)
    ref, _ = _run(model, 1, prompts, sample=True)
    got, _ = _run(model, 2, prompts, sample=True)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a, b)


def test_tp_greedy_token_identity_int8_kv(model):
    """int8 page mode: the scale grids shard along heads with the
    pools; quantize-on-append and dequant-on-gather are per-head math,
    so sharded quantization is bit-identical to the single chip's."""
    prompts = _prompts(seed=5)
    ref, _ = _run(model, 1, prompts, kv_cache_dtype="int8")
    got, s = _run(model, 2, prompts, kv_cache_dtype="int8")
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a, b)
    assert s["pages"]["quantized"] and s["pages"]["tp"] == 2


def test_tp_prefix_hit_token_identity(model):
    """A prefix-cache hit rides the tail-prefill program — under tp its
    all-layers gather walks head-sharded pools. Same prompt twice: the
    second admission must hit the cached chain AND produce identical
    tokens to the tp=1 lane's identical hit."""
    rng = np.random.RandomState(9)
    prefix = rng.randint(0, 512, size=(8,)).astype("int64")
    tails = [rng.randint(0, 512, size=(3,)).astype("int64")
             for _ in range(2)]
    prompts = [np.concatenate([prefix, t]) for t in tails]

    def run(tp):
        with _engine(model, tp=tp, prefix_cache=True,
                     prefill_buckets=(4, 16),
                     name=f"tppfx{tp}") as eng:
            outs = [eng.generate(p, max_new_tokens=6) for p in prompts]
            return outs, eng.stats()

    ref, s1 = run(1)
    got, sN = run(2)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a, b)
    # the hit actually happened on the sharded lane (shared pages +
    # tail program), and nothing traced outside warmup
    assert sN["kv"]["prefix"]["hits"] >= 1
    assert all(v == 1 for v in sN["compiles"].values())


def test_tp_spec_verify_token_identity(model):
    """Speculative decoding replaces the decode program with ONE
    verify[k] program — under tp that whole block (draft scoring,
    acceptance scan, scratch-routed rollback writes) runs sharded and
    must stay token-identical to the tp=1 speculative lane AND the
    plain greedy lane."""
    prompts = [np.array([7, 8, 9, 7, 8, 9, 7], np.int64),
               np.array([5, 5, 5, 5, 5, 5, 5], np.int64)]
    plain, _ = _run(model, 1, prompts)
    ref, s1 = _run(model, 1, prompts, spec_k=2)
    got, sN = _run(model, 2, prompts, spec_k=2)
    for a, b, c in zip(got, ref, plain):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)
    assert sN["compiles"]["verify[k=2]"] == 1
    assert not any(k.startswith("decode") for k in sN["compiles"])
    assert sN["compiles"] == s1["compiles"]


def test_tp_tier_demote_promote_token_identity(model):
    """Host-tier round trip under tp (ISSUE 18 seam): the demotion
    gather's sharded out_specs reassemble every head shard into ONE
    full host page, and the chunked promotion upload splits the staged
    full blocks back across the slice — token identity with the tp=1
    tier lane proves the reassembly is lossless both ways."""
    rng = np.random.RandomState(31)
    prompts = [np.concatenate([rng.randint(0, 512, size=(8,)),
                               rng.randint(0, 512, size=(3,))])
               .astype("int64") for _ in range(8)]

    def run(tp):
        with _engine(model, tp=tp, num_pages=12, prefill_buckets=(16,),
                     max_new_tokens=4, prefix_cache=True, kv_tier=True,
                     kv_tier_host_bytes=64 << 20, kv_tier_chunk_pages=2,
                     name=f"tptier{tp}") as eng:
            flood = [eng.generate(p, max_new_tokens=4) for p in prompts]
            again = eng.generate(prompts[0], max_new_tokens=4)
            return flood + [again], eng.stats()

    ref, s1 = run(1)
    got, sN = run(2)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a, b)
    # the sharded lane really demoted AND promoted through the tier
    assert sN["kv"]["prefix"]["demotions"] >= 2
    assert sN["kv"]["prefix"]["promotions"] >= 2
    assert sN["compiles"]["tier_gather"] == 1


# -- capacity / gauges ------------------------------------------------------

def test_tp_shard_bytes_and_gauge(model):
    base = monitor.stat_get("STAT_tp_kv_shard_bytes") or 0
    with _engine(model, tp=2, name="tpgauge") as eng:
        s = eng.stats()["pages"]
        assert s["tp"] == 2
        assert s["shard_hbm_bytes"] * 2 == s["hbm_bytes"]
        # the live per-shard gauge carries exactly this cache's share
        assert (monitor.stat_get("STAT_tp_kv_shard_bytes") - base
                == s["shard_hbm_bytes"])
        pr = eng.pressure()
        assert pr["tp"] == 2
        assert pr["kv_shard_bytes"] == s["shard_hbm_bytes"]


def test_tp_page_arithmetic_per_shard():
    """page_hbm_bytes/pages_for_budget size against ONE chip of the
    slice: the same per-chip budget admits tp× the pages — the
    serve-larger-models unlock, and the admission arithmetic stays in
    tp-invariant page units (the page axis is full on every shard)."""
    kw = dict(num_layers=2, num_heads=4, head_dim=16, page_size=4)
    full = PagedKVCache.page_hbm_bytes(**kw)
    half = PagedKVCache.page_hbm_bytes(**kw, tp=2)
    assert half * 2 == full
    n1 = PagedKVCache.pages_for_budget(1 << 20, **kw)
    n2 = PagedKVCache.pages_for_budget(1 << 20, **kw, tp=2)
    assert n2 == 2 * n1
    q = PagedKVCache.page_hbm_bytes(**kw, dtype="int8", tp=2)
    assert q * 2 == PagedKVCache.page_hbm_bytes(**kw, dtype="int8")
    with pytest.raises(InvalidArgumentError):
        PagedKVCache.page_hbm_bytes(**kw, tp=3)   # 4 heads % 3 != 0


def test_tp_validation(model):
    with pytest.raises(InvalidArgumentError):
        _engine(model, tp=3, name="tpbad")        # 4 heads % 3 != 0
    with pytest.raises(InvalidArgumentError):
        serving.GenerationConfig(tp=0)
