"""Pipelined multi-device serving: shared collector + per-chip dispatch
lanes with async completion.

Runs on the 8-virtual-device CPU mesh (conftest), so every lane is a
real jax device: replica placement, per-(device, bucket) warmup
compiles, and lane failover exercise the same code path a multi-chip
host uses. Numerics note: different lanes (devices) are different
compiled executables — cross-lane results are compared with allclose,
not bitwise (bit-identity holds only within one compiled shape/device).
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference, serving
from paddle_tpu.framework import monitor
from paddle_tpu.framework.errors import (ExecutionTimeoutError,
                                         UnavailableError)
from paddle_tpu.static.input_spec import InputSpec


class _Mlp(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(paddle.tanh(self.fc1(x)))


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    paddle.seed(11)
    prefix = str(tmp_path_factory.mktemp("serving_ml") / "mlp")
    paddle.jit.save(_Mlp(), prefix,
                    input_spec=[InputSpec([None, 8], "float32")])
    return prefix


def _x(rows, seed=0):
    return np.random.RandomState(seed).standard_normal(
        (rows, 8)).astype("float32")


# ---------------------------------------------------------------------------
# tentpole: one dispatch lane (+ Predictor replica) per local device
# ---------------------------------------------------------------------------

def test_path_model_defaults_to_all_local_devices(artifact):
    import jax
    n = len(jax.local_devices())
    assert n >= 2  # conftest forces the 8-virtual-device mesh
    # oracle predictor built + warmed BEFORE the compile snapshot so its
    # own trace never pollutes the engine's compile ledger below
    pred = inference.create_predictor(inference.Config(artifact))
    pred.run([_x(1)])
    c0 = monitor.stat_get("STAT_predictor_compiles")
    eng = serving.InferenceEngine(artifact, batch_buckets=(1, 4),
                                  max_batch_size=4, max_batch_delay_ms=1.0,
                                  name="ml_default")
    try:
        s = eng.stats()
        assert len(s["lanes"]) == n
        assert len({l["device"] for l in s["lanes"]}) == n  # distinct chips
        # warmup compiled every (device, bucket) pair exactly once — the
        # per-replica trace counters sum into STAT_predictor_compiles
        assert monitor.stat_get("STAT_predictor_compiles") - c0 == 2 * n
        futs = [eng.submit(_x(1, seed=i)) for i in range(6 * n)]
        res = [f.result(timeout=60) for f in futs]
        # correctness on every lane: allclose vs the single-predictor
        # oracle (different devices = different executables; bitwise
        # identity is only guaranteed within one compiled shape/device)
        for i, r in enumerate(res):
            np.testing.assert_allclose(r[0], pred.run([_x(1, seed=i)])[0],
                                       rtol=1e-5, atol=1e-6)
        s = eng.stats()
        assert sum(l["batches"] for l in s["lanes"]) >= 1
        assert sum(l["rows"] for l in s["lanes"]) == 6 * n
        # no live compiles beyond warmup, on ANY lane
        assert monitor.stat_get("STAT_predictor_compiles") - c0 == 2 * n
        assert all(c == 1 for l in s["lanes"]
                   for c in l["bucket_compiles"].values())
    finally:
        eng.shutdown()


def test_explicit_device_list_pins_replicas(artifact):
    import jax
    local = jax.local_devices()
    eng = serving.InferenceEngine(artifact, devices=[0, 3],
                                  batch_buckets=(1,), max_batch_size=1,
                                  max_batch_delay_ms=0.0, name="ml_pin")
    try:
        s = eng.stats()
        assert [l["device"] for l in s["lanes"]] == [str(local[0]),
                                                     str(local[3])]
        assert eng._lanes[0].predictor.device == local[0]
        assert eng._lanes[1].predictor.device == local[3]
        # replicas share the deserialized artifact but not jit state
        assert (eng._lanes[0].predictor._translated
                is eng._lanes[1].predictor._translated)
        assert eng.run(_x(1))[0].shape == (1, 4)
    finally:
        eng.shutdown()


def test_user_predictor_stays_single_lane(artifact):
    # replicating a user-built Predictor implicitly would be a surprise:
    # devices=None keeps the engine on exactly that replica
    pred = inference.create_predictor(inference.Config(artifact))
    eng = serving.InferenceEngine(pred, batch_buckets=(1,),
                                  max_batch_size=1, max_batch_delay_ms=0.0)
    try:
        assert len(eng.stats()["lanes"]) == 1
        assert eng._lanes[0].predictor is pred
    finally:
        eng.shutdown()


def test_caller_predictor_never_mutated_by_pinning(artifact):
    import jax
    pred = inference.create_predictor(inference.Config(artifact))
    assert pred.device is None
    eng = serving.InferenceEngine(pred, devices=[1, 2], batch_buckets=(1,),
                                  max_batch_size=1, max_batch_delay_ms=0.0)
    try:
        assert pred.device is None  # the engine pinned a CLONE, not ours
        assert eng._lanes[0].predictor is not pred
        assert eng._lanes[0].predictor.device == jax.local_devices()[1]
        assert eng.run(_x(1))[0].shape == (1, 4)
    finally:
        eng.shutdown()


def test_int_and_bad_device_specs(artifact):
    import jax
    n = len(jax.local_devices())
    eng = serving.InferenceEngine(artifact, devices=2, batch_buckets=(1,),
                                  max_batch_size=1, max_batch_delay_ms=0.0)
    try:
        assert len(eng.stats()["lanes"]) == 2
    finally:
        eng.shutdown()
    with pytest.raises(ValueError, match="host has"):
        serving.InferenceEngine(artifact, devices=n + 5)
    with pytest.raises(ValueError, match="max_inflight"):
        serving.EngineConfig(max_inflight=0)


# ---------------------------------------------------------------------------
# tentpole: async dispatch pipelines within a lane, bounded by max_inflight
# ---------------------------------------------------------------------------

class _Gate:
    """Callable model that blocks inside dispatch until released; input
    value 666 kills the lane (a BaseException, not a poisoned request)."""

    class Death(BaseException):
        pass

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()
        self.calls = 0

    def __call__(self, arrays):
        a = np.asarray(arrays[0])
        self.calls += 1
        self.entered.set()
        assert self.release.wait(30)
        if (a == 666.0).any():
            raise _Gate.Death("replica wedged")
        return [a * 2.0]


def _v(val):
    return np.full((1, 4), float(val), "float32")


def test_inflight_bound_pipelines_and_backpressures():
    gate = _Gate()
    eng = serving.InferenceEngine(
        gate, input_spec=[([None, 4], "float32")], warmup=False,
        max_batch_size=1, batch_buckets=(1,), max_batch_delay_ms=0.0,
        max_inflight=2, name="ml_pipe")
    try:
        f1 = eng.submit(_v(1))
        assert gate.entered.wait(10)  # batch 1 "on device"
        f2 = eng.submit(_v(2))        # admitted: lane pipelines batch 2
        f3 = eng.submit(_v(3))        # beyond max_inflight: stays queued
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            s = eng.stats()
            if s["lanes"][0]["inflight"] == 2 and s["queue_depth"] == 1:
                break
            time.sleep(0.005)
        s = eng.stats()
        assert s["lanes"][0]["inflight"] == 2  # dispatch ran ahead of completion
        assert s["queue_depth"] == 1           # backpressure stays at the door
        gate.release.set()
        for f, v in ((f1, 2.0), (f2, 4.0), (f3, 6.0)):
            np.testing.assert_array_equal(f.result(timeout=30)[0],
                                          np.full((1, 4), v, "float32"))
        assert eng.stats()["inflight_depth"]["max"] == 2
    finally:
        gate.release.set()
        eng.shutdown()


# ---------------------------------------------------------------------------
# tentpole: lane failover — a dead lane fails only its own in-flight work
# ---------------------------------------------------------------------------

def test_lane_failover_only_kills_own_inflight():
    g0, g1 = _Gate(), _Gate()
    eng = serving.InferenceEngine(
        [g0, g1], input_spec=[([None, 4], "float32")], warmup=False,
        max_batch_size=1, batch_buckets=(1,), max_batch_delay_ms=0.0,
        max_inflight=2, name="ml_failover")
    d0 = monitor.stat_get("STAT_serving_lane_deaths")
    try:
        # routing is deterministic: least-inflight with round-robin ties
        f1 = eng.submit(_v(666))   # lane0, enters gate0
        assert g0.entered.wait(10)
        f2 = eng.submit(_v(2))     # lane1 (least inflight), enters gate1
        assert g1.entered.wait(10)
        f3 = eng.submit(_v(3))     # tie → round-robin → lane0's inbox
        f4 = eng.submit(_v(4))     # lane0 full → lane1's inbox
        deadline = time.monotonic() + 5
        while (time.monotonic() < deadline
               and eng.stats()["lanes"][0]["inflight"] < 2):
            time.sleep(0.005)
        g0.release.set()           # lane0 dies on the 666 request
        with pytest.raises(UnavailableError, match="lane0.*died"):
            f1.result(timeout=30)
        with pytest.raises(UnavailableError, match="lane0.*died"):
            f3.result(timeout=30)  # lane0's other in-flight batch
        g1.release.set()           # lane1 unaffected
        np.testing.assert_array_equal(f2.result(timeout=30)[0], _v(4))
        np.testing.assert_array_equal(f4.result(timeout=30)[0], _v(8))
        assert monitor.stat_get("STAT_serving_lane_deaths") == d0 + 1
        s = eng.stats()
        assert [l["alive"] for l in s["lanes"]] == [False, True]
        # the engine keeps serving on the surviving lane
        for i in range(4):
            np.testing.assert_array_equal(eng.run(_v(5))[0], _v(10))
        assert g1.calls >= 6
    finally:
        g0.release.set()
        g1.release.set()
        eng.shutdown()


def test_all_lanes_dead_closes_engine():
    gate = _Gate()
    eng = serving.InferenceEngine(
        gate, input_spec=[([None, 4], "float32")], warmup=False,
        max_batch_size=1, batch_buckets=(1,), max_batch_delay_ms=0.0,
        max_inflight=1, name="ml_alldead")
    f1 = eng.submit(_v(666))
    assert gate.entered.wait(10)
    f2 = eng.submit(_v(2))  # queued behind the doomed batch
    gate.release.set()
    with pytest.raises(UnavailableError):
        f1.result(timeout=30)
    with pytest.raises(UnavailableError):
        f2.result(timeout=30)  # collector failed the stranded queue
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not eng._closed:
        time.sleep(0.005)
    with pytest.raises(UnavailableError, match="shut down"):
        eng.submit(_v(1))
    eng.shutdown()


# ---------------------------------------------------------------------------
# satellite: deadlines are enforced at completion too
# ---------------------------------------------------------------------------

def test_deadline_enforced_at_completion():
    gate = _Gate()
    eng = serving.InferenceEngine(
        gate, input_spec=[([None, 4], "float32")], warmup=False,
        max_batch_size=1, batch_buckets=(1,), max_batch_delay_ms=0.0,
        max_inflight=1, name="ml_deadline")
    t0 = monitor.stat_get("STAT_serving_timeouts")
    try:
        # the request is claimed and dispatched IMMEDIATELY (capacity is
        # free), so the queue-time deadline check never sees it; it
        # expires while "on device" inside the gate
        f = eng.submit(_v(1), timeout_ms=30.0)
        assert gate.entered.wait(10)
        time.sleep(0.08)
        gate.release.set()
        with pytest.raises(ExecutionTimeoutError, match="in flight"):
            f.result(timeout=30)
        assert monitor.stat_get("STAT_serving_timeouts") == t0 + 1
        # an un-deadlined request on the same lane still serves
        np.testing.assert_array_equal(eng.run(_v(3), timeout_ms=0)[0],
                                      _v(6))
    finally:
        gate.release.set()
        eng.shutdown()


# ---------------------------------------------------------------------------
# satellite: shutdown-during-submit races
# ---------------------------------------------------------------------------

def test_shutdown_during_submit_race():
    for _ in range(3):
        eng = serving.InferenceEngine(
            lambda arrays: [np.asarray(arrays[0]) + 1.0],
            input_spec=[([None, 4], "float32")], warmup=False,
            max_batch_size=8, batch_buckets=(8,), max_batch_delay_ms=0.2,
            name="ml_race")
        futs, lock = [], threading.Lock()
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    f = eng.submit(np.ones((1, 4), "float32"),
                                   timeout_ms=0)
                except (UnavailableError, serving.EngineOverloaded):
                    return
                with lock:
                    futs.append(f)

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        eng.shutdown()  # races live submits; must drain, never hang
        stop.set()
        for t in threads:
            t.join(10)
            assert not t.is_alive()
        assert futs
        for f in futs:
            # every accepted future resolves: a result (drained) — never
            # a silent hang
            assert f.result(timeout=10)[0].shape == (1, 4)
        with pytest.raises(UnavailableError):
            eng.submit(np.ones((1, 4), "float32"))


# ---------------------------------------------------------------------------
# satellite: monitor.reset_all_stats
# ---------------------------------------------------------------------------

def test_reset_all_stats():
    monitor.stat_add("STAT_reset_probe", 7)
    monitor.histogram("reset_probe_ms").observe(3.0)
    assert monitor.stat_get("STAT_reset_probe") == 7
    monitor.reset_all_stats()
    assert monitor.stat_get("STAT_reset_probe") == 0
    assert monitor.histogram("reset_probe_ms").count == 0
    # registry still works after reset
    monitor.stat_add("STAT_reset_probe")
    assert monitor.stat_get("STAT_reset_probe") == 1


# ---------------------------------------------------------------------------
# slow: multi-lane stress (excluded from tier-1 via -m 'not slow')
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_multilane_stress_throughput(artifact):
    c0 = monitor.stat_get("STAT_predictor_compiles")
    eng = serving.InferenceEngine(artifact, devices=4,
                                  batch_buckets=(1, 4, 16),
                                  max_batch_size=16, max_batch_delay_ms=2.0,
                                  max_queue_depth=1024, name="ml_stress")
    try:
        warm = monitor.stat_get("STAT_predictor_compiles") - c0
        assert warm == 4 * 3
        done = []
        lock = threading.Lock()

        def client(i):
            from collections import deque
            out = deque()
            for k in range(25):
                out.append(eng.submit(_x(1 + (i + k) % 3, seed=i)))
                if len(out) >= 2:
                    out.popleft().result(timeout=120)
            while out:
                out.popleft().result(timeout=120)
            with lock:
                done.append(i)

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert len(done) == 32
        s = eng.stats()
        assert sum(l["batches"] for l in s["lanes"]) >= 4
        assert sum(1 for l in s["lanes"] if l["batches"] > 0) >= 2
        # the compile ledger stays exact under stress: warmup only
        assert monitor.stat_get("STAT_predictor_compiles") - c0 == warm
        assert all(c == 1 for l in s["lanes"]
                   for c in l["bucket_compiles"].values())
    finally:
        eng.shutdown()


@pytest.mark.slow
def test_shutdown_submit_storm_cycles(artifact):
    for cycle in range(5):
        eng = serving.InferenceEngine(artifact, devices=2,
                                      batch_buckets=(1, 4),
                                      max_batch_size=4,
                                      max_batch_delay_ms=0.5,
                                      name=f"ml_storm{cycle}")
        futs, lock = [], threading.Lock()
        stop = threading.Event()

        def hammer(seed):
            while not stop.is_set():
                try:
                    f = eng.submit(_x(1, seed=seed), timeout_ms=0)
                except (UnavailableError, serving.EngineOverloaded):
                    return
                with lock:
                    futs.append(f)

        threads = [threading.Thread(target=hammer, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        eng.shutdown()
        stop.set()
        for t in threads:
            t.join(20)
            assert not t.is_alive()
        for f in futs:
            assert f.result(timeout=20)[0].shape == (1, 4)
