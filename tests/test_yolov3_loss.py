"""yolov3_loss (reference `operators/detection/yolov3_loss_op.cc`)."""
import math

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision.ops import yolo_box, yolov3_loss

ANCHORS = [10, 13, 16, 30, 33, 23]
MASK = [0, 1, 2]
CLS = 3
DS = 32


def _inputs(N=2, HW=4, seed=0):
    rng = np.random.RandomState(seed)
    C = len(MASK) * (5 + CLS)
    x = rng.randn(N, C, HW, HW).astype("float32") * 0.1
    gt = np.zeros((N, 2, 4), "float32")
    gt[:, 0] = [0.4, 0.6, 0.15, 0.2]
    lab = np.zeros((N, 2), "int64")
    lab[:, 0] = 1
    return x, gt, lab


def test_shape_positivity_grad():
    x, gt, lab = _inputs()
    t = paddle.to_tensor(x)
    t.stop_gradient = False
    loss = yolov3_loss(t, paddle.to_tensor(gt), paddle.to_tensor(lab),
                       ANCHORS, MASK, CLS, ignore_thresh=0.7,
                       downsample_ratio=DS)
    assert loss.shape == [2]
    assert (loss.numpy() > 0).all()
    loss.sum().backward()
    g = t.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_perfect_prediction_loss_near_zero():
    """Construct the head output whose decode equals the gt exactly
    (verified through yolo_box) — every loss term then approaches 0."""
    N, HW = 1, 4
    gt = np.zeros((N, 1, 4), "float32")
    cx, cy, w, h = 0.5625, 0.5625, 0.15, 0.2   # center INSIDE cell (2,2)
    gt[:, 0] = [cx, cy, w, h]
    lab = np.zeros((N, 1), "int64")
    in_sz = HW * DS

    # best anchor by w/h IoU
    gw, gh = w * in_sz, h * in_sz
    ious = []
    for a in range(3):
        aw, ah = ANCHORS[2 * a], ANCHORS[2 * a + 1]
        inter = min(gw, aw) * min(gh, ah)
        ious.append(inter / (gw * gh + aw * ah - inter))
    best = int(np.argmax(ious))
    gi, gj = int(cx * HW), int(cy * HW)

    big = 20.0
    xp = np.full((N, 3, 5 + CLS, HW, HW), -big, "float32")
    xp[:, :, 2:4] = 0.0
    tx, ty = cx * HW - gi, cy * HW - gj

    def logit(p):
        return math.log(p / (1 - p))
    xp[:, best, 0, gj, gi] = logit(tx)
    xp[:, best, 1, gj, gi] = logit(ty)
    aw, ah = ANCHORS[2 * best], ANCHORS[2 * best + 1]
    xp[:, best, 2, gj, gi] = math.log(gw / aw)
    xp[:, best, 3, gj, gi] = math.log(gh / ah)
    xp[:, best, 4, gj, gi] = big
    xp[:, best, 5 + 0, gj, gi] = big

    x = xp.reshape(N, -1, HW, HW)
    # decode cross-check: yolo_box recovers the gt box
    boxes, _ = yolo_box(paddle.to_tensor(x),
                        paddle.to_tensor(np.array([[in_sz, in_sz]],
                                                  "int32")),
                        ANCHORS, CLS, conf_thresh=0.0,
                        downsample_ratio=DS, clip_bbox=False)
    bb = boxes.numpy().reshape(-1, 4)
    flat = best * HW * HW + gj * HW + gi
    x1, y1, x2, y2 = bb[flat]
    np.testing.assert_allclose(
        [(x1 + x2) / 2 / in_sz, (y1 + y2) / 2 / in_sz,
         (x2 - x1) / in_sz, (y2 - y1) / in_sz],
        [cx, cy, w, h], rtol=1e-4, atol=1e-4)

    loss = yolov3_loss(paddle.to_tensor(x), paddle.to_tensor(gt),
                       paddle.to_tensor(lab), ANCHORS, MASK, CLS,
                       ignore_thresh=0.7, downsample_ratio=DS,
                       use_label_smooth=False)
    # BCE against the soft x/y offsets has an irreducible entropy floor
    # H(t) (same as the reference's sigmoid-CE formulation); everything
    # else (w/h L1, objectness, class, noobj) must be ~0
    def H(t):
        return -t * math.log(t) - (1 - t) * math.log(1 - t)
    floor = (H(tx) + H(ty)) * (2.0 - w * h)
    got = float(loss.numpy()[0])
    np.testing.assert_allclose(got, floor, rtol=1e-3, atol=0.05)


def test_ignore_thresh_suppresses_noobj():
    """A confident prediction overlapping the gt above ignore_thresh at
    a NON-assigned location must not be punished as noobj."""
    x, gt, lab = _inputs(N=1)
    base = yolov3_loss(paddle.to_tensor(x), paddle.to_tensor(gt),
                       paddle.to_tensor(lab), ANCHORS, MASK, CLS,
                       ignore_thresh=0.99, downsample_ratio=DS)
    relaxed = yolov3_loss(paddle.to_tensor(x), paddle.to_tensor(gt),
                          paddle.to_tensor(lab), ANCHORS, MASK, CLS,
                          ignore_thresh=0.0, downsample_ratio=DS)
    # thresh 0: every overlapping prediction is ignored -> less noobj
    assert float(relaxed.numpy()[0]) <= float(base.numpy()[0])


def test_training_reduces_loss():
    x, gt, lab = _inputs(N=1, HW=4, seed=3)
    t = paddle.to_tensor(x)
    t.stop_gradient = False
    gtt, labt = paddle.to_tensor(gt), paddle.to_tensor(lab)
    first = None
    cur = t
    for i in range(30):
        cur.stop_gradient = False
        loss = yolov3_loss(cur, gtt, labt, ANCHORS, MASK, CLS,
                           ignore_thresh=0.7, downsample_ratio=DS)
        s = loss.sum()
        if first is None:
            first = float(s.numpy())
        s.backward()
        cur = paddle.to_tensor(cur.numpy() - 0.05 * cur.grad.numpy())
    assert float(s.numpy()) < first * 0.7, (first, float(s.numpy()))
