"""Aux subsystems: profiler, NaN check, auto-checkpoint, PyLayer,
quantization, inference predictor, text datasets, incubate optimizers."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_profiler_records_and_exports(tmp_path):
    from paddle_tpu.profiler import (RecordEvent, export_chrome_tracing,
                                     start_profiler, stop_profiler)
    start_profiler()
    with RecordEvent("my_op"):
        paddle.ones([4]).sum()
    rows = stop_profiler()
    assert any(name == "my_op" for name, _ in rows)
    start_profiler()
    with RecordEvent("x"):
        pass
    p = str(tmp_path / "trace.json")
    export_chrome_tracing(p)
    assert os.path.exists(p)


def test_nan_check_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor([1.0, 0.0])
        with pytest.raises(FloatingPointError):
            paddle.log(x * 0 - 1.0)  # log(-1) = nan
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_pylayer_custom_grad():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Double.apply(x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_auto_checkpoint_resume(tmp_path):
    os.environ["PADDLE_CHECKPOINT_PATH"] = str(tmp_path)
    os.environ["PADDLE_JOB_ID"] = "job1"
    from paddle_tpu.incubate import TrainEpochRange
    net = nn.Linear(2, 2)
    r = TrainEpochRange(3, "t1").add(net)
    seen = []
    for e in r:
        seen.append(e)
        net.weight.set_value(np.full((2, 2), float(e), np.float32))
    assert seen == [0, 1, 2]
    # "restart": new range resumes past the end (no epochs to run)
    net2 = nn.Linear(2, 2)
    r2 = TrainEpochRange(3, "t1").add(net2)
    assert r2.get() == 3
    np.testing.assert_allclose(net2.weight.numpy(), 2.0)


def test_quantization_qat_forward_backward():
    from paddle_tpu.quantization import ImperativeQuantAware
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    ImperativeQuantAware().quantize(net)
    x = paddle.randn([4, 4])
    out = net(x)
    out.sum().backward()
    assert out.shape == [4, 2]
    # fake-quant must round to the int grid
    w = net[0].inner.weight
    assert w.grad is not None


def test_inference_predictor_roundtrip(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.static import InputSpec

    net = nn.Sequential(nn.Linear(4, 3), nn.Tanh())
    path = str(tmp_path / "m")
    paddle.jit.save(net, path, input_spec=[InputSpec([2, 4], "float32")])
    pred = create_predictor(Config(path))
    x = np.random.rand(2, 4).astype("float32")
    (out,) = pred.run([x])
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_text_datasets():
    from paddle_tpu.text import Imdb, UCIHousing, WMT14
    ds = Imdb(mode="train")
    doc, label = ds[0]
    assert doc.dtype == np.int64
    h = UCIHousing(mode="test")
    x, y = h[0]
    assert x.shape == (13,) and y.shape == (1,)
    mt = WMT14(mode="train")
    src, tin, tout = mt[0]
    assert len(tin) == len(tout)


def test_viterbi_decode():
    from paddle_tpu.text import viterbi_decode
    pot = paddle.to_tensor(np.random.RandomState(0).rand(2, 5, 3)
                           .astype("float32"))
    trans = paddle.to_tensor(np.random.RandomState(1).rand(3, 3)
                             .astype("float32"))
    score, path = viterbi_decode(pot, trans)
    assert path.shape == [2, 5]
    assert score.shape == [2]


def test_gradient_merge_optimizer():
    from paddle_tpu.incubate import GradientMergeOptimizer
    net = nn.Linear(2, 2)
    inner = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    gm = GradientMergeOptimizer(inner, k_steps=2)
    w0 = net.weight.numpy().copy()
    x = paddle.ones([1, 2])
    (net(x).sum()).backward()
    gm.step()
    np.testing.assert_allclose(net.weight.numpy(), w0)  # not applied yet
    (net(x).sum()).backward()
    gm.step()
    assert not np.allclose(net.weight.numpy(), w0)  # applied


def test_lookahead():
    from paddle_tpu.incubate import LookAhead
    net = nn.Linear(2, 2)
    inner = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    la = LookAhead(inner, alpha=0.5, k=2)
    x = paddle.ones([1, 2])
    for _ in range(4):
        (net(x).sum()).backward()
        la.step()
        la.clear_grad()
    assert np.isfinite(net.weight.numpy()).all()


def test_device_namespace():
    assert paddle.device.get_device() in ("cpu",) or ":" in \
        paddle.device.get_device()
    assert paddle.device.cuda.device_count() >= 1


def test_utils_run_check(capsys):
    paddle.utils.run_check()
    assert "successfully" in capsys.readouterr().out
