"""Round-5 op-gap closers (ops/extra_ops.py): numpy-reference parity and
finite-difference gradients (the OpTest pattern, reference
`unittests/op_test.py`)."""
import numpy as np
import pytest

import paddle_tpu as paddle

F = paddle.nn.functional
RNG = np.random.RandomState(7)


def _num_grad(fn, x, eps=1e-3):
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[i] += eps
        xm[i] -= eps
        g[i] = (fn(xp) - fn(xm)) / (2 * eps)
        it.iternext()
    return g


class TestLayoutOps:
    def test_pixel_unshuffle_roundtrip(self):
        x = RNG.rand(2, 3, 4, 6).astype("float32")
        down = F.pixel_unshuffle(paddle.to_tensor(x), 2)
        assert down.shape == [2, 12, 2, 3]
        up = F.pixel_shuffle(down, 2)
        np.testing.assert_allclose(up.numpy(), x, rtol=1e-6)

    def test_space_to_depth_alias(self):
        x = RNG.rand(1, 2, 4, 4).astype("float32")
        a = F.pixel_unshuffle(paddle.to_tensor(x), 2).numpy()
        b = paddle.space_to_depth(paddle.to_tensor(x), 2).numpy()
        np.testing.assert_array_equal(a, b)

    def test_channel_shuffle(self):
        x = np.arange(8, dtype="float32").reshape(1, 8, 1, 1)
        out = F.channel_shuffle(paddle.to_tensor(x), 4).numpy().ravel()
        np.testing.assert_array_equal(out, [0, 2, 4, 6, 1, 3, 5, 7])

    def test_temporal_shift_values(self):
        x = RNG.rand(4, 8, 2, 2).astype("float32")   # N=2 segments of T=2
        out = paddle.temporal_shift(paddle.to_tensor(x), seg_num=2,
                                    shift_ratio=0.25).numpy()
        v = x.reshape(2, 2, 8, 2, 2)
        o = out.reshape(2, 2, 8, 2, 2)
        # reference temporal_shift_op.h: [0,c1) reads t-1, [c1,c2) reads t+1
        np.testing.assert_allclose(o[:, 1, :2], v[:, 0, :2])    # from t-1
        np.testing.assert_allclose(o[:, 0, :2], 0.0)            # t-1 of t=0
        np.testing.assert_allclose(o[:, 0, 2:4], v[:, 1, 2:4])  # from t+1
        np.testing.assert_allclose(o[:, 1, 2:4], 0.0)           # t+1 of t=T-1
        np.testing.assert_allclose(o[:, :, 4:], v[:, :, 4:])    # rest

    def test_affine_grid_identity_matches_grid_sample(self):
        theta = np.tile(np.array([[1., 0, 0], [0, 1, 0]], "float32"),
                        (2, 1, 1))
        x = RNG.rand(2, 3, 5, 7).astype("float32")
        grid = F.affine_grid(paddle.to_tensor(theta), [2, 3, 5, 7])
        assert grid.shape == [2, 5, 7, 2]
        out = F.grid_sample(paddle.to_tensor(x), grid)
        np.testing.assert_allclose(out.numpy(), x, rtol=1e-4, atol=1e-5)

    def test_max_unpool2d_inverts_positions(self):
        x = RNG.rand(1, 2, 4, 4).astype("float32")
        pooled = F.max_pool2d(paddle.to_tensor(x), 2, 2)
        # indices: flat position of each max within the input plane
        flat = x.reshape(1, 2, 4, 4)
        idx = np.zeros((1, 2, 2, 2), "int32")
        for c in range(2):
            for i in range(2):
                for j in range(2):
                    win = flat[0, c, 2*i:2*i+2, 2*j:2*j+2]
                    r, s = np.unravel_index(np.argmax(win), (2, 2))
                    idx[0, c, i, j] = (2*i + r) * 4 + (2*j + s)
        up = F.max_unpool2d(pooled, paddle.to_tensor(idx), 2, 2)
        assert up.shape == [1, 2, 4, 4]
        # every pooled max lands back at its source position
        for c in range(2):
            for i in range(2):
                for j in range(2):
                    pos = idx[0, c, i, j]
                    assert up.numpy()[0, c, pos // 4, pos % 4] == \
                        pooled.numpy()[0, c, i, j]

    def test_roi_pool_small_roi_no_sentinels(self):
        x = paddle.to_tensor(RNG.rand(1, 2, 8, 8).astype("float32"))
        boxes = paddle.to_tensor(np.array([[1., 1., 3., 3.]], "float32"))
        num = paddle.to_tensor(np.array([1], "int32"))
        out = paddle.vision.ops.roi_pool(x, boxes, num, 7).numpy()
        assert out.shape == (1, 2, 7, 7)
        assert np.isfinite(out).all()
        assert out.min() >= 0.0          # empty bins are 0, not -3.4e38


class TestSegmentAndTree:
    def test_segment_reductions(self):
        d = np.array([[1., 2], [3, 4], [5, 6], [7, 8]], "float32")
        ids = np.array([0, 0, 1, 1])
        t, i = paddle.to_tensor(d), paddle.to_tensor(ids)
        np.testing.assert_allclose(
            paddle.incubate.segment_sum(t, i).numpy(), [[4, 6], [12, 14]])
        np.testing.assert_allclose(
            paddle.incubate.segment_mean(t, i).numpy(), [[2, 3], [6, 7]])
        np.testing.assert_allclose(
            paddle.incubate.segment_max(t, i).numpy(), [[3, 4], [7, 8]])
        np.testing.assert_allclose(
            paddle.incubate.segment_min(t, i).numpy(), [[1, 2], [5, 6]])

    def test_segment_static_requires_num(self):
        paddle.enable_static()
        try:
            from paddle_tpu import static
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                ids = static.data("ids", [4], "int32")
                d = static.data("d", [4, 2], "float32")
                with pytest.raises(ValueError):
                    paddle.incubate.segment_sum(d, ids)
                out = paddle.incubate.segment_sum(d, ids, num_segments=2)
            exe = static.Executor()
            got, = exe.run(main, feed={
                "ids": np.array([0, 1, 1, 0], "int32"),
                "d": np.ones((4, 2), "float32")}, fetch_list=[out])
            np.testing.assert_allclose(got, [[2, 2], [2, 2]])
        finally:
            paddle.disable_static()

    def test_gather_tree(self):
        ids = np.array([[[2, 2]], [[6, 1]], [[3, 9]]], "int64")
        parents = np.array([[[0, 0]], [[1, 1]], [[0, 1]]], "int64")
        out = F.gather_tree(paddle.to_tensor(ids),
                            paddle.to_tensor(parents)).numpy()
        # beam 0: t2 emits 3, parent chain 0 -> t1 emits ids[1,0]=6,
        # whose parent is 1 -> t0 emits ids[0,1]=2
        np.testing.assert_array_equal(out[:, 0, 0], [2, 6, 3])
        np.testing.assert_array_equal(out[:, 0, 1], [2, 1, 9])


class TestFluidOps:
    def test_affine_channel(self):
        x = RNG.rand(2, 3, 2, 2).astype("float32")
        s = np.array([1., 2, 3], "float32")
        b = np.array([.5, 0, -1], "float32")
        out = paddle.affine_channel(paddle.to_tensor(x),
                                    paddle.to_tensor(s),
                                    paddle.to_tensor(b)).numpy()
        ref = x * s.reshape(1, 3, 1, 1) + b.reshape(1, 3, 1, 1)
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_row_conv_matches_reference_formula(self):
        x = RNG.rand(2, 5, 3).astype("float32")
        w = RNG.rand(3, 3).astype("float32")   # context 3
        out = paddle.row_conv(paddle.to_tensor(x),
                              paddle.to_tensor(w)).numpy()
        ref = np.zeros_like(x)
        for t in range(5):
            for i in range(3):
                if t + i < 5:
                    ref[:, t] += x[:, t + i] * w[i]
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_conv_shift_circular(self):
        x = RNG.rand(2, 6).astype("float32")
        y = RNG.rand(2, 3).astype("float32")
        out = paddle.conv_shift(paddle.to_tensor(x),
                                paddle.to_tensor(y)).numpy()
        ref = np.zeros_like(x)
        for b in range(2):
            for i in range(6):
                for j in range(3):
                    ref[b, i] += x[b, (i + j - 1) % 6] * y[b, j]
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_cvm(self):
        x = RNG.rand(3, 6).astype("float32")
        c = np.abs(RNG.rand(3, 2)).astype("float32")
        keep = paddle.cvm(paddle.to_tensor(x), paddle.to_tensor(c),
                          use_cvm=True).numpy()
        np.testing.assert_allclose(keep[:, 2:], x[:, 2:], rtol=1e-6)
        # reference cvm_op.h: log-transform X's OWN show/click columns
        np.testing.assert_allclose(keep[:, 0], np.log(x[:, 0] + 1),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            keep[:, 1], np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1),
            rtol=1e-5, atol=1e-6)
        strip = paddle.cvm(paddle.to_tensor(x), paddle.to_tensor(c),
                           use_cvm=False).numpy()
        assert strip.shape == (3, 4)

    def test_data_norm(self):
        x = RNG.rand(4, 3).astype("float32")
        n = np.full((3,), 10.0, "float32")
        s = RNG.rand(3).astype("float32") * 10
        sq = s * s / 10 + 10.0           # variance 1-ish
        out = paddle.data_norm(paddle.to_tensor(x), paddle.to_tensor(n),
                               paddle.to_tensor(s),
                               paddle.to_tensor(sq)).numpy()
        mean = s / n
        scale = np.sqrt(n / np.maximum(sq - n * mean * mean, 1e-4))
        np.testing.assert_allclose(out, (x - mean) * scale, rtol=1e-4)

    def test_pad_constant_like_and_partials(self):
        x = paddle.to_tensor(np.zeros((3, 4), "float32"))
        y = paddle.to_tensor(np.ones((2, 3), "float32"))
        out = paddle.pad_constant_like(x, y, pad_value=5.0).numpy()
        assert out.shape == (3, 4)
        assert out[0, 0] == 1.0 and out[2, 3] == 5.0

        a = paddle.to_tensor(RNG.rand(2, 5).astype("float32"))
        b = paddle.to_tensor(RNG.rand(2, 5).astype("float32"))
        pc = paddle.partial_concat([a, b], start_index=1, length=2)
        assert pc.shape == [2, 4]
        ps = paddle.partial_sum([a, b], start_index=1, length=2)
        np.testing.assert_allclose(
            ps.numpy(), a.numpy()[:, 1:3] + b.numpy()[:, 1:3], rtol=1e-6)

    def test_norm_ops_with_grads(self):
        x = RNG.rand(3, 4).astype("float32")
        t = paddle.to_tensor(x)
        t.stop_gradient = False
        l1 = paddle.l1_norm(t)
        np.testing.assert_allclose(float(l1.numpy()), np.abs(x).sum(),
                                   rtol=1e-5)
        l1.backward()
        np.testing.assert_allclose(t.grad.numpy(), np.sign(x), rtol=1e-6)

        t2 = paddle.to_tensor(x)
        t2.stop_gradient = False
        sq = paddle.squared_l2_norm(t2)
        np.testing.assert_allclose(float(sq.numpy()), (x * x).sum(),
                                   rtol=1e-5)
        sq.backward()
        np.testing.assert_allclose(t2.grad.numpy(), 2 * x, rtol=1e-5)

    def test_im2sequence(self):
        x = RNG.rand(2, 3, 4, 4).astype("float32")
        out = paddle.im2sequence(paddle.to_tensor(x), filter_size=2,
                                 stride=2).numpy()
        assert out.shape == (2 * 2 * 2, 3 * 2 * 2)
        first = x[0, :, 0:2, 0:2].reshape(-1)
        np.testing.assert_allclose(out[0], first, rtol=1e-6)

    def test_shuffle_batch_is_permutation(self):
        x = np.arange(12, dtype="float32").reshape(6, 2)
        out = paddle.shuffle_batch(paddle.to_tensor(x), seed=3).numpy()
        assert sorted(out[:, 0].tolist()) == x[:, 0].tolist()


class TestRankingLosses:
    def test_rank_loss_formula(self):
        t = np.array([[1.0], [0.0]], "float32")
        left = np.array([[2.0], [0.5]], "float32")
        right = np.array([[1.0], [1.5]], "float32")
        out = paddle.rank_loss(paddle.to_tensor(t), paddle.to_tensor(left),
                               paddle.to_tensor(right)).numpy()
        o = left - right
        ref = np.logaddexp(0, o) - t * o
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_bpr_loss_positive_and_grad(self):
        logit = RNG.rand(4, 5).astype("float32")
        label = np.array([0, 2, 4, 1])
        t = paddle.to_tensor(logit)
        t.stop_gradient = False
        loss = paddle.bpr_loss(t, paddle.to_tensor(label))
        assert loss.shape == [4, 1]
        assert (loss.numpy() > 0).all()
        loss.sum().backward()
        g = t.grad.numpy()
        num = _num_grad(
            lambda lv: float(np.sum(-np.sum(
                np.log(1 / (1 + np.exp(-(lv[np.arange(4), label][:, None]
                                         - lv))))
                * (np.arange(5)[None] != label[:, None]), 1) / 4)), logit)
        np.testing.assert_allclose(g, num, rtol=2e-2, atol=2e-3)

    def test_center_loss(self):
        x = RNG.rand(4, 3).astype("float32")
        y = np.array([0, 1, 0, 1])
        centers = RNG.rand(2, 3).astype("float32")
        loss, new_c = paddle.center_loss(
            paddle.to_tensor(x), paddle.to_tensor(y),
            paddle.to_tensor(centers), alpha=0.5)
        diff = x - centers[y]
        np.testing.assert_allclose(
            loss.numpy(), 0.5 * (diff ** 2).sum(1, keepdims=True),
            rtol=1e-5)
        assert not np.allclose(new_c.numpy(), centers)

    def test_hinge_loss(self):
        logits = np.array([[0.5], [-2.0]], "float32")
        labels = np.array([[1.0], [0.0]], "float32")
        out = paddle.hinge_loss(paddle.to_tensor(logits),
                                paddle.to_tensor(labels)).numpy()
        np.testing.assert_allclose(out, [[0.5], [0.0]], rtol=1e-6)


class TestLinearChainCRF:
    def test_crf_nll_matches_brute_force(self):
        B, T, C = 2, 4, 3
        em = RNG.rand(B, T, C).astype("float32")
        tr = RNG.rand(C + 2, C).astype("float32")
        y = RNG.randint(0, C, (B, T)).astype("int64")
        ln = np.array([4, 3])
        nll = paddle.linear_chain_crf(
            paddle.to_tensor(em), paddle.to_tensor(tr),
            paddle.to_tensor(y), paddle.to_tensor(ln)).numpy()

        import itertools
        start, stop, trans = tr[0], tr[1], tr[2:]
        for b in range(B):
            L = ln[b]
            def score(seq):
                s = start[seq[0]] + em[b, 0, seq[0]]
                for t in range(1, L):
                    s += trans[seq[t - 1], seq[t]] + em[b, t, seq[t]]
                return s + stop[seq[L - 1]]
            logz = np.logaddexp.reduce(
                [score(s) for s in itertools.product(range(C), repeat=L)])
            ref = logz - score(y[b, :L])
            np.testing.assert_allclose(nll[b, 0], ref, rtol=1e-4)

    def test_crf_gradient_flows(self):
        em = paddle.to_tensor(RNG.rand(2, 3, 3).astype("float32"))
        em.stop_gradient = False
        tr = paddle.to_tensor(RNG.rand(5, 3).astype("float32"))
        tr.stop_gradient = False
        nll = paddle.linear_chain_crf(
            em, tr, paddle.to_tensor(np.zeros((2, 3), "int64")),
            paddle.to_tensor(np.array([3, 3])))
        nll.sum().backward()
        assert np.isfinite(em.grad.numpy()).all()
        assert np.isfinite(tr.grad.numpy()).all()
        assert float(np.abs(tr.grad.numpy()).sum()) > 0


class TestDetectionDistillOps:
    def test_fsp_matrix(self):
        x = RNG.rand(2, 3, 4, 4).astype("float32")
        y = RNG.rand(2, 5, 4, 4).astype("float32")
        out = paddle.fsp(paddle.to_tensor(x), paddle.to_tensor(y)).numpy()
        ref = np.einsum("bchw,bdhw->bcd", x, y) / 16
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_cross_entropy2(self):
        import paddle_tpu.nn.functional as F
        logits = RNG.rand(3, 6).astype("float32")
        prob = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
        y = np.array([1, 2, 3])
        out = paddle.cross_entropy2(paddle.to_tensor(prob),
                                    paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(out[:, 0],
                                   -np.log(prob[np.arange(3), y]),
                                   rtol=1e-5)

    def test_psroi_pool_groups(self):
        # constant feature per channel group -> each bin returns its
        # group's constant
        oc, oh, ow = 2, 2, 2
        feat = np.zeros((1, oc * oh * ow, 6, 6), "float32")
        for c in range(oc * oh * ow):
            feat[0, c] = c
        boxes = paddle.to_tensor(np.array([[0., 0., 5., 5.]], "float32"))
        bn = paddle.to_tensor(np.array([1], "int32"))
        out = paddle.psroi_pool(paddle.to_tensor(feat), boxes, bn,
                                oc, 1.0, oh, ow).numpy()
        for c in range(oc):
            for i in range(oh):
                for j in range(ow):
                    assert out[0, c, i, j] == c * oh * ow + i * ow + j

    def test_correlation_self_is_mean_square(self):
        x = RNG.rand(1, 4, 5, 5).astype("float32")
        out = paddle.correlation(paddle.to_tensor(x), paddle.to_tensor(x),
                                 pad_size=1, kernel_size=1,
                                 max_displacement=1).numpy()
        assert out.shape == (1, 9, 5, 5)
        center = out[0, 4]               # zero displacement plane
        np.testing.assert_allclose(center, (x[0] ** 2).mean(0), rtol=1e-5)

    def test_nce_positive_loss_and_grad(self):
        x = paddle.to_tensor(RNG.rand(4, 6).astype("float32"))
        x.stop_gradient = False
        loss = paddle.nce(x, paddle.to_tensor(np.array([0, 1, 2, 3])),
                          num_total_classes=9, num_neg_samples=4, seed=5)
        assert (loss.numpy() > 0).all()
        loss.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()

    def test_deformable_conv_zero_offset_equals_conv(self):
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(RNG.rand(2, 3, 6, 6).astype("float32"))
        off = paddle.to_tensor(np.zeros((2, 18, 6, 6), "float32"))
        w = paddle.to_tensor(RNG.rand(4, 3, 3, 3).astype("float32"))
        out = paddle.deformable_conv(x, off, w, padding=1)
        ref = F.conv2d(x, w, padding=1)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_deformable_conv_offset_shifts_sampling(self):
        # constant +1.0 x-offset == sampling the input shifted by one
        x = paddle.to_tensor(RNG.rand(1, 1, 6, 6).astype("float32"))
        off = np.zeros((1, 2, 6, 6), "float32")
        off[0, 1] = 1.0                  # dx = +1 for the 1x1 kernel
        w = paddle.to_tensor(np.ones((1, 1, 1, 1), "float32"))
        out = paddle.deformable_conv(x, paddle.to_tensor(off), w).numpy()
        ref = np.zeros_like(x.numpy())
        ref[0, 0, :, :-1] = x.numpy()[0, 0, :, 1:]
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class TestSequenceLoD:
    def _lt(self, arr, lod):
        import jax.numpy as jnp
        from paddle_tpu.ops.legacy import LoDTensor
        return LoDTensor(jnp.asarray(arr), [lod])

    def test_sequence_reshape(self):
        lt = self._lt(np.arange(12, dtype="float32").reshape(6, 2),
                      [0, 2, 6])
        out = paddle.sequence_reshape(lt, 4)
        assert np.asarray(out._value).shape == (3, 4)
        assert out.lod()[0] == [0, 1, 3]

    def test_sequence_slice(self):
        lt = self._lt(np.arange(12, dtype="float32").reshape(6, 2),
                      [0, 3, 6])
        out = paddle.sequence_slice(lt, np.array([1, 0]), np.array([2, 1]))
        v = np.asarray(out._value)
        np.testing.assert_allclose(v[0], [2, 3])     # row 1 of seq 0
        assert out.lod()[0] == [0, 2, 3]

    def test_sequence_scatter_and_lod_reset(self):
        base = paddle.to_tensor(np.zeros((2, 5), "float32"))
        idx = self._lt(np.array([1, 3, 0], "int64"), [0, 2, 3])
        upd = self._lt(np.array([10., 20., 30.], "float32"), [0, 2, 3])
        out = paddle.sequence_scatter(base, idx, upd).numpy()
        assert out[0, 1] == 10 and out[0, 3] == 20 and out[1, 0] == 30
        lt = paddle.lod_reset(paddle.to_tensor(
            np.zeros((4, 2), "float32")), target_lod=[0, 1, 4])
        assert lt.lod()[0] == [0, 1, 4]

    def test_sequence_scatter_accumulates_duplicates(self):
        base = paddle.to_tensor(np.zeros((1, 4), "float32"))
        idx = self._lt(np.array([0, 0], "int64"), [0, 2])
        upd = self._lt(np.array([1., 1.], "float32"), [0, 2])
        out = paddle.sequence_scatter(base, idx, upd).numpy()
        assert out[0, 0] == 2.0          # both updates land


class TestCtrOps:
    def test_batch_fc(self):
        x = RNG.rand(3, 4, 5).astype("float32")
        w = RNG.rand(3, 5, 2).astype("float32")
        b = RNG.rand(3, 2).astype("float32")
        out = paddle.batch_fc(paddle.to_tensor(x), paddle.to_tensor(w),
                              paddle.to_tensor(b)).numpy()
        ref = np.einsum("sbi,sio->sbo", x, w) + b[:, None]
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_sample_logits(self):
        lg = RNG.rand(4, 10).astype("float32")
        y = np.array([1, 3, 5, 7])
        samp, ids = paddle.sample_logits(paddle.to_tensor(lg),
                                         paddle.to_tensor(y), 5, seed=2)
        assert samp.shape == [4, 6] and ids.shape == [4, 6]
        np.testing.assert_array_equal(ids.numpy()[:, 0], y)
        np.testing.assert_allclose(samp.numpy()[:, 0],
                                   lg[np.arange(4), y], rtol=1e-6)
        taken = np.take_along_axis(lg, ids.numpy().astype(int), axis=1)
        np.testing.assert_allclose(samp.numpy(), taken, rtol=1e-6)

    def test_filter_by_instag(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.legacy import LoDTensor
        ins = paddle.to_tensor(np.arange(8, dtype="float32").reshape(4, 2))
        tags = LoDTensor(jnp.asarray(np.array([1, 2, 3, 2, 9, 4])),
                         [[0, 2, 3, 5, 6]])
        out, idx, lw = paddle.filter_by_instag(
            ins, tags, paddle.to_tensor(np.array([2])))
        np.testing.assert_array_equal(idx.numpy(), [0, 2])
        np.testing.assert_allclose(out.numpy(), ins.numpy()[[0, 2]])
        assert lw.shape == [2, 1]


class TestTreeAndVarConv:
    def test_var_conv_2d_shapes(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.legacy import LoDTensor
        r = np.array([4, 6])
        c = np.array([4, 2])
        total = 1 * 4 * 4 + 1 * 6 * 2
        lt = LoDTensor(jnp.asarray(RNG.rand(total).astype("float32")),
                       [[0, 16, 28]])
        w = paddle.to_tensor(RNG.rand(2, 1, 3, 3).astype("float32"))
        out = paddle.var_conv_2d(lt, paddle.to_tensor(r),
                                 paddle.to_tensor(c), 1, 2, 3, w=w)
        offs = out.lod()[0]
        assert offs == [0, 2 * 4 * 4, 2 * 4 * 4 + 2 * 6 * 2]

    def test_tree_conv_root_with_children(self):
        # 1 tree: node 0 with children 1, 2
        x = RNG.rand(1, 3, 4).astype("float32")
        edges = np.array([[[0, 1], [0, 2], [0, 0]]], "int64")  # pad (0,0)
        f = RNG.rand(4, 5, 3).astype("float32")
        out = paddle.tree_conv(paddle.to_tensor(x),
                               paddle.to_tensor(edges),
                               paddle.to_tensor(f))
        assert out.shape == [1, 3, 5]
        wt, wl, wr = f[..., 0], f[..., 1], f[..., 2]
        # node 0: top + child1 fully left + child2 fully right
        ref0 = np.tanh(x[0, 0] @ wt + x[0, 1] @ wl + x[0, 2] @ wr)
        np.testing.assert_allclose(out.numpy()[0, 0], ref0, rtol=1e-4)
        # leaf nodes: only the top term
        ref1 = np.tanh(x[0, 1] @ wt)
        np.testing.assert_allclose(out.numpy()[0, 1], ref1, rtol=1e-4)


class TestBilateralSlice:
    def test_constant_grid_is_affine(self):
        """A grid whose coefficients are constant everywhere reduces to
        one global affine transform — exact regardless of guide."""
        N, Ci, H, W = 1, 3, 6, 6
        Co = 3
        A = RNG.rand(Co, Ci + 1).astype("float32")
        grid = np.tile(A.reshape(1, Co * (Ci + 1), 1, 1, 1),
                       (N, 1, 2, 3, 3)).astype("float32")
        x = RNG.rand(N, Ci, H, W).astype("float32")
        guide = RNG.rand(N, H, W).astype("float32")
        out = paddle.bilateral_slice(
            paddle.to_tensor(x), paddle.to_tensor(guide),
            paddle.to_tensor(grid)).numpy()
        ref = np.einsum("oc,nchw->nohw", A[:, :Ci], x) + \
            A[:, Ci].reshape(1, Co, 1, 1)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_guide_selects_depth(self):
        """Two depth slabs with different biases: guide 0 picks slab 0,
        guide 1 picks slab 1 (input zeros, pure offset)."""
        N, Ci, H, W = 1, 1, 4, 4
        Co, Gd = 1, 2
        grid = np.zeros((N, Co * 2, Gd, 2, 2), "float32")
        grid[:, 1, 0] = 10.0           # offset channel, slab 0
        grid[:, 1, 1] = 20.0           # slab 1
        x = np.zeros((N, Ci, H, W), "float32")
        lo = paddle.bilateral_slice(
            paddle.to_tensor(x),
            paddle.to_tensor(np.zeros((N, H, W), "float32")),
            paddle.to_tensor(grid)).numpy()
        hi = paddle.bilateral_slice(
            paddle.to_tensor(x),
            paddle.to_tensor(np.ones((N, H, W), "float32")),
            paddle.to_tensor(grid)).numpy()
        assert abs(lo.mean() - 10.0) < 1e-4
        assert abs(hi.mean() - 20.0) < 1e-4

    def test_grad_flows(self):
        x = paddle.to_tensor(RNG.rand(1, 3, 4, 4).astype("float32"))
        g = paddle.to_tensor(RNG.rand(1, 3 * 4, 2, 2, 2).astype("float32"))
        x.stop_gradient = False
        g.stop_gradient = False
        out = paddle.bilateral_slice(
            x, paddle.to_tensor(RNG.rand(1, 4, 4).astype("float32")), g)
        out.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        assert np.abs(g.grad.numpy()).sum() > 0


class TestRankAttention:
    def test_matches_kernel_semantics(self):
        """Brute-force replay of the reference expand_input/expand_param
        CUDA kernels (rank_attention.cu.h)."""
        ins, D, pc, K = 4, 3, 2, 2
        x = RNG.rand(ins, D).astype("float32")
        p = RNG.rand(K * K * D, pc).astype("float32")
        off = np.zeros((ins, 2 * K + 1), "int64")
        off[0] = [1, 2, 1, 1, 2]     # rank 1; related (rank2,row1),(rank1,row2)
        off[1] = [2, 1, 0, 0, 0]     # rank 2; one related (rank1,row0)
        off[2] = [0, 1, 3, 0, 0]     # absent rank -> zero row
        off[3] = [1, 0, 0, 2, 3]     # k=0 absent, k=1 (rank2,row3)
        out = paddle.rank_attention(paddle.to_tensor(x),
                                    paddle.to_tensor(off),
                                    paddle.to_tensor(p),
                                    max_rank=K).numpy()

        ref = np.zeros((ins, pc), "float32")
        pb = p.reshape(K * K, D, pc)
        for i in range(ins):
            my = off[i, 0] - 1
            for k in range(K):
                rk = off[i, 2 * k + 1] - 1
                idx = off[i, 2 * k + 2]
                if my < 0 or rk < 0:
                    continue
                ref[i] += x[idx] @ pb[my * K + rk]
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_grad_flows_to_param(self):
        ins, D, pc, K = 3, 2, 2, 2
        x = paddle.to_tensor(RNG.rand(ins, D).astype("float32"))
        p = paddle.to_tensor(RNG.rand(K * K * D, pc).astype("float32"))
        off = paddle.to_tensor(np.array(
            [[1, 1, 0, 2, 1], [2, 1, 2, 0, 0], [1, 2, 1, 1, 0]], "int64"))
        x.stop_gradient = False
        p.stop_gradient = False
        out = paddle.rank_attention(x, off, p, max_rank=K)
        out.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        assert np.abs(p.grad.numpy()).sum() > 0

    def test_shape_validation(self):
        x = paddle.to_tensor(RNG.rand(2, 3).astype("float32"))
        off = paddle.to_tensor(np.zeros((2, 7), "int64"))  # max_rank 3
        p = paddle.to_tensor(RNG.rand(2 * 2 * 3, 2).astype("float32"))
        with pytest.raises(ValueError):
            paddle.rank_attention(x, off, p, max_rank=2)
        with pytest.raises(ValueError):
            paddle.rank_attention(
                x, paddle.to_tensor(np.zeros((2, 5), "int64")),
                paddle.to_tensor(RNG.rand(7, 2).astype("float32")),
                max_rank=2)


class TestPyramidHash:
    def _lod(self, arr, offs):
        import jax.numpy as jnp
        from paddle_tpu.ops.legacy import LoDTensor
        return LoDTensor(jnp.asarray(np.asarray(arr, "int32")), [offs])

    def test_xxh32_spec_vectors(self):
        from paddle_tpu.ops.legacy import _xxh32
        assert _xxh32(b"", 0) == 0x02CC5D05
        assert _xxh32(b"a", 0) == 0x550D7456
        assert _xxh32(b"abc", 0) == 0x32D153FF

    def test_row_counts_and_chunks(self):
        W = RNG.rand(100 + 4).astype("float32")
        seq = self._lod([3, 7, 9, 2, 5], [0, 5])
        out = paddle.search_pyramid_hash(
            seq, num_emb=8, space_len=100, pyramid_layer=3, rand_len=4,
            weights=W)
        # windows: len-2 -> 4 grams, len-3 -> 3 grams = 7 rows
        assert out.lod()[0] == [0, 7]
        o = np.asarray(out._value)
        assert o.shape == (7, 8)
        # every rand_len chunk is a contiguous slice of W
        flat = W
        for row in o:
            for j in range(0, 8, 4):
                chunk = row[j:j + 4]
                found = any(np.allclose(chunk, flat[s:s + 4])
                            for s in range(100))
                assert found

    def test_filters_and_dropout(self):
        from paddle_tpu.ops.legacy import _xxh32
        W = RNG.rand(50 + 2).astype("float32")
        seq = self._lod([1, 2, 3], [0, 3])
        # compute the hash key of the first bigram to whitelist only it
        gram = np.asarray([1, 2], np.float32).tobytes()
        key = _xxh32(gram, 0)
        out = paddle.search_pyramid_hash(
            seq, num_emb=4, space_len=50, pyramid_layer=2, rand_len=2,
            use_filter=True, white_list=[key], weights=W)
        assert out.lod()[0] == [0, 1]        # only the whitelisted gram
        out2 = paddle.search_pyramid_hash(
            seq, num_emb=4, space_len=50, pyramid_layer=2, rand_len=2,
            use_filter=True, black_list=[key], weights=W)
        assert out2.lod()[0] == [0, 1]       # the OTHER bigram survives
        out3 = paddle.search_pyramid_hash(
            seq, num_emb=4, space_len=50, pyramid_layer=2, rand_len=2,
            is_training=True, drop_out_percent=100, weights=W)
        assert out3.lod()[0] == [0, 0]       # percent scale: 100 = drop all

    def test_weights_are_trainable(self):
        W = paddle.to_tensor(RNG.rand(50 + 2).astype("float32"))
        W.stop_gradient = False
        seq = self._lod([4, 5, 6], [0, 3])
        out = paddle.search_pyramid_hash(
            seq, num_emb=4, space_len=50, pyramid_layer=2, rand_len=2,
            weights=W)
        out.sum().backward()
        g = W.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0
