"""LocalSGD / adaptive LocalSGD / DGC strategy tests on the 8-device CPU
mesh (reference `test_fleet_localsgd_meta_optimizer.py`,
`test_dgc_optimizer.py` — rebased onto loss-parity + state checks)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.parallel import (create_mesh, dgc_compress, dgc_init,
                                 local_write_back, make_local_train_step,
                                 make_sharded_train_step, mesh_scope,
                                 set_mesh)


@pytest.fixture(autouse=True)
def _clean_mesh():
    yield
    set_mesh(None)


def _toy():
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype("float32")
    w = rng.randn(8, 1).astype("float32")
    y = (x @ w).astype("float32")
    return x, y


def _build(lr=0.1):
    paddle.seed(11)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = paddle.optimizer.SGD(lr, parameters=net.parameters())
    return net, opt


def _mse(outs, labels):
    out = outs[0] if isinstance(outs, (list, tuple)) else outs
    d = out - labels[0]
    return (d * d).mean()


def test_dgc_compress_topk_and_error_feedback():
    g = {"w": jnp.asarray([0.1, -2.0, 0.3, 5.0])}
    st = dgc_init(g)
    out, st2 = dgc_compress(g, st, momentum=0.0, sparsity=0.5)
    # top-2 of |v|=|g| are 5.0 and -2.0; rest stay in the error buffer
    np.testing.assert_allclose(np.asarray(out["w"]), [0, -2.0, 0, 5.0])
    np.testing.assert_allclose(np.asarray(st2["w"]["v"]), [0.1, 0, 0.3, 0])
    # next step the residual re-enters the accumulated velocity
    out2, _ = dgc_compress({"w": jnp.zeros(4)}, st2, momentum=0.0,
                           sparsity=0.5)
    np.testing.assert_allclose(np.asarray(out2["w"]), [0.1, 0, 0.3, 0])


def test_dgc_spmd_step_converges():
    x, y = _toy()
    net, opt = _build()
    with mesh_scope(create_mesh({"dp": 8})):
        step, state = make_sharded_train_step(net, opt, _mse, dgc=True,
                                              dgc_sparsity=0.75)
        assert "dgc" in state
        losses = []
        for _ in range(30):
            state, lv = step(state, (x,), (y,), rng=jax.random.PRNGKey(0))
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5


def test_localsgd_k1_matches_sync_dp():
    """k_steps=1 LocalSGD with SGD == fully synchronous DP (averaging
    after a linear update commutes with averaging the gradient)."""
    x, y = _toy()
    ref_losses = []
    net, opt = _build()
    with mesh_scope(create_mesh({"dp": 8})):
        step, state = make_sharded_train_step(net, opt, _mse)
        for _ in range(4):
            state, lv = step(state, (x,), (y,), rng=jax.random.PRNGKey(0))
            ref_losses.append(float(lv))
    set_mesh(None)

    net2, opt2 = _build()
    local_losses = []
    with mesh_scope(create_mesh({"dp": 8})):
        step2, state2 = make_local_train_step(net2, opt2, _mse, k_steps=1,
                                              begin_step=0)
        for _ in range(4):
            state2, lv = step2(state2, (x,), (y,),
                               rng=jax.random.PRNGKey(0))
            local_losses.append(float(lv))
    np.testing.assert_allclose(local_losses, ref_losses, rtol=2e-4)


def test_localsgd_k4_converges_and_syncs():
    x, y = _toy()
    net, opt = _build()
    with mesh_scope(create_mesh({"dp": 8})):
        step, state = make_local_train_step(net, opt, _mse, k_steps=4)
        losses = []
        for _ in range(24):
            state, lv = step(state, (x,), (y,), rng=jax.random.PRNGKey(0))
            losses.append(float(lv))
        assert losses[-1] < losses[0] * 0.5
        # at a sync boundary every replica holds identical params
        p0 = jax.tree_util.tree_leaves(state["params"])[0]
        blocks = np.asarray(p0)
        for i in range(1, blocks.shape[0]):
            np.testing.assert_allclose(blocks[i], blocks[0], rtol=1e-5)
        local_write_back(net, state)


def test_adaptive_localsgd_adjusts_k():
    x, y = _toy()
    net, opt = _build()
    with mesh_scope(create_mesh({"dp": 8})):
        step, state = make_local_train_step(net, opt, _mse, k_steps=2,
                                            adaptive=True)
        for _ in range(12):
            state, _ = step(state, (x,), (y,), rng=jax.random.PRNGKey(0))
        k = int(state["k"])
        assert 1 <= k <= 16
        assert float(state["loss0"]) > 0.0


def test_fleet_strategy_localsgd_and_dgc_paths():
    import paddle_tpu.distributed.fleet as fleet
    x, y = _toy()
    with mesh_scope(create_mesh({"dp": 8})):
        strat = fleet.DistributedStrategy()
        strat.localsgd = True
        strat.localsgd_configs = {"k_steps": 2, "begin_step": 1}
        fleet.init(is_collective=True, strategy=strat)
        net, opt = _build()
        step, state = fleet.fleet.build_sharded_train_step(net, opt, _mse)
        state, lv = step(state, (x,), (y,), rng=jax.random.PRNGKey(0))
        assert np.isfinite(float(lv))

        strat2 = fleet.DistributedStrategy()
        strat2.dgc = True
        fleet.init(is_collective=True, strategy=strat2)
        net2, opt2 = _build()
        step2, state2 = fleet.fleet.build_sharded_train_step(net2, opt2,
                                                             _mse)
        assert "dgc" in state2
        state2, lv2 = step2(state2, (x,), (y,), rng=jax.random.PRNGKey(0))
        assert np.isfinite(float(lv2))
