"""Functional higher-order autodiff (reference
`python/paddle/autograd/functional.py` vjp/jvp/jacobian/hessian) vs
numpy closed forms."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.autograd import hessian, jacobian, jvp, vjp


def _x(shape=(3,), seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).standard_normal(shape).astype(
            "float32"))


def test_vjp_matches_manual():
    x = _x()
    out, g = vjp(lambda t: (t * t).sum(), x)
    np.testing.assert_allclose(float(out.numpy()),
                               (x.numpy() ** 2).sum(), rtol=1e-5)
    np.testing.assert_allclose(g.numpy(), 2 * x.numpy(), rtol=1e-5)


def test_jvp_matches_directional_derivative():
    x = _x(seed=1)
    v = _x(seed=2)
    out, tang = jvp(lambda t: paddle.sin(t), x, v)
    np.testing.assert_allclose(tang.numpy(),
                               np.cos(x.numpy()) * v.numpy(), rtol=1e-5)


def test_jacobian_of_vector_fn():
    x = _x((4,), seed=3)
    jac = jacobian(lambda t: paddle.tanh(t), x)
    expect = np.diag(1.0 - np.tanh(x.numpy()) ** 2)
    np.testing.assert_allclose(jac.numpy(), expect, rtol=1e-4, atol=1e-6)


def test_hessian_of_quadratic():
    a = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
    x = _x((2,), seed=4)
    at = paddle.to_tensor(a)
    h = hessian(lambda t: 0.5 * (t @ (at @ t)), x)
    np.testing.assert_allclose(h.numpy(), a, rtol=1e-4, atol=1e-5)


def test_multi_input_jacobian():
    x, y = _x((3,), 5), _x((3,), 6)
    jx, jy = jacobian(lambda a, b: a * b, [x, y])
    np.testing.assert_allclose(jx.numpy(), np.diag(y.numpy()), rtol=1e-5)
    np.testing.assert_allclose(jy.numpy(), np.diag(x.numpy()), rtol=1e-5)


def test_vjp_list_output_with_explicit_v():
    x = _x(seed=7)
    out, g = vjp(lambda t: [t * t], x, v=[paddle.ones([3])])
    np.testing.assert_allclose(g.numpy(), 2 * x.numpy(), rtol=1e-5)


def test_hessian_rejects_non_scalar():
    import pytest
    x = _x((3,), seed=8)
    with pytest.raises(ValueError, match="single scalar"):
        hessian(lambda t: t * t, x)


def test_create_graph_raises_clearly():
    import pytest
    x = _x(seed=9)
    with pytest.raises(NotImplementedError, match="create_graph"):
        jacobian(lambda t: t, x, create_graph=True)


def test_distributed_launch_module_alias():
    import importlib
    m = importlib.import_module("paddle_tpu.distributed.launch")
    assert callable(m.launch)
