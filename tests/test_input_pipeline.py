"""Input-pipeline perf semantics (ISSUE 4): sharding-aware DeviceFeeder,
device-resident sharded carry, tail-batch bucketing, and the DataLoader
shared-memory slot ring.

CPU-checkable contracts for the perf work: feeder leaves land in the
requested NamedSharding and the sharded step does zero re-placement,
the padded tail's masked loss isolates the real rows bitwise (within one
compiled shape — cross-shape bit-identity is not an XLA guarantee),
drop_last=False costs exactly one train-step compile per epoch, the shm
ring maps a fixed number of segments no matter how long the epoch runs,
and the fleet fit loop writes the carry back once per epoch, not once
per step.
"""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.monitor import stat_get, stat_reset
from paddle_tpu.io import DataLoader, Dataset, DeviceFeeder, IterableDataset, \
    TensorDataset
from paddle_tpu.parallel import batch_placement, create_mesh, \
    make_sharded_train_step, mesh_scope, set_mesh


def _toy(n=128, dim=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim).astype("float32") * 3
    y = rng.randint(0, classes, n)
    x = (centers[y] + rng.randn(n, dim)).astype("float32")
    return x, y.astype("int64")


def _toy_model(dim=8, classes=3, lr=0.01, seed=0, loss=None):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(dim, 16), nn.ReLU(),
                        nn.Linear(16, classes))
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(lr, parameters=net.parameters()),
                  loss if loss is not None else nn.CrossEntropyLoss())
    # pin the single-process path; earlier tests may have left fleet/mesh
    # globals initialized
    model._dist_ctx = None
    return model, net


@pytest.fixture
def tail_flag():
    prev = paddle.get_flags(["FLAGS_train_tail_bucketing"])
    yield
    paddle.set_flags(prev)


@pytest.fixture
def clean_mesh():
    yield
    set_mesh(None)


# ---------------------------------------------------------------------------
# sharding-aware DeviceFeeder
# ---------------------------------------------------------------------------

def test_feeder_places_leaves_with_requested_sharding(clean_mesh):
    mesh = create_mesh({"dp": 8})
    place = batch_placement(mesh)
    batches = [[np.ones((16, 4), "float32") * i,
                np.arange(16, dtype="int64")] for i in range(3)]
    out = list(DeviceFeeder(batches, device=place))
    assert len(out) == 3
    want2d = NamedSharding(mesh, P("dp", None))
    want1d = NamedSharding(mesh, P("dp"))
    for i, (xb, yb) in enumerate(out):
        assert xb._value.sharding == want2d
        assert yb._value.sharding == want1d
        np.testing.assert_array_equal(np.asarray(xb._value),
                                      batches[i][0])


def test_sharded_step_consumes_preplaced_batches_without_reput(clean_mesh):
    """A feeder-placed batch must ride into the pjit step as-is: zero
    device_put re-placements (STAT_sharded_batch_puts stays flat), and
    the loss matches the host-array path exactly."""
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype("float32")
    y = rng.randint(0, 4, 16).astype("int64")

    def loss_fn(outs, labels):
        out = outs[0] if isinstance(outs, (list, tuple)) else outs
        return nn.CrossEntropyLoss()(out, labels[0])

    with mesh_scope(create_mesh({"dp": 8})) as mesh:
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = paddle.optimizer.Momentum(0.1, parameters=net.parameters())
        step, state = make_sharded_train_step(net, opt, loss_fn)

        # host-array path: the step itself places inputs + labels
        stat_reset("STAT_sharded_batch_puts")
        state, lv_host = step(state, (x,), (y,), rng=jax.random.PRNGKey(0))
        assert stat_get("STAT_sharded_batch_puts") == 2

        # feeder-placed path: committed NamedShardings on this mesh
        (xb, yb), = list(DeviceFeeder([[x, y]],
                                      device=batch_placement(mesh)))
        stat_reset("STAT_sharded_batch_puts")
        state, lv_fed = step(state, (xb._value,), (yb._value,),
                             rng=jax.random.PRNGKey(0))
        assert stat_get("STAT_sharded_batch_puts") == 0
        assert np.isfinite(float(lv_fed))


def test_feeder_len_delegates_and_raises_for_generators():
    x, y = _toy(32)
    dl = DataLoader(TensorDataset([x, y]), batch_size=8)
    assert len(DeviceFeeder(dl)) == 4

    def gen():
        yield [x[:8], y[:8]]

    with pytest.raises(TypeError):
        len(DeviceFeeder(gen()))


def test_fit_over_generator_and_iterable_dataset():
    """Countless mode: fit must run over loaders with no __len__."""
    x, y = _toy(40)
    model, _ = _toy_model()

    def gen():
        for i in range(5):
            yield [x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]]

    stat_reset("STAT_train_steps")
    model.fit(gen(), epochs=1, verbose=0)
    assert stat_get("STAT_train_steps") == 5

    class Stream(IterableDataset):
        def __iter__(self):
            for i in range(20):
                yield x[i], y[i]

    loader = DataLoader(Stream(), batch_size=8)  # len() raises TypeError
    model2, net2 = _toy_model(seed=1)
    stat_reset("STAT_train_steps")
    model2.fit(loader, epochs=1, verbose=0)
    assert stat_get("STAT_train_steps") == 3  # 8 + 8 + tail 4
    assert np.isfinite(net2[0].weight.numpy()).all()


def test_feeder_overlap_counts_only_real_batches():
    stat_reset("STAT_device_feeder_overlap")
    stat_reset("STAT_device_feeder_batches")
    assert list(DeviceFeeder([])) == []
    assert stat_get("STAT_device_feeder_overlap") == 0
    assert stat_get("STAT_device_feeder_batches") == 0

    def boom():
        raise RuntimeError("dead source")
        yield  # pragma: no cover

    with pytest.raises(RuntimeError, match="dead source"):
        list(DeviceFeeder(boom()))
    # the forwarded exception raced into the queue but is not a batch
    assert stat_get("STAT_device_feeder_overlap") == 0
    assert stat_get("STAT_device_feeder_batches") == 0


# ---------------------------------------------------------------------------
# tail-batch bucketing
# ---------------------------------------------------------------------------

def test_masked_tail_matches_unpadded_and_isolates_real_rows(tail_flag):
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    x, y = _toy(8)
    nreal, full = 5, 8
    mask = np.zeros((full,), "float32")
    mask[:nreal] = 1.0

    # (a) value parity: masked padded loss == unpadded loss on the real
    # rows (different compiled shapes -> allclose, not bitwise; the
    # within-one-shape caveat is pinned by (b))
    model_u, _ = _toy_model(seed=3)
    lv_u, _ = model_u.train_batch([x[:nreal]], [y[:nreal]])
    model_p, net_p = _toy_model(seed=3)
    xp = np.concatenate([x[:nreal], np.repeat(x[nreal - 1:nreal], 3, 0)])
    yp = np.concatenate([y[:nreal], np.repeat(y[nreal - 1:nreal], 3)])
    lv_p, _ = model_p.train_batch([xp], [yp], loss_mask=mask)
    np.testing.assert_allclose(float(lv_u[0]), float(lv_p[0]),
                               rtol=1e-6, atol=1e-7)

    # (b) bitwise within one compiled shape: what rides the pad rows is
    # irrelevant — loss AND the updated weights are bit-identical
    model_q, net_q = _toy_model(seed=3)
    xq = np.concatenate([x[:nreal], np.repeat(x[:1], 3, 0) * 7.5])
    yq = np.concatenate([y[:nreal], np.repeat(y[:1], 3)])
    lv_q, _ = model_q.train_batch([xq], [yq], loss_mask=mask)
    assert float(lv_p[0]) == float(lv_q[0])
    np.testing.assert_array_equal(net_p[0].weight.numpy(),
                                  net_q[0].weight.numpy())


def test_fit_drop_last_false_compiles_once_per_epoch(tail_flag):
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    x, y = _toy(70)  # bs 16 -> 4 full batches + a 6-row tail
    model, net = _toy_model()
    stat_reset("STAT_train_step_compiles")
    stat_reset("STAT_tail_pad_batches")
    stat_reset("STAT_tail_pad_compiles_avoided")
    model.fit(TensorDataset([x, y]), batch_size=16, epochs=2, verbose=0,
              shuffle=False, drop_last=False)
    assert stat_get("STAT_train_step_compiles") == 1
    assert stat_get("STAT_tail_pad_batches") == 2  # one tail per epoch
    assert stat_get("STAT_tail_pad_compiles_avoided") == 2
    assert np.isfinite(net[0].weight.numpy()).all()

    # flag off restores the old two-compiles behavior
    paddle.set_flags({"FLAGS_train_tail_bucketing": False})
    model2, _ = _toy_model(seed=2)
    stat_reset("STAT_train_step_compiles")
    model2.fit(TensorDataset([x, y]), batch_size=16, epochs=1, verbose=0,
               shuffle=False, drop_last=False)
    assert stat_get("STAT_train_step_compiles") == 2


def test_tail_bucketing_training_matches_unpadded(tail_flag):
    """End-to-end numerics: a fit over a tailed dataset converges to the
    same weights whether the tail is padded+masked or compiled unpadded."""
    x, y = _toy(40)  # bs 16 -> 2 full + 8-row tail

    def run(flag_on, seed=11):
        paddle.set_flags({"FLAGS_train_tail_bucketing": flag_on})
        model, net = _toy_model(seed=seed)
        model.fit(TensorDataset([x, y]), batch_size=16, epochs=3,
                  verbose=0, shuffle=False, drop_last=False)
        return net[0].weight.numpy().copy()

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5,
                               atol=1e-6)


def test_tail_mask_fallback_for_scalar_loss(tail_flag):
    """A loss that only yields a scalar cannot fold the row mask: the
    model warns once, reruns the real rows unpadded, and keeps training
    (one extra compile for the tail shape — the old behavior)."""
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    import paddle_tpu.nn.functional as F
    x, _ = _toy(24)
    t = np.tanh(x[:, :3]).astype("float32")

    def scalar_loss(out, label):
        return F.mse_loss(out, label)  # reduction='mean' baked in: scalar

    model, net = _toy_model(loss=scalar_loss)
    stat_reset("STAT_train_step_compiles")
    with pytest.warns(UserWarning, match="per-row"):
        model.fit(TensorDataset([x, t]), batch_size=16, epochs=1,
                  verbose=0, shuffle=False, drop_last=False)
    assert model._tail_maskable is False
    assert stat_get("STAT_train_step_compiles") == 2  # full + tail shapes
    assert np.isfinite(net[0].weight.numpy()).all()


def test_hole_mask_fallback_trains_on_exactly_the_real_rows(tail_flag):
    """loss_mask is public and may have holes: the scalar-loss fallback
    must rerun the rows the mask selects, not the first popcount rows."""
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    import paddle_tpu.nn.functional as F
    x, _ = _toy(8)
    t = np.tanh(x[:, :3]).astype("float32")

    def scalar_loss(out, label):
        return F.mse_loss(out, label)

    mask = np.array([1, 0, 1, 1, 0, 1, 0, 0], "float32")
    sel = np.flatnonzero(mask)
    with pytest.warns(UserWarning, match="per-row"):
        m_a, net_a = _toy_model(seed=7, loss=scalar_loss)
        m_a.train_batch([x], [t], loss_mask=mask)
    m_b, net_b = _toy_model(seed=7, loss=scalar_loss)
    m_b.train_batch([x[sel]], [t[sel]])
    np.testing.assert_array_equal(net_a[0].weight.numpy(),
                                  net_b[0].weight.numpy())


def test_predict_still_pads_after_mask_fallback(tail_flag):
    """predict has no loss, so a loss that refused the row mask must not
    cost predict its tail padding (one executable, rows sliced off)."""
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    import paddle_tpu.nn.functional as F
    x, _ = _toy(20)
    t = np.tanh(x[:, :3]).astype("float32")
    model, _ = _toy_model(loss=lambda o, l: F.mse_loss(o, l))
    with pytest.warns(UserWarning, match="per-row"):
        model.fit(TensorDataset([x, t]), batch_size=16, epochs=1,
                  verbose=0, shuffle=False)
    assert model._tail_maskable is False
    out = model.predict(TensorDataset([x]), batch_size=8,
                        stack_outputs=True, verbose=0)
    assert out.shape[0] == 20
    assert len(model._pred_step_cache) == 1


def test_eval_and_predict_share_the_padded_shape(tail_flag):
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    x, y = _toy(20)
    model, _ = _toy_model()
    logs = model.evaluate(TensorDataset([x, y]), batch_size=8, verbose=0)
    assert np.isfinite(logs["loss"])
    assert len(model._eval_step_cache) == 1  # tail reused the 8-row entry

    out = model.predict(TensorDataset([x]), batch_size=8,
                        stack_outputs=True, verbose=0)
    assert out.shape[0] == 20  # padded rows never reach the caller
    assert len(model._pred_step_cache) == 1


def test_eval_masked_loss_matches_unpadded(tail_flag):
    x, y = _toy(20)
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    model, _ = _toy_model(seed=5)
    padded = model.evaluate(TensorDataset([x, y]), batch_size=8,
                            verbose=0)["loss"]
    paddle.set_flags({"FLAGS_train_tail_bucketing": False})
    model2, _ = _toy_model(seed=5)
    plain = model2.evaluate(TensorDataset([x, y]), batch_size=8,
                            verbose=0)["loss"]
    np.testing.assert_allclose(padded, plain, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# shared-memory slot ring
# ---------------------------------------------------------------------------

class _ArrayDataset(Dataset):
    def __init__(self, n=256, dim=16):
        rng = np.random.RandomState(3)
        self.x = rng.standard_normal((n, dim)).astype(np.float32)
        self.y = rng.randint(0, 10, size=(n,)).astype(np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def test_shm_ring_segment_count_constant_across_long_epoch():
    ds = _ArrayDataset(n=256)
    loader = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                        prefetch_factor=2)  # ring of 4 slots, 64 batches
    stat_reset("STAT_shm_slot_segments")
    stat_reset("STAT_shm_slots_reused")
    seen = 0
    for xb, yb in loader:
        seen += 1
        assert xb.numpy().shape == (4, 16)
    assert seen == 64
    segments = stat_get("STAT_shm_slot_segments")
    reused = stat_get("STAT_shm_slots_reused")
    # parent maps at most one segment per ring slot; every other batch is
    # served from an already-mapped slot with ZERO shm syscalls
    assert 1 <= segments <= 4
    assert reused == seen - segments

    # parity with the single-process path (data is bitwise intact
    # through slot reuse)
    single = list(DataLoader(ds, batch_size=4, num_workers=0,
                             shuffle=False))
    multi = list(DataLoader(ds, batch_size=4, num_workers=2,
                            shuffle=False))
    for (xs, ys), (xm, ym) in zip(single, multi):
        np.testing.assert_array_equal(xs.numpy(), xm.numpy())
        np.testing.assert_array_equal(ys.numpy(), ym.numpy())


def test_shm_ring_regrows_slots_for_bigger_batches():
    class Ragged(Dataset):
        def __len__(self):
            return 24

        def __getitem__(self, i):
            # later samples are larger: slots must regrow, data stays right
            return np.full((8 * (1 + i // 8),), i, dtype=np.int64)

    loader = DataLoader(Ragged(), batch_size=4, num_workers=2,
                        shuffle=False,
                        collate_fn=lambda b: np.concatenate(b))
    out = list(loader)
    assert len(out) == 6
    for j, t in enumerate(out):
        arr = t.numpy()
        want = np.concatenate([np.full((8 * (1 + i // 8),), i, np.int64)
                               for i in range(j * 4, j * 4 + 4)])
        np.testing.assert_array_equal(arr, want)


# ---------------------------------------------------------------------------
# device-resident sharded carry
# ---------------------------------------------------------------------------

def _fleet_model(x_dim=8, classes=4, lr=0.01, seed=3):
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.sharding = True
    strategy.hybrid_configs = {"dp_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(x_dim, 16), nn.ReLU(),
                        nn.Linear(16, classes))
    model = paddle.Model(net)
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Adam(lr, parameters=net.parameters()))
    model.prepare(opt, nn.CrossEntropyLoss())
    assert model._dist_ctx is not None
    return model, net


def test_sharded_fit_syncs_carry_once_per_epoch(clean_mesh, tail_flag):
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    rng = np.random.RandomState(3)
    x = rng.randn(72, 8).astype("float32")  # bs 16 -> 4 full + 8-row tail
    y = rng.randint(0, 4, 72).astype("int64")
    model, net = _fleet_model()
    w0 = net[0].weight.numpy().copy()
    stat_reset("STAT_sharded_carry_syncs")
    stat_reset("STAT_train_steps")
    model.fit(TensorDataset([x, y]), batch_size=16, epochs=2, verbose=0,
              shuffle=False, drop_last=False)
    assert stat_get("STAT_train_steps") == 10  # 5 batches x 2 epochs
    # ONE write_back per epoch — not one per step
    assert stat_get("STAT_sharded_carry_syncs") == 2
    assert model._sharded_dirty is False
    w1 = net[0].weight.numpy()
    assert np.isfinite(w1).all()
    assert not np.allclose(w0, w1)


def test_sharded_standalone_train_batch_writes_back(clean_mesh):
    rng = np.random.RandomState(5)
    x = rng.randn(16, 8).astype("float32")
    y = rng.randint(0, 4, 16).astype("int64")
    model, net = _fleet_model(seed=5)
    stat_reset("STAT_sharded_carry_syncs")
    model.train_batch([x], [y])
    # outside fit the public contract holds: Tensors are fresh per call
    assert stat_get("STAT_sharded_carry_syncs") == 1
    assert model._sharded_dirty is False
    out = net(paddle.to_tensor(x[:4]))
    assert np.isfinite(out.numpy()).all()


def test_sharded_fit_with_dp_indivisible_tail(clean_mesh, tail_flag):
    """The buffered feeder must not crash placing a raw tail batch whose
    rows don't divide dp (jax.device_put hard-fails on uneven shards):
    batch_placement leaves such leaves unplaced, fit pads them to the
    full (divisible) batch, and the step lays them out."""
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    rng = np.random.RandomState(11)
    x = rng.randn(68, 8).astype("float32")  # bs 16 -> 4 full + 4-row tail
    y = rng.randint(0, 4, 68).astype("int64")
    model, net = _fleet_model(seed=11)
    loader = DataLoader(TensorDataset([x, y]), batch_size=16,
                        shuffle=False)  # buffered feeder engaged
    model.fit(loader, epochs=1, verbose=0)
    assert np.isfinite(net[0].weight.numpy()).all()
    out = model.predict(TensorDataset([x]), batch_size=16,
                        stack_outputs=True, verbose=0)
    assert out.shape[0] == 68


def test_no_tail_dataset_keeps_the_maskless_step(tail_flag):
    """Datasets whose epochs cannot produce a partial batch must keep
    the exact pre-bucketing step (no mask in the signature): the masked
    reduction is only paid where a tail can actually occur."""
    paddle.set_flags({"FLAGS_train_tail_bucketing": True})
    x, y = _toy(64)  # bs 16 -> 4 full batches, no tail possible
    model, _ = _toy_model()
    stat_reset("STAT_tail_pad_batches")
    model.fit(TensorDataset([x, y]), batch_size=16, epochs=1, verbose=0,
              shuffle=False)
    assert stat_get("STAT_tail_pad_batches") == 0
    # the compiled step's signature carried no mask
    ((_, _, _, mask_sig),) = list(model._train_step_cache.keys())
    assert mask_sig is None


def test_sharded_fit_with_buffered_feeder_skips_step_puts(clean_mesh):
    """The fit loop's DeviceFeeder carries the fleet batch placement, so
    the steady-state sharded step does zero input re-placements (the
    once-per-epoch padded tail and its mask are the only puts)."""
    rng = np.random.RandomState(9)
    x = rng.randn(64, 8).astype("float32")  # bs 16 -> 4 full batches
    y = rng.randint(0, 4, 64).astype("int64")
    model, net = _fleet_model(seed=9)
    loader = DataLoader(TensorDataset([x, y]), batch_size=16,
                        shuffle=False)  # use_buffer_reader defaults on
    stat_reset("STAT_sharded_batch_puts")
    model.fit(loader, epochs=1, verbose=0)
    assert stat_get("STAT_sharded_batch_puts") == 0
    assert np.isfinite(net[0].weight.numpy()).all()
